"""nn stack tests: functional parity vs numpy/torch, layer round-trips
(SURVEY.md §4 test_nn_*)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip('torch')
import torch.nn.functional as TF  # noqa: E402


def t2n(x):
    return x.numpy()


def assert_close(a, b, tol=1e-5):
    np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


class TestFunctionalParity:
    def _cmp(self, ours, theirs, x, tol=1e-5, **kw):
        a = ours(paddle.to_tensor(x), **kw).numpy()
        b = theirs(torch.tensor(x), **kw).numpy()
        assert_close(a, b, tol)

    @pytest.mark.slow

    def test_activations(self):
        x = np.random.randn(4, 7).astype(np.float32)
        for ours, theirs in [
            (F.relu, TF.relu), (F.relu6, TF.relu6), (F.silu, TF.silu),
            (F.sigmoid, torch.sigmoid), (F.tanh, torch.tanh),
            (F.elu, TF.elu), (F.selu, TF.selu), (F.celu, TF.celu),
            (F.hardswish, TF.hardswish), (F.hardsigmoid, TF.hardsigmoid),
            (F.mish, TF.mish), (F.softplus, TF.softplus),
            (F.softsign, TF.softsign), (F.leaky_relu, TF.leaky_relu),
            (F.hardshrink, TF.hardshrink), (F.softshrink, TF.softshrink),
            (F.tanhshrink, TF.tanhshrink), (F.logsigmoid, TF.logsigmoid),
        ]:
            self._cmp(ours, theirs, x)

    def test_gelu(self):
        x = np.random.randn(4, 7).astype(np.float32)
        assert_close(F.gelu(paddle.to_tensor(x)).numpy(),
                     TF.gelu(torch.tensor(x)).numpy(), 1e-5)
        assert_close(F.gelu(paddle.to_tensor(x), approximate=True).numpy(),
                     TF.gelu(torch.tensor(x), approximate='tanh').numpy(),
                     1e-5)

    def test_softmax_family(self):
        x = np.random.randn(3, 5).astype(np.float32)
        assert_close(F.softmax(paddle.to_tensor(x)).numpy(),
                     TF.softmax(torch.tensor(x), dim=-1).numpy())
        assert_close(F.log_softmax(paddle.to_tensor(x)).numpy(),
                     TF.log_softmax(torch.tensor(x), dim=-1).numpy())

    def test_linear(self):
        x = np.random.randn(2, 4).astype(np.float32)
        w = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(3).astype(np.float32)
        ours = F.linear(paddle.to_tensor(x), paddle.to_tensor(w),
                        paddle.to_tensor(b)).numpy()
        assert_close(ours, x @ w + b)

    def test_embedding_padding_idx(self):
        w = np.random.randn(5, 3).astype(np.float32)
        ids = np.array([[0, 1], [4, 1]])
        out = F.embedding(paddle.to_tensor(ids), paddle.to_tensor(w),
                          padding_idx=1).numpy()
        assert_close(out[0, 0], w[0])
        assert np.all(out[0, 1] == 0)
        assert np.all(out[1, 1] == 0)

    def test_layer_norm(self):
        x = np.random.randn(2, 3, 8).astype(np.float32)
        w = np.random.rand(8).astype(np.float32)
        b = np.random.randn(8).astype(np.float32)
        ours = F.layer_norm(paddle.to_tensor(x), 8, paddle.to_tensor(w),
                            paddle.to_tensor(b)).numpy()
        theirs = TF.layer_norm(torch.tensor(x), (8,), torch.tensor(w),
                               torch.tensor(b)).numpy()
        assert_close(ours, theirs, 1e-4)

    def test_rms_norm(self):
        x = np.random.randn(2, 8).astype(np.float32)
        out = F.rms_norm(paddle.to_tensor(x)).numpy()
        expect = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        assert_close(out, expect, 1e-5)

    def test_group_norm(self):
        x = np.random.randn(2, 6, 4, 4).astype(np.float32)
        w = np.random.rand(6).astype(np.float32)
        b = np.random.randn(6).astype(np.float32)
        ours = F.group_norm(paddle.to_tensor(x), 3, paddle.to_tensor(w),
                            paddle.to_tensor(b)).numpy()
        theirs = TF.group_norm(torch.tensor(x), 3, torch.tensor(w),
                               torch.tensor(b)).numpy()
        assert_close(ours, theirs, 1e-4)

    def test_batch_norm_train_and_eval(self):
        x = np.random.randn(4, 3, 5, 5).astype(np.float32)
        bn = nn.BatchNorm2D(3, momentum=0.9)
        tbn = torch.nn.BatchNorm2d(3, momentum=0.1)  # torch momentum is 1-m
        with torch.no_grad():
            tbn.weight.copy_(torch.tensor(bn.weight.numpy()))
            tbn.bias.copy_(torch.tensor(bn.bias.numpy()))
        out = bn(paddle.to_tensor(x)).numpy()
        tout = tbn(torch.tensor(x)).detach().numpy()
        assert_close(out, tout, 1e-4)
        assert_close(bn._mean.numpy(), tbn.running_mean.numpy(), 1e-4)
        assert_close(bn._variance.numpy(), tbn.running_var.numpy(), 1e-4)
        bn.eval(); tbn.eval()
        assert_close(bn(paddle.to_tensor(x)).numpy(),
                     tbn(torch.tensor(x)).detach().numpy(), 1e-4)

    def test_conv2d(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        w = np.random.randn(5, 3, 3, 3).astype(np.float32) * 0.1
        b = np.random.randn(5).astype(np.float32)
        ours = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        paddle.to_tensor(b), stride=2, padding=1).numpy()
        theirs = TF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                           stride=2, padding=1).numpy()
        assert_close(ours, theirs, 1e-4)

    def test_conv2d_groups_dilation(self):
        x = np.random.randn(1, 4, 9, 9).astype(np.float32)
        w = np.random.randn(8, 2, 3, 3).astype(np.float32) * 0.1
        ours = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        groups=2, dilation=2).numpy()
        theirs = TF.conv2d(torch.tensor(x), torch.tensor(w), groups=2,
                           dilation=2).numpy()
        assert_close(ours, theirs, 1e-4)

    def test_conv2d_transpose(self):
        x = np.random.randn(1, 4, 5, 5).astype(np.float32)
        w = np.random.randn(4, 3, 3, 3).astype(np.float32) * 0.1
        ours = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                                  stride=2, padding=1,
                                  output_padding=1).numpy()
        theirs = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                     stride=2, padding=1,
                                     output_padding=1).numpy()
        assert_close(ours, theirs, 1e-4)

    def test_pools(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        assert_close(
            F.max_pool2d(paddle.to_tensor(x), 2).numpy(),
            TF.max_pool2d(torch.tensor(x), 2).numpy())
        assert_close(
            F.avg_pool2d(paddle.to_tensor(x), 2).numpy(),
            TF.avg_pool2d(torch.tensor(x), 2).numpy())
        assert_close(
            F.adaptive_avg_pool2d(paddle.to_tensor(x), 3).numpy(),
            TF.adaptive_avg_pool2d(torch.tensor(x), 3).numpy(), 1e-4)
        assert_close(
            F.adaptive_max_pool2d(paddle.to_tensor(x), 3).numpy(),
            TF.adaptive_max_pool2d(torch.tensor(x), 3).numpy(), 1e-4)

    def test_interpolate(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        assert_close(
            F.interpolate(paddle.to_tensor(x), scale_factor=2).numpy(),
            TF.interpolate(torch.tensor(x), scale_factor=2).numpy())
        assert_close(
            F.interpolate(paddle.to_tensor(x), size=7, mode='bilinear',
                          align_corners=True).numpy(),
            TF.interpolate(torch.tensor(x), size=7, mode='bilinear',
                           align_corners=True).numpy(), 1e-4)

    def test_pad_modes(self):
        x = np.random.randn(1, 2, 3, 3).astype(np.float32)
        for mode, tmode in [('constant', 'constant'), ('reflect', 'reflect'),
                            ('replicate', 'replicate')]:
            assert_close(
                F.pad(paddle.to_tensor(x), [1, 2, 1, 0], mode=mode).numpy(),
                TF.pad(torch.tensor(x), (1, 2, 1, 0), mode=tmode).numpy())

    def test_cross_entropy(self):
        logits = np.random.randn(6, 5).astype(np.float32)
        labels = np.array([0, 4, 2, 1, 3, 2])
        assert_close(
            F.cross_entropy(paddle.to_tensor(logits),
                            paddle.to_tensor(labels)).numpy(),
            TF.cross_entropy(torch.tensor(logits),
                             torch.tensor(labels)).numpy(), 1e-5)
        # ignore_index + weight
        labels2 = np.array([0, -100, 2, 1, -100, 2])
        w = np.random.rand(5).astype(np.float32) + 0.5
        assert_close(
            F.cross_entropy(paddle.to_tensor(logits),
                            paddle.to_tensor(labels2),
                            weight=paddle.to_tensor(w)).numpy(),
            TF.cross_entropy(torch.tensor(logits), torch.tensor(labels2),
                             weight=torch.tensor(w)).numpy(), 1e-5)
        # label smoothing
        assert_close(
            F.cross_entropy(paddle.to_tensor(logits),
                            paddle.to_tensor(labels),
                            label_smoothing=0.1).numpy(),
            TF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                             label_smoothing=0.1).numpy(), 1e-5)

    def test_bce(self):
        p = np.random.rand(8).astype(np.float32) * 0.9 + 0.05
        y = (np.random.rand(8) > 0.5).astype(np.float32)
        assert_close(
            F.binary_cross_entropy(paddle.to_tensor(p),
                                   paddle.to_tensor(y)).numpy(),
            TF.binary_cross_entropy(torch.tensor(p), torch.tensor(y)).numpy(),
            1e-5)
        z = np.random.randn(8).astype(np.float32)
        assert_close(
            F.binary_cross_entropy_with_logits(
                paddle.to_tensor(z), paddle.to_tensor(y)).numpy(),
            TF.binary_cross_entropy_with_logits(
                torch.tensor(z), torch.tensor(y)).numpy(), 1e-5)

    def test_misc_losses(self):
        a = np.random.randn(7).astype(np.float32)
        b = np.random.randn(7).astype(np.float32)
        assert_close(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
                     TF.mse_loss(torch.tensor(a), torch.tensor(b)).numpy())
        assert_close(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
                     TF.l1_loss(torch.tensor(a), torch.tensor(b)).numpy())
        assert_close(
            F.smooth_l1_loss(paddle.to_tensor(a), paddle.to_tensor(b)).numpy(),
            TF.smooth_l1_loss(torch.tensor(a), torch.tensor(b)).numpy(), 1e-5)
        logp = TF.log_softmax(torch.tensor(a), dim=-1).numpy()
        q = TF.softmax(torch.tensor(b), dim=-1).numpy()
        assert_close(
            F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(q)).numpy(),
            TF.kl_div(torch.tensor(logp), torch.tensor(q)).numpy(), 1e-5)

    def test_sdpa_vs_torch(self):
        q = np.random.randn(2, 6, 4, 8).astype(np.float32)
        k = np.random.randn(2, 6, 4, 8).astype(np.float32)
        v = np.random.randn(2, 6, 4, 8).astype(np.float32)
        ours = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            is_causal=True).numpy()
        # torch layout is [b, h, s, d]
        tq, tk, tv = (torch.tensor(x.transpose(0, 2, 1, 3)) for x in (q, k, v))
        theirs = TF.scaled_dot_product_attention(
            tq, tk, tv, is_causal=True).numpy().transpose(0, 2, 1, 3)
        assert_close(ours, theirs, 1e-4)

    def test_sequence_mask_onehot(self):
        m = F.sequence_mask(paddle.to_tensor(np.array([1, 3])), maxlen=4)
        np.testing.assert_array_equal(
            m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0]])


class TestLayers:
    @pytest.mark.slow
    def test_grad_flow_through_block(self):
        blk = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        x = paddle.randn([2, 5, 16])
        x.stop_gradient = False
        out = blk(x)
        out.mean().backward()
        for n, p in blk.named_parameters():
            assert p.grad is not None, n

    def test_state_dict_roundtrip_values(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = m.state_dict()
        assert set(sd) == {'0.weight', '0.bias', '2.weight', '2.bias'}
        m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m2.set_state_dict({k: v.numpy() for k, v in sd.items()})
        x = paddle.randn([3, 4])
        assert_close(m(x).numpy(), m2(x).numpy())

    def test_named_parameters_and_buffers(self):
        bn = nn.BatchNorm2D(4)
        names = dict(bn.named_parameters())
        assert 'weight' in names and 'bias' in names
        bufs = dict(bn.named_buffers())
        assert '_mean' in bufs and '_variance' in bufs
        sd = bn.state_dict()
        assert '_mean' in sd  # buffers persist in state_dict

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert all(not l.training for l in m.sublayers())
        m.train()
        assert all(l.training for l in m.sublayers())

    def test_hooks(self):
        lin = nn.Linear(3, 3)
        calls = []
        h = lin.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        lin(paddle.randn([1, 3]))
        assert calls == [1]
        h.remove()
        lin(paddle.randn([1, 3]))
        assert calls == [1]

    @pytest.mark.slow
    def test_mha_cache_decode(self):
        mha = nn.MultiHeadAttention(16, 4)
        mha.eval()
        x = paddle.randn([1, 4, 16])
        full = mha(x, x, x,
                   attn_mask=None)
        # incremental: feed tokens one by one with cache, causal equivalence
        cache = mha.gen_cache(paddle.randn([1, 0, 16]))
        outs = []
        for i in range(4):
            step = x[:, i:i + 1, :]
            o, cache = mha(step, step, step, cache=cache)
            outs.append(o.numpy())
        # last token attends to all previous: equals causal full attention row
        full_causal = F.scaled_dot_product_attention(
            mha._split(mha.q_proj(x)), mha._split(mha.k_proj(x)),
            mha._split(mha.v_proj(x)), is_causal=True)
        import jax.numpy as jnp
        merged = full_causal.numpy().reshape(1, 4, 16)
        expect = mha.out_proj(paddle.to_tensor(merged)).numpy()
        got = np.concatenate(outs, axis=1)
        assert_close(got, expect, 1e-4)

    def test_initializers_stats(self):
        paddle.seed(3)
        w = nn.initializer.KaimingNormal()((256, 128))
        std = float(np.std(np.asarray(w)))
        assert abs(std - np.sqrt(2.0 / 256)) < 0.01
        q = nn.initializer.Orthogonal()((64, 64))
        qq = np.asarray(q)
        assert_close(qq @ qq.T, np.eye(64), 1e-4)

    def test_clip_global_norm(self):
        clip = nn.ClipGradByGlobalNorm(1.0)
        g1 = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
        pg = clip([(None, g1)])
        _, g = pg[0]
        assert_close(np.linalg.norm(g.numpy()), 1.0, 1e-5)
