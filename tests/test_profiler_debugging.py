"""Round-5 profiler scheduler API and paddle.amp.debugging (upstream
python/paddle/profiler/, python/paddle/amp/debugging.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle

P = paddle.profiler


class TestScheduler:
    def test_make_scheduler_states(self):
        sched = P.make_scheduler(closed=2, ready=1, record=3, repeat=1,
                                 skip_first=1)
        states = [sched(i) for i in range(10)]
        assert states == [P.ProfilerState.CLOSED] * 3 + [
            P.ProfilerState.READY, P.ProfilerState.RECORD,
            P.ProfilerState.RECORD, P.ProfilerState.RECORD_AND_RETURN,
        ] + [P.ProfilerState.CLOSED] * 3

    def test_repeat_forever(self):
        sched = P.make_scheduler(closed=1, ready=0, record=1)
        assert sched(0) == P.ProfilerState.CLOSED
        assert sched(1) == P.ProfilerState.RECORD_AND_RETURN
        assert sched(100) == P.ProfilerState.CLOSED
        assert sched(101) == P.ProfilerState.RECORD_AND_RETURN

    def test_bad_cycle_rejected(self):
        with pytest.raises(ValueError):
            P.make_scheduler(closed=0, ready=0, record=0)

    def test_windowed_profiler_fires_handler(self, tmp_path):
        sched = P.make_scheduler(closed=2, ready=1, record=3, repeat=1,
                                 skip_first=1)
        handler = P.export_chrome_tracing(str(tmp_path))
        fired = []
        prof = P.Profiler(scheduler=sched,
                          on_trace_ready=lambda p: fired.append(
                              handler(p)))
        prof.start()
        for i in range(10):
            with P.RecordEvent('work'):
                sum(range(100))
            prof.step()
        prof.stop()
        assert len(fired) == 1
        res = P.load_profiler_result(fired[0])
        assert 'work' in [e['name'] for e in res['traceEvents']]


class TestSchedulerEdgeCases:
    def test_record_first_cycle_fires(self, tmp_path):
        # schedule whose cycle STARTS with record: the 0-based step
        # indexing must still consult index 0
        fired = []
        prof = P.Profiler(
            scheduler=P.make_scheduler(closed=0, ready=0, record=1,
                                       repeat=1),
            on_trace_ready=lambda p: fired.append(1))
        prof.start()
        for i in range(5):
            prof.step()
        prof.stop()
        assert len(fired) == 1

    def test_tuple_scheduler_single_window(self):
        fired = []
        prof = P.Profiler(scheduler=(2, 4),
                          on_trace_ready=lambda p: fired.append(1))
        prof.start()
        for i in range(20):
            prof.step()
        prof.stop()
        assert len(fired) == 1  # upstream: ONE [2, 4) window
        with pytest.raises(ValueError):
            P.Profiler(scheduler=(5, 3))

    def test_windows_export_per_window_data(self, tmp_path):
        # repeating schedule: each window must contain only its own data
        handler = P.export_chrome_tracing(str(tmp_path))
        outs = []
        prof = P.Profiler(
            scheduler=P.make_scheduler(closed=2, ready=0, record=1),
            on_trace_ready=lambda p: outs.append(handler(p)))
        prof.start()
        for i in range(6):
            with P.RecordEvent('tick'):
                pass
            prof.step()
        prof.stop()
        assert len(outs) == 2
        for path in outs:
            ev = [e for e in P.load_profiler_result(path)['traceEvents']
                  if e['name'] == 'tick']
            assert ev and ev[0]['args']['calls'] <= 2  # not cumulative


class TestAmpDebugging:
    def test_double_enable_is_safe(self):
        D = paddle.amp.debugging
        D.enable_operator_stats_collection()
        D.enable_operator_stats_collection()  # notebook cell re-run
        paddle.ones([2]) + 1.0
        assert D.collect_operator_numerical_stats()['add']['calls'] == 1
        D.disable_operator_stats_collection()
        # hook fully removed: later ops run clean
        out = paddle.ones([2]) + 1.0
        assert D.collect_operator_numerical_stats() == {}
        np.testing.assert_allclose(out.numpy(), 2.0)

    def test_operator_stats_collection(self, capsys):
        D = paddle.amp.debugging
        D.enable_operator_stats_collection()
        x = paddle.randn([4, 4]).astype('bfloat16')
        paddle.matmul(x, x)
        stats = D.collect_operator_numerical_stats()
        D.disable_operator_stats_collection()
        assert stats['matmul']['calls'] == 1
        assert stats['matmul']['dtypes'] == {'bfloat16': 1}
        assert 'matmul' in capsys.readouterr().out
        # collection really stopped
        paddle.matmul(x, x)
        assert D.collect_operator_numerical_stats() == {}

    def test_tensor_checker_aborts_on_nan(self):
        D = paddle.amp.debugging
        D.enable_tensor_checker()
        try:
            with pytest.raises(Exception, match='[Nn]a[Nn]'):
                paddle.to_tensor(
                    np.array([1.0, np.nan], np.float32)) * 2.0
        finally:
            D.disable_tensor_checker()
        # off again: nan flows without raising
        out = paddle.to_tensor(np.array([np.nan], np.float32)) * 2.0
        assert np.isnan(out.numpy()).all()

    def test_check_numerics_one_shot(self):
        D = paddle.amp.debugging
        good = paddle.ones([3])
        D.check_numerics(good, 'good_op')
        bad = paddle.to_tensor(np.array([np.inf], np.float32))
        with pytest.raises(Exception):
            D.check_numerics(bad, 'bad_op')
        # non-abort mode: returns instead of raising
        D.check_numerics(bad, 'bad_op',
                         debug_mode=D.DebugMode.CHECK_NAN_INF)

    def test_stats_chain_with_checker(self):
        # enabling stats while the nan checker is on must keep BOTH
        D = paddle.amp.debugging
        D.enable_tensor_checker()
        D.enable_operator_stats_collection()
        try:
            paddle.ones([2]) + 1.0
            stats = D.collect_operator_numerical_stats()
            assert stats['add']['calls'] == 1
            with pytest.raises(Exception):
                paddle.to_tensor(np.array([np.nan], np.float32)) * 1.0
        finally:
            D.disable_operator_stats_collection()
            D.disable_tensor_checker()
