"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4).

Must set env before jax initializes its backends, hence module-level.
"""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

# The image preloads a TPU-tunnel plugin that rewrites jax_platforms at
# startup; override it back to cpu before the backend initializes.
jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.seed(42)
    np.random.seed(42)
    yield
