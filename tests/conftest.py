"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4).

Must set env before jax initializes its backends, hence module-level.
"""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

# Donation posture is pinned OFF for tier-1 determinism: the installed
# jaxlib (0.4.36) is the known intermittently-corrupting runtime, so an
# 'auto' probe's verdict — and therefore every donated/undonated code
# path downstream — would be nondeterministic across runs. The donation
# tests (tests/test_donation.py) opt back in per-test via set_flags /
# PADDLE_DONATION_PROBE_MODE. (setdefault: an operator exporting the
# flag explicitly still wins.)
os.environ.setdefault('FLAGS_donation', 'off')

import jax  # noqa: E402

# The image preloads a TPU-tunnel plugin that rewrites jax_platforms at
# startup; override it back to cpu before the backend initializes.
jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.seed(42)
    np.random.seed(42)
    yield


@pytest.fixture(autouse=True)
def _reset_span_state():
    """Zero this thread's span nesting depth around every test.

    The PR-11 ordering flake: a test that begin()s a Span and never
    end()s it (e.g. a serving queue span on a request the test abandons
    mid-flight) leaks `_span_state.depth` in the main thread, so a
    later test asserting absolute depths (test_span_nesting_records_
    depth_and_order) fails when test_serving happens to run first.
    Span state is per-test scaffolding, not cross-test truth — reset it
    on both sides."""
    from paddle_tpu.observability import events as _events
    _events._span_state.depth = 0
    yield
    _events._span_state.depth = 0


@pytest.fixture
def sanitizer_strict():
    """Run the test under the runtime concurrency sanitizer in STRICT
    mode (ISSUE 15): any lock-order cycle, non-reentrant re-entry, or
    guarded-field lockset race raises ConcurrencySanitizerError at the
    offending acquire/access — and even if a violation is swallowed by
    a failover/retry path mid-test, the teardown assertion on the
    violation counter still fails the test. The chaos gauntlets
    (router failover storm, autoscaler thundering herd, hotswap
    kill-mid-swap, donation sentinel trips) all opt in."""
    from paddle_tpu import observability as obs
    from paddle_tpu.analysis import runtime as _rt

    reg = obs.get_registry()

    def _total():
        fam = reg.get('paddle_sanitizer_violations_total')
        return fam.total() if fam is not None else 0.0

    before = _total()
    n_before = len(_rt.violations())
    _rt.enable('strict')
    try:
        yield _rt
    finally:
        _rt.disable()
    new = _rt.violations()[n_before:]
    assert _total() == before and not new, (
        'concurrency sanitizer reported violations during the '
        f'gauntlet: {new}')


@pytest.fixture
def fleet_mesh():
    """Factory for a hybrid fleet mesh over the forced 8-device CPU
    platform: `fleet_mesh(dp=..., mp=..., pp=..., sp=...)` runs
    fleet.init with those degrees and returns the strategy. Tears the
    whole parallel env (mesh, HCG, resize history) down afterwards so
    mesh-shaped tests stay independent — the elastic suite re-meshes
    mid-test and must not leak a shrunken world into the next test."""
    from paddle_tpu.distributed import env, fleet

    def make(dp=1, mp=1, pp=1, sp=1, sharding=False, stage=1):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {'dp_degree': dp, 'mp_degree': mp,
                                   'pp_degree': pp, 'sep_degree': sp}
        if sharding:
            strategy.sharding = True
            strategy.sharding_configs['stage'] = stage
        fleet.init(is_collective=True, strategy=strategy)
        return strategy

    yield make
    env.destroy_process_group()
    fleet._fleet.initialized = False
    fleet._fleet.strategy = None
    fleet._fleet._hcg = None
    fleet._resize_history.clear()
