"""paddle_tpu BERT vs HuggingFace torch BERT on copied weights:
post-LN encoder, gelu, learned positions + token types, tanh pooler."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp import BertConfig, BertModel

torch = pytest.importorskip('torch')
hf = pytest.importorskip('transformers')

from hf_parity_utils import make_put


def _make_pair(seed=0):
    paddle.seed(seed)
    cfg = BertConfig(vocab_size=120, hidden_size=48, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=96,
                     max_position_embeddings=64, type_vocab_size=2,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    model = BertModel(cfg).eval()
    hc = hf.BertConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        intermediate_size=cfg.intermediate_size,
        max_position_embeddings=cfg.max_position_embeddings,
        type_vocab_size=cfg.type_vocab_size, hidden_act='gelu',
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=cfg.layer_norm_eps, pad_token_id=cfg.pad_token_id)
    tm = hf.BertModel(hc).eval()
    sd = {k: np.asarray(v.numpy()) for k, v in model.state_dict().items()}
    put = make_put(sd, torch)

    e = tm.embeddings
    put(e.word_embeddings.weight, 'embeddings.word_embeddings.weight',
        transpose=False)
    put(e.position_embeddings.weight,
        'embeddings.position_embeddings.weight', transpose=False)
    put(e.token_type_embeddings.weight,
        'embeddings.token_type_embeddings.weight', transpose=False)
    put(e.LayerNorm.weight, 'embeddings.layer_norm.weight', transpose=False)
    put(e.LayerNorm.bias, 'embeddings.layer_norm.bias', transpose=False)
    for i, blk in enumerate(tm.encoder.layer):
        p = f'encoder.layers.{i}.'
        put(blk.attention.self.query.weight, p + 'self_attn.q_proj.weight')
        put(blk.attention.self.query.bias, p + 'self_attn.q_proj.bias',
            transpose=False)
        put(blk.attention.self.key.weight, p + 'self_attn.k_proj.weight')
        put(blk.attention.self.key.bias, p + 'self_attn.k_proj.bias',
            transpose=False)
        put(blk.attention.self.value.weight, p + 'self_attn.v_proj.weight')
        put(blk.attention.self.value.bias, p + 'self_attn.v_proj.bias',
            transpose=False)
        put(blk.attention.output.dense.weight, p + 'self_attn.out_proj.weight')
        put(blk.attention.output.dense.bias, p + 'self_attn.out_proj.bias',
            transpose=False)
        put(blk.attention.output.LayerNorm.weight, p + 'norm1.weight',
            transpose=False)
        put(blk.attention.output.LayerNorm.bias, p + 'norm1.bias',
            transpose=False)
        put(blk.intermediate.dense.weight, p + 'linear1.weight')
        put(blk.intermediate.dense.bias, p + 'linear1.bias',
            transpose=False)
        put(blk.output.dense.weight, p + 'linear2.weight')
        put(blk.output.dense.bias, p + 'linear2.bias', transpose=False)
        put(blk.output.LayerNorm.weight, p + 'norm2.weight',
            transpose=False)
        put(blk.output.LayerNorm.bias, p + 'norm2.bias', transpose=False)
    put(tm.pooler.dense.weight, 'pooler.dense.weight')
    put(tm.pooler.dense.bias, 'pooler.dense.bias', transpose=False)
    return cfg, model, tm


class TestBertHFParity:
    @pytest.mark.slow
    def test_sequence_output_and_pooler_match_hf(self):
        cfg, model, tm = _make_pair(seed=0)
        rng = np.random.RandomState(0)
        ids = rng.randint(3, cfg.vocab_size, (2, 10))
        tok = rng.randint(0, 2, (2, 10))
        seq, pooled = model(ids, token_type_ids=tok)
        with torch.no_grad():
            ref = tm(input_ids=torch.tensor(ids),
                     token_type_ids=torch.tensor(tok))
        np.testing.assert_allclose(seq.numpy(),
                                   ref.last_hidden_state.numpy(),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(pooled.numpy(),
                                   ref.pooler_output.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_padding_mask_matches_hf(self):
        cfg, model, tm = _make_pair(seed=1)
        rng = np.random.RandomState(1)
        ids = rng.randint(3, cfg.vocab_size, (2, 12))
        mask = np.ones((2, 12), np.int64)
        mask[0, 8:] = 0
        mask[1, 5:] = 0
        ids = ids * mask
        seq, _ = model(ids, attention_mask=mask)
        with torch.no_grad():
            ref = tm(input_ids=torch.tensor(ids),
                     attention_mask=torch.tensor(mask)).last_hidden_state
        # compare only the non-pad positions (pad rows attend freely in
        # both, but numerical garbage there is irrelevant)
        m = mask.astype(bool)
        np.testing.assert_allclose(seq.numpy()[m], ref.numpy()[m],
                                   rtol=2e-4, atol=2e-4)
