"""Round-5 surface additions: small top-level ops, printoptions,
unique_name, LazyGuard lazy parameter init, and paddle.hub (local)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestSmallOps:
    def test_is_tensor(self):
        assert paddle.is_tensor(paddle.ones([2]))
        assert not paddle.is_tensor(np.ones(2))

    def test_shape_rank(self):
        x = paddle.ones([2, 3, 4])
        assert paddle.shape(x).numpy().tolist() == [2, 3, 4]
        assert int(paddle.rank(x).numpy()) == 3

    def test_inf_sign_ops(self):
        x = paddle.to_tensor([float('inf'), -float('inf'), 1.0, -2.0])
        assert paddle.isposinf(x).numpy().tolist() == [True, False, False,
                                                       False]
        assert paddle.isneginf(x).numpy().tolist() == [False, True, False,
                                                       False]
        np.testing.assert_allclose(paddle.positive(x[2:]).numpy(),
                                   [1.0, -2.0])
        np.testing.assert_allclose(paddle.negative(x[2:]).numpy(),
                                   [-1.0, 2.0])

    def test_multigammaln_vs_scipy(self):
        from scipy.special import multigammaln as ref
        x = np.array([3.2, 5.5, 9.1])
        for p in (1, 2, 3):
            got = paddle.multigammaln(paddle.to_tensor(x), p).numpy()
            want = np.array([ref(v, p) for v in x])
            np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_flatten_inplace(self):
        x = paddle.ones([2, 3, 4])
        y = paddle.flatten_(x, 1, 2)
        assert y is x and x.shape == [2, 12]

    def test_set_printoptions(self):
        paddle.set_printoptions(precision=2)
        try:
            s = repr(paddle.to_tensor([3.14159]))
            assert '3.14' in s and '3.1416' not in s
        finally:
            paddle.set_printoptions(precision=4)


class TestDunders:
    def test_reflected_and_shift_operators(self):
        it = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        np.testing.assert_array_equal((7 % it).numpy(), [[0, 1], [1, 3]])
        np.testing.assert_array_equal((7 // it).numpy(), [[7, 3], [2, 1]])
        np.testing.assert_array_equal((it << 2).numpy(),
                                      [[4, 8], [12, 16]])
        np.testing.assert_array_equal((it >> 1).numpy(), [[0, 1], [1, 2]])
        q, r = divmod(it, 3)
        np.testing.assert_array_equal(q.numpy(), [[0, 0], [1, 1]])
        np.testing.assert_array_equal(r.numpy(), [[1, 2], [0, 1]])
        q2, r2 = divmod(7, paddle.to_tensor(np.array([1, 2, 3])))
        np.testing.assert_array_equal(q2.numpy(), [7, 3, 2])
        np.testing.assert_array_equal(r2.numpy(), [0, 1, 1])
        np.testing.assert_array_equal(
            (2 << paddle.to_tensor(np.array([1, 2]))).numpy(), [4, 8])
        np.testing.assert_array_equal(
            (16 >> paddle.to_tensor(np.array([1, 2]))).numpy(), [8, 4])
        t = paddle.ones([2])
        assert (+t) is t
        np.testing.assert_array_equal(
            paddle.bitwise_left_shift(
                it, paddle.to_tensor(np.array(1))).numpy(),
            [[2, 4], [6, 8]])


class TestLrAndInit:
    def test_linear_lr_vs_torch(self):
        import torch
        from paddle_tpu.optimizer.lr import LinearLR
        s = LinearLR(0.1, total_steps=4, start_factor=0.5)
        topt = torch.optim.SGD(torch.nn.Linear(1, 1).parameters(), lr=0.1)
        ts = torch.optim.lr_scheduler.LinearLR(topt, start_factor=0.5,
                                               total_iters=4)
        for i in range(7):
            np.testing.assert_allclose(s(), ts.get_last_lr()[0],
                                       rtol=1e-6)
            s.step(); topt.step(); ts.step()

    def test_multiplicative_decay_vs_torch(self):
        import torch
        from paddle_tpu.optimizer.lr import MultiplicativeDecay
        m = MultiplicativeDecay(0.1, lambda e: 0.9)
        topt = torch.optim.SGD(torch.nn.Linear(1, 1).parameters(), lr=0.1)
        tms = torch.optim.lr_scheduler.MultiplicativeLR(topt,
                                                        lambda e: 0.9)
        for i in range(5):
            np.testing.assert_allclose(m(), tms.get_last_lr()[0],
                                       rtol=1e-6)
            m.step(); topt.step(); tms.step()

    def test_bilinear_initializer_interpolates(self):
        I = paddle.nn.initializer
        w = np.asarray(I.Bilinear()((1, 1, 4, 4)))
        # tent filter: symmetric, peaks in the middle
        np.testing.assert_allclose(w[0, 0], w[0, 0].T, rtol=1e-6)
        assert w[0, 0, 1, 1] == w[0, 0].max()
        # a stride-2 transposed conv with this kernel upsamples a
        # constant image to a constant image (interpolation property)
        conv = paddle.nn.Conv2DTranspose(
            1, 1, 4, stride=2, padding=1,
            weight_attr=paddle.ParamAttr(initializer=I.Bilinear()),
            bias_attr=False)
        out = conv(paddle.ones([1, 1, 6, 6])).numpy()
        np.testing.assert_allclose(out[0, 0, 2:-2, 2:-2], 1.0, rtol=1e-5)

    def test_set_global_initializer(self):
        I = paddle.nn.initializer
        I.set_global_initializer(I.Constant(3.0), I.Constant(-1.0))
        try:
            lin = paddle.nn.Linear(2, 2)
        finally:
            I.set_global_initializer(None)
        assert float(lin.weight.numpy().min()) == 3.0
        assert float(lin.bias.numpy()[0]) == -1.0
        # defaults restored for layers built after reset
        assert float(paddle.nn.Linear(2, 2).weight.numpy().std()) > 0


class TestDiagGrad:
    def test_diag_vector_gradient_flows(self):
        # diag/diagflat used to wrap raw jnp results, silently detaching
        # the tape — exp(v) -> diag -> sum must backprop exp(v)
        v = paddle.to_tensor(np.array([0.1, 0.4], np.float32))
        v.stop_gradient = False
        m = paddle.diag(paddle.exp(v))
        assert not m.stop_gradient
        (g,) = paddle.grad(m.sum(), [v])
        np.testing.assert_allclose(g.numpy(), np.exp([0.1, 0.4]),
                                   rtol=1e-6)

    def test_diagflat_gradient_flows(self):
        v = paddle.to_tensor(np.array([[1.0, 2.0]], np.float32))
        v.stop_gradient = False
        (g,) = paddle.grad(paddle.diagflat(v * 2.0).sum(), [v])
        np.testing.assert_allclose(g.numpy(), [[2.0, 2.0]])

    def test_diag_extract_and_padding(self):
        m = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
        np.testing.assert_allclose(paddle.diag(m).numpy(), [0, 4, 8])
        d = paddle.diag(paddle.to_tensor(np.array([1.0, 2.0], np.float32)),
                        padding_value=7.0)
        np.testing.assert_allclose(d.numpy(), [[1, 7], [7, 2]])


class TestUniqueName:
    def test_generate_sequence(self):
        from paddle_tpu.utils import unique_name
        with unique_name.guard():
            assert unique_name.generate('fc') == 'fc_0'
            assert unique_name.generate('fc') == 'fc_1'
            assert unique_name.generate('conv') == 'conv_0'

    def test_guard_scoping_and_prefix(self):
        from paddle_tpu.utils import unique_name
        with unique_name.guard():
            a = unique_name.generate('x')
            with unique_name.guard('blk_'):
                assert unique_name.generate('x') == 'blk_x_0'
            # inner guard did not advance the outer sequence
            assert unique_name.generate('x') == 'x_1'
            assert a == 'x_0'


class TestLazyGuard:
    def test_lazy_params_materialize(self):
        paddle.seed(7)
        with paddle.LazyGuard():
            net = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                       paddle.nn.Linear(16, 4))
        ps = list(net.parameters())
        assert all(p.is_lazy for p in ps)
        # metadata is available before materialization
        assert ps[0].shape == [8, 16] and str(ps[0].dtype) == 'float32'
        for p in ps:
            p.initialize()
        assert not any(p.is_lazy for p in ps)
        out = net(paddle.ones([2, 8]))
        assert out.shape == [2, 4]

    def test_initialize_matches_eager_under_same_seed(self):
        paddle.seed(11)
        eager = paddle.nn.Linear(6, 5)
        paddle.seed(11)
        with paddle.LazyGuard():
            lazy = paddle.nn.Linear(6, 5)
        for p in lazy.parameters():
            p.initialize()
        np.testing.assert_allclose(eager.weight.numpy(),
                                   lazy.weight.numpy())

    def test_lazy_embedding_padding_idx(self):
        paddle.seed(3)
        with paddle.LazyGuard():
            emb = paddle.nn.Embedding(10, 4, padding_idx=0)
        emb.weight.initialize()
        w = emb.weight.numpy()
        np.testing.assert_allclose(w[0], 0.0)
        assert np.abs(w[1:]).sum() > 0

    def test_eager_param_initialize_is_noop(self):
        lin = paddle.nn.Linear(3, 3)
        w = lin.weight.numpy()
        lin.weight.initialize()
        np.testing.assert_allclose(lin.weight.numpy(), w)


class TestIoCallbacksDistributed:
    def test_concat_dataset(self):
        from paddle_tpu.io import ConcatDataset, Dataset

        class R(Dataset):
            def __init__(self, lo, hi):
                self.v = list(range(lo, hi))

            def __getitem__(self, i):
                return self.v[i]

            def __len__(self):
                return len(self.v)

        d = ConcatDataset([R(0, 3), R(10, 15)])
        assert len(d) == 8
        assert [d[i] for i in range(8)] == [0, 1, 2, 10, 11, 12, 13, 14]
        assert d[-1] == 14

    def test_reduce_lr_on_plateau(self):
        m = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(learning_rate=1.0,
                                    parameters=m.parameters())
        cb = paddle.callbacks.ReduceLROnPlateau(monitor='loss', factor=0.5,
                                                patience=2, verbose=0)

        class FakeModel:
            _optimizer = opt
        cb.model = FakeModel()
        cb.on_epoch_end(0, {'loss': 1.0})
        for e in range(1, 4):  # no improvement for patience=2 epochs
            cb.on_epoch_end(e, {'loss': 1.0})
        assert abs(opt.get_lr() - 0.5) < 1e-9

    def test_reduce_lr_plateau_eval_takes_precedence(self):
        m = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(learning_rate=1.0,
                                    parameters=m.parameters())
        cb = paddle.callbacks.ReduceLROnPlateau(monitor='loss', factor=0.5,
                                                patience=2, verbose=0)

        class FakeModel:
            _optimizer = opt
        cb.model = FakeModel()
        # eval improves while train plateaus: eval wins, no LR cut even
        # after many epochs (the old double-count would have cut twice)
        for e in range(6):
            cb.on_eval_end({'loss': 1.0 - 0.1 * e})
            cb.on_epoch_end(e, {'loss': 5.0})
        assert opt.get_lr() == 1.0

    def test_reduce_lr_plateau_rejects_bad_factor(self):
        with pytest.raises(ValueError, match='factor'):
            paddle.callbacks.ReduceLROnPlateau(factor=1.5)

    def test_concat_dataset_out_of_range(self):
        from paddle_tpu.io import ConcatDataset, TensorDataset
        d = ConcatDataset([TensorDataset([np.zeros((3, 2))])])
        with pytest.raises(IndexError):
            d[3]
        with pytest.raises(IndexError):
            d[-4]

    def test_destroy_specific_default_group(self):
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        g = dist.get_group()
        assert dist.get_group() is g  # cached => identity-stable
        dist.destroy_process_group(g)
        assert dist.get_group() is not g  # really removed, fresh next time
        dist.destroy_process_group()

    def test_spawn_and_destroy(self):
        import paddle_tpu.distributed as dist
        got = dist.spawn(lambda a, b: a + b, args=(2, 3))
        assert got == 5 and dist.is_initialized()
        with pytest.raises(NotImplementedError):
            dist.spawn(lambda: None, nprocs=4)
        dist.destroy_process_group()
        assert not dist.is_initialized()
        dist.init_parallel_env()  # fresh init works after teardown
        assert dist.is_initialized()


class TestHub:
    def test_local_hub_roundtrip(self, tmp_path):
        (tmp_path / 'hubconf.py').write_text(
            "import paddle_tpu as paddle\n"
            "def tiny_mlp(width=4):\n"
            "    '''A tiny MLP.'''\n"
            "    return paddle.nn.Linear(2, width)\n")
        d = str(tmp_path)
        assert 'tiny_mlp' in paddle.hub.list(d)
        assert 'tiny MLP' in paddle.hub.help(d, 'tiny_mlp')
        m = paddle.hub.load(d, 'tiny_mlp', width=6)
        assert m(paddle.ones([1, 2])).shape == [1, 6]

    def test_remote_source_rejected(self, tmp_path):
        with pytest.raises(RuntimeError, match='network'):
            paddle.hub.load('user/repo', 'model', source='github')

    def test_missing_entry_point(self, tmp_path):
        (tmp_path / 'hubconf.py').write_text('x = 1\n')
        with pytest.raises(ValueError, match='entry point'):
            paddle.hub.load(str(tmp_path), 'nope')
