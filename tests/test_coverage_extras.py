"""Round-4 API wideners: paddle.fft, paddle.signal, tensordot/cdist/
bucketize, linalg.lu, nn.functional grid_sample/affine_grid/fold/
temporal_shift, nn.utils weight_norm/spectral_norm, paddle.flops,
io.SubsetRandomSampler (upstream python/paddle/{fft,signal,...})."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestFFT:
    def test_fft_roundtrip_matches_numpy(self):
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        out = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), np.fft.fft(x), rtol=1e-4,
                                   atol=1e-4)
        back = paddle.fft.ifft(out)
        np.testing.assert_allclose(back.numpy().real, x, rtol=1e-4,
                                   atol=1e-5)

    def test_rfft_irfft(self):
        x = np.random.RandomState(1).randn(8).astype(np.float32)
        r = paddle.fft.rfft(paddle.to_tensor(x))
        assert r.shape == [5]
        np.testing.assert_allclose(
            paddle.fft.irfft(r, n=8).numpy(), x, rtol=1e-4, atol=1e-5)

    def test_fft2_and_shift(self):
        x = np.random.RandomState(2).randn(4, 4).astype(np.float32)
        f2 = paddle.fft.fft2(paddle.to_tensor(x))
        np.testing.assert_allclose(f2.numpy(), np.fft.fft2(x), rtol=1e-4,
                                   atol=1e-4)
        sh = paddle.fft.fftshift(f2)
        np.testing.assert_allclose(sh.numpy(),
                                   np.fft.fftshift(np.fft.fft2(x)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(paddle.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5), rtol=1e-6)


class TestSignal:
    def test_stft_matches_manual_dft(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 64).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=16,
                                  hop_length=8, center=False)
        assert spec.shape == [1, 9, 7]
        # frame 0 == rfft of the first 16 samples
        np.testing.assert_allclose(spec.numpy()[0, :, 0],
                                   np.fft.rfft(x[0, :16]), rtol=1e-3,
                                   atol=1e-4)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 128).astype(np.float32)
        win = np.hanning(16).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=16,
                                  hop_length=4,
                                  window=paddle.to_tensor(win))
        back = paddle.signal.istft(spec, n_fft=16, hop_length=4,
                                   window=paddle.to_tensor(win),
                                   length=128)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-3, atol=1e-3)

    def test_frame_overlap_add_inverse(self):
        x = paddle.to_tensor(np.arange(20, dtype=np.float32))
        fr = paddle.signal.frame(x, frame_length=4, hop_length=4)
        assert fr.shape == [4, 5]
        back = paddle.signal.overlap_add(fr, hop_length=4)
        np.testing.assert_array_equal(back.numpy(), x.numpy())


class TestMathExtras:
    def test_tensordot_modes(self):
        a = np.random.RandomState(0).randn(3, 4, 5).astype(np.float32)
        b = np.random.RandomState(1).randn(4, 5, 6).astype(np.float32)
        out = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                               axes=2)
        np.testing.assert_allclose(out.numpy(), np.tensordot(a, b, 2),
                                   rtol=1e-4)
        out2 = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                                axes=[[1], [0]])
        np.testing.assert_allclose(out2.numpy(),
                                   np.tensordot(a, b, ([1], [0])),
                                   rtol=1e-4)

    @pytest.mark.parametrize('p', [2.0, 1.0, float('inf')])
    def test_cdist(self, p):
        a = np.random.RandomState(2).randn(4, 3).astype(np.float32)
        b = np.random.RandomState(3).randn(5, 3).astype(np.float32)
        got = paddle.cdist(paddle.to_tensor(a), paddle.to_tensor(b),
                           p=p).numpy()
        from scipy.spatial.distance import cdist as sp
        metric = {2.0: 'euclidean', 1.0: 'cityblock',
                  float('inf'): 'chebyshev'}[p]
        np.testing.assert_allclose(got, sp(a, b, metric=metric),
                                   rtol=1e-4, atol=1e-5)

    def test_bucketize(self):
        out = paddle.bucketize(paddle.to_tensor([0.5, 1.0, 2.5, 9.0]),
                               paddle.to_tensor([1.0, 2.0, 3.0]))
        np.testing.assert_array_equal(out.numpy(), [0, 0, 2, 3])
        right = paddle.bucketize(paddle.to_tensor([1.0]),
                                 paddle.to_tensor([1.0, 2.0]), right=True)
        assert int(right.numpy()[0]) == 1

    def test_lu_reconstruction(self):
        m = np.random.RandomState(4).randn(5, 5).astype(np.float32)
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(m))
        assert piv.numpy().min() >= 1  # paddle pivots are 1-based
        P, L, U = paddle.linalg.lu_unpack(lu_t, piv)
        np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(), m,
                                   rtol=1e-4, atol=1e-4)


class TestFunctionalExtras:
    @pytest.mark.slow
    def test_fold_inverts_unfold(self):
        x = paddle.rand([2, 3, 8, 8])
        cols = F.unfold(x, 2, strides=2)
        back = F.fold(cols, output_sizes=(8, 8), kernel_sizes=2,
                      strides=2)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)

    def test_fold_sums_overlaps(self):
        x = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32))
        cols = F.unfold(x, 3, strides=1)
        back = F.fold(cols, (4, 4), 3, strides=1)
        # center cells belong to 9 overlapping 3x3 patches
        assert float(back.numpy()[0, 0, 1, 1]) == pytest.approx(4.0)

    @pytest.mark.slow

    def test_affine_grid_identity_and_grid_sample(self):
        theta = paddle.to_tensor(
            np.array([[[1, 0, 0], [0, 1, 0]]], np.float32))
        grid = F.affine_grid(theta, [1, 1, 4, 4])
        assert grid.shape == [1, 4, 4, 2]
        x = paddle.rand([1, 2, 4, 4])
        out = F.grid_sample(x, grid)
        np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-5,
                                   atol=1e-6)

    def test_grid_sample_zeros_padding(self):
        x = paddle.to_tensor(np.ones((1, 1, 2, 2), np.float32))
        grid = paddle.to_tensor(
            np.array([[[[-3.0, -3.0], [0.0, 0.0]]]], np.float32))
        out = F.grid_sample(x, grid)
        np.testing.assert_allclose(out.numpy()[0, 0, 0], [0.0, 1.0],
                                   rtol=1e-6)

    def test_temporal_shift_moves_channels(self):
        nt, c = 4, 8  # n=2 segments of t=2
        x = np.arange(nt * c * 1 * 1, dtype=np.float32) \
            .reshape(nt, c, 1, 1)
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=2,
                               shift_ratio=0.25).numpy()
        # first fold channels pulled from t+1; last timestep zero-filled
        np.testing.assert_array_equal(out[0, :2], x[1, :2])
        np.testing.assert_array_equal(out[1, :2], 0)


class TestNNUtils:
    def test_weight_norm_preserves_forward_and_trains(self):
        paddle.seed(0)
        lin = nn.Linear(6, 4)
        w0 = lin.weight.numpy().copy()
        nn.utils.weight_norm(lin)
        x = paddle.rand([3, 6])
        np.testing.assert_allclose(
            lin(x).numpy(), x.numpy() @ w0 + lin.bias.numpy(),
            rtol=1e-5, atol=1e-6)
        lin(x).sum().backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None
        nn.utils.remove_weight_norm(lin)
        np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5,
                                   atol=1e-6)

    def test_spectral_norm_divides_by_sigma(self):
        paddle.seed(1)
        lin = nn.Linear(5, 7)
        w = lin.weight.numpy().copy()
        nn.utils.spectral_norm(lin, n_power_iterations=25)
        x = paddle.rand([2, 5])
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        np.testing.assert_allclose(
            lin(x).numpy(),
            x.numpy() @ (w / sigma) + lin.bias.numpy(),
            rtol=1e-3, atol=1e-4)

    def test_spectral_norm_layer_form(self):
        w = np.random.RandomState(5).randn(5, 7).astype(np.float32)
        sn = nn.SpectralNorm([5, 7], power_iters=25)
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        np.testing.assert_allclose(sn(paddle.to_tensor(w)).numpy(),
                                   w / sigma, rtol=1e-3, atol=1e-4)

    def test_parameters_vector_roundtrip(self):
        lin = nn.Linear(3, 2)
        vec = nn.utils.parameters_to_vector(lin.parameters())
        assert vec.shape == [8]
        nn.utils.vector_to_parameters(vec * 2.0, lin.parameters())
        np.testing.assert_allclose(
            nn.utils.parameters_to_vector(lin.parameters()).numpy(),
            vec.numpy() * 2.0, rtol=1e-6)


class TestFlopsAndSamplers:
    @pytest.mark.slow
    def test_flops_counts_linear_and_conv(self):
        m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                          nn.Flatten(), nn.Linear(8 * 64, 10))
        total = paddle.flops(m, [1, 3, 8, 8])
        want = 2 * 64 * 8 * 3 * 9 + 64 * 8 + 2 * 8 * 64 * 10
        assert total == want

    def test_flops_custom_ops_override(self):
        m = nn.Linear(4, 4)
        total = paddle.flops(m, [1, 4],
                             custom_ops={nn.Linear: lambda l, i, o: 42})
        assert total == 42

    def test_subset_random_sampler(self):
        from paddle_tpu.io import SubsetRandomSampler
        s = SubsetRandomSampler([3, 5, 7], generator=0)
        got = list(s)
        assert sorted(got) == [3, 5, 7] and len(s) == 3

    @pytest.mark.slow

    def test_conv3d_transpose_shape(self):
        ct = nn.Conv3DTranspose(2, 3, 3, stride=2, padding=1)
        out = ct(paddle.rand([1, 2, 4, 4, 4]))
        assert out.shape == [1, 3, 7, 7, 7]


class TestReviewRegressions:
    """Round-4 review findings — each was a confirmed defect."""

    @pytest.mark.slow
    def test_shufflenet_x0_25_has_own_widths(self):
        from paddle_tpu.vision import models as M
        m = M.shufflenet_v2_x0_25(num_classes=3)
        # x0_25 tail conv outputs 512 channels (0.5 would be 1024)
        assert m.fc.in_features == 512

    def test_color_jitter_accepts_ranges(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.RandomState(0).rand(8, 8, 3) * 255) \
            .astype(np.uint8)
        out = T.ColorJitter(brightness=(0.8, 1.2), contrast=(0.9, 1.1),
                            saturation=(0.9, 1.1), hue=(-0.1, 0.1))(img)
        assert out.shape == img.shape

    def test_temporal_shift_nhwc_matches_nchw(self):
        x = np.random.RandomState(1).randn(4, 8, 2, 3).astype(np.float32)
        ref = F.temporal_shift(paddle.to_tensor(x), seg_num=2).numpy()
        got = F.temporal_shift(
            paddle.to_tensor(x.transpose(0, 2, 3, 1)), seg_num=2,
            data_format='NHWC').numpy()
        np.testing.assert_allclose(got.transpose(0, 3, 1, 2), ref,
                                   rtol=1e-6)
        with pytest.raises(ValueError, match='data_format'):
            F.temporal_shift(paddle.to_tensor(x), 2, data_format='NCWH')

    def test_lu_unpack_batched(self):
        m = np.random.RandomState(2).randn(3, 4, 4).astype(np.float32)
        lu_t, piv = paddle.linalg.lu(paddle.to_tensor(m))
        P, L, U = paddle.linalg.lu_unpack(lu_t, piv)
        rec = np.einsum('bij,bjk,bkl->bil', P.numpy(), L.numpy(),
                        U.numpy())
        np.testing.assert_allclose(rec, m, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize('padding_mode', ['zeros', 'border',
                                              'reflection'])
    @pytest.mark.parametrize('mode', ['bilinear', 'nearest'])
    @pytest.mark.parametrize('align_corners', [True, False])
    def test_grid_sample_matches_torch(self, padding_mode, mode,
                                       align_corners):
        torch = pytest.importorskip('torch')
        rng = np.random.RandomState(3)
        x = rng.randn(2, 3, 5, 6).astype(np.float32)
        grid = (rng.rand(2, 4, 7, 2).astype(np.float32) * 3 - 1.5)
        want = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(grid), mode=mode,
            padding_mode=padding_mode,
            align_corners=align_corners).numpy()
        got = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                            mode=mode, padding_mode=padding_mode,
                            align_corners=align_corners).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
