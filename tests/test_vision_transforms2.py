"""Round-5 vision.transforms additions (upstream
python/paddle/vision/transforms/): single-factor jitters, RandomErasing,
RandomAffine, RandomPerspective, Transpose, crop/erase/adjust_* ops."""
import numpy as np
import pytest

import paddle_tpu as paddle

T = paddle.vision.transforms


def _img(h=32, w=48):
    return np.random.RandomState(0).uniform(0, 255, (h, w, 3)).astype(
        np.uint8)


class TestSimpleTransforms:
    def test_transpose(self):
        out = T.Transpose()(_img())
        assert out.shape == (3, 32, 48)
        np.testing.assert_array_equal(out[0], _img()[:, :, 0])

    def test_single_factor_jitters_change_image(self):
        np.random.seed(1)
        img = _img()
        for cls in (T.BrightnessTransform, T.ContrastTransform,
                    T.SaturationTransform, T.HueTransform):
            out = cls(0.4)(img)
            assert out.shape == img.shape and out.dtype == img.dtype
        # zero-value jitter is identity
        np.testing.assert_array_equal(T.BrightnessTransform(0)(img), img)

    def test_adjust_ops(self):
        img = _img()
        b = T.adjust_brightness(img, 1.5)
        assert float(b.mean()) > float(img.mean()) * 1.2
        d = T.adjust_brightness(img, 0.5)
        assert float(d.mean()) < float(img.mean()) * 0.6
        c = T.adjust_contrast(img, 0.0)  # zero contrast -> flat image
        assert np.ptp(c.astype(np.float32).mean(axis=2)) <= 1.0

    def test_crop_and_erase(self):
        img = _img()
        c = T.crop(img, 4, 6, 10, 12)
        np.testing.assert_array_equal(c, img[4:14, 6:18])
        e = T.erase(img, 2, 3, 5, 7, 0)
        assert (e[2:7, 3:10] == 0).all()
        assert (e[0:2] == img[0:2]).all()
        # inplace=False left the original untouched
        assert not (img[2:7, 3:10] == 0).all()


class TestRandomErasing:
    def test_erases_with_prob_one(self):
        np.random.seed(0)
        img = _img()
        out = T.RandomErasing(prob=1.0, value=0)(img)
        erased = (out == 0).all(axis=2).sum()
        assert erased >= int(0.02 * 32 * 48 * 0.9)
        np.testing.assert_array_equal(
            T.RandomErasing(prob=0.0)(img), img)

    def test_chw_input_erases_spatial_patch(self):
        # upstream applies RandomErasing AFTER ToTensor (CHW float32):
        # the erased region must be a spatial rectangle, not a
        # cross-channel band
        np.random.seed(5)
        chw = T.ToTensor()(_img())
        out = T.RandomErasing(prob=1.0, value=0)(chw)
        assert out.shape == chw.shape
        zero = (out == 0).all(axis=0)
        ys, xs = np.nonzero(zero)
        rect = (ys.max() - ys.min() + 1) * (xs.max() - xs.min() + 1)
        assert len(ys) == rect  # contiguous spatial rectangle
        e = T.erase(chw, 2, 3, 5, 7, 0.0)
        assert (e[:, 2:7, 3:10] == 0).all()

    def test_rotation_through_shared_warp(self):
        img = _img(20, 20)
        np.testing.assert_array_equal(T.rotate(img, 0), img)
        np.testing.assert_array_equal(T.rotate(img, 180),
                                      img[::-1, ::-1])

    def test_random_fill(self):
        np.random.seed(0)
        out = T.RandomErasing(prob=1.0, value='random')(_img())
        assert out.shape == (32, 48, 3)


class TestWarps:
    def test_identity_affine_and_perspective_are_exact(self):
        np.random.seed(2)
        img = _img()
        np.testing.assert_array_equal(
            T.RandomAffine(degrees=(0, 0))(img), img)
        np.testing.assert_array_equal(
            T.RandomPerspective(prob=1.0, distortion_scale=0.0)(img), img)

    def test_pure_translation_shifts(self):
        np.random.seed(0)
        img = np.zeros((16, 16, 1), np.float32)
        img[8, 8, 0] = 1.0
        # translate range (d, d) forces a deterministic |shift| <= d*16
        out = T.RandomAffine(degrees=(0, 0), translate=(0.25, 0.25))(img)
        # mass is conserved away from borders
        assert abs(out.sum() - 1.0) < 1e-4
        ys, xs = np.nonzero(out[:, :, 0] > 1e-6)
        assert len(ys) >= 1  # landed somewhere (possibly split bilinear)

    def test_affine_scale_shrinks_content(self):
        np.random.seed(0)
        img = np.ones((20, 20, 1), np.float32)
        out = T.RandomAffine(degrees=(0, 0), scale=(2.0, 2.0))(img)
        # scale=2 zooms OUT content in inverse map convention or IN —
        # either way the warp must keep values in [0, 1]
        assert out.min() >= 0.0 and out.max() <= 1.0 + 1e-6

    def test_perspective_distorts(self):
        np.random.seed(3)
        img = _img()
        out = T.RandomPerspective(prob=1.0, distortion_scale=0.5)(img)
        assert out.shape == img.shape
        assert np.abs(out.astype(int) - img.astype(int)).mean() > 1.0


class TestImageFolders:
    def _make_tree(self, tmp_path):
        from PIL import Image
        for cls in ('cat', 'dog'):
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                arr = np.random.RandomState(i).randint(
                    0, 255, (8, 8, 3), np.uint8)
                Image.fromarray(arr).save(str(d / f'{i}.png'))
                (d / f'{i}.txt').write_text('not an image')
        return str(tmp_path)

    def test_dataset_folder(self, tmp_path):
        root = self._make_tree(tmp_path)
        ds = paddle.vision.datasets.DatasetFolder(root)
        assert ds.classes == ['cat', 'dog'] and len(ds) == 6
        img, lab = ds[0]
        assert img.shape == (8, 8, 3) and img.dtype == np.uint8
        assert sorted({l for _, l in ds.samples}) == [0, 1]

    def test_image_folder_and_loader_pipeline(self, tmp_path):
        root = self._make_tree(tmp_path)
        flat = paddle.vision.datasets.ImageFolder(root)
        assert len(flat) == 6 and flat[0][0].shape == (8, 8, 3)
        t = T.Compose([T.Resize(16), T.ToTensor()])
        ds = paddle.vision.datasets.DatasetFolder(root, transform=t)
        from paddle_tpu.io import DataLoader
        xb, yb = next(iter(DataLoader(ds, batch_size=4, shuffle=True)))
        assert list(xb.shape) == [4, 3, 16, 16] and list(yb.shape) == [4]

    def test_image_load_and_backend(self, tmp_path):
        from PIL import Image
        p = str(tmp_path / 'x.png')
        arr = np.random.RandomState(0).randint(0, 255, (6, 7, 3), np.uint8)
        Image.fromarray(arr).save(p)
        got = paddle.vision.image_load(p)
        np.testing.assert_array_equal(got, arr)
        assert paddle.vision.get_image_backend() == 'pil'
        with pytest.raises(ValueError):
            paddle.vision.set_image_backend('nope')

    def test_empty_folder_raises(self, tmp_path):
        with pytest.raises(RuntimeError):
            paddle.vision.datasets.DatasetFolder(str(tmp_path))


class TestComposeIntegration:
    def test_augmentation_pipeline(self):
        np.random.seed(4)
        pipe = T.Compose([
            T.Resize(40),
            T.RandomCrop(32),
            T.RandomHorizontalFlip(),
            T.BrightnessTransform(0.2),
            T.RandomErasing(prob=1.0),
            T.ToTensor(),
        ])
        out = pipe(_img(48, 64))
        assert list(out.shape) == [3, 32, 32]
        assert str(out.dtype) == 'float32'
