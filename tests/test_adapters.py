"""paddle_tpu.serving.adapters — multi-tenant LoRA adapter serving
(ISSUE 19).

The acceptance surface: a fixed-capacity packed `AdapterBank` (slot
table, ref-count pinning, LRU eviction, WeightStore hot-load/publish
with corrupt-manifest quarantine), heterogeneous-adapter batched
decode that is bit-identical to serving each adapter alone with ZERO
recompiles across mixes AND a mid-run publish, prefix-cache
namespacing on (adapter_id, version) so tenants never share prefix KV
across adapters, tenancy `adapter=` defaults with the typed
`adapter_unavailable` fast-fail, and loadgen per-tenant adapter mixes
that keep traces bit-identical from one seed.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import loadgen, observability as obs
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (FINISHED, AdapterBank, AdapterUnavailable,
                                AdmissionRejected, InferenceEngine,
                                ReplicaSet, Router, SamplingParams,
                                TenantRegistry, make_adapter_factors,
                                parse_tenant_spec)
from paddle_tpu.serving.hotswap import WeightStore

NO_EOS = -1


@pytest.fixture(scope='module')
def gpt():
    paddle.seed(7)
    return GPTForCausalLM(GPTConfig.tiny()).eval()


def _sp(n):
    return SamplingParams(max_new_tokens=n, eos_token_id=NO_EOS)


def _prompts(lens, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, (s,)).tolist() for s in lens]


def _ref_generate(model, prompt, max_new):
    out, _ = model.generate(
        paddle.to_tensor(np.array([prompt])), max_new_tokens=max_new,
        decode_strategy='greedy_search', eos_token_id=NO_EOS)
    return out.numpy()[0].tolist()


def _events_since(log, n0, name):
    return [e for e in log.events()[n0:] if e['name'] == name]


def _bank(gpt, n_adapters=2, capacity=None, rank=4, seed0=1, **kw):
    """A bank holding `ad0..ad{n-1}` with deterministic factors —
    `make_adapter_factors(bank, seed)` depends only on sites/rank, so
    two banks built the same way hold bit-identical adapters."""
    bank = AdapterBank(gpt, capacity=capacity or n_adapters + 1,
                       rank=rank, **kw)
    for i in range(n_adapters):
        bank.load(f'ad{i}', _factors(bank, seed0 + i), version=1)
    return bank


def _factors(bank, seed):
    """Factors strong enough to actually flip greedy argmax on the
    tiny test model (the default 0.02 scale is tuned for bench-sized
    decode lengths)."""
    return make_adapter_factors(bank, seed=seed, scale=0.2)


# ---------------------------------------------------------------------------
# the bank: slot table, pinning, eviction, validation
# ---------------------------------------------------------------------------

class TestAdapterBank:
    def test_ctor_validation(self, gpt):
        with pytest.raises(ValueError):
            AdapterBank(gpt, capacity=0)
        with pytest.raises(ValueError):
            AdapterBank(gpt, rank=0)
        with pytest.raises(ValueError):
            AdapterBank(gpt, targets=('no_such_proj',))

    def test_statics_carry_only_geometry(self, gpt):
        """The zero-recompile contract: program-store keys see capacity,
        rank, and the target-site set — NEVER slot contents."""
        bank = AdapterBank(gpt, capacity=4, rank=4)
        st0 = bank.describe_statics()
        assert st0 == {'capacity': 4, 'rank': 4,
                       'targets': tuple(sorted(bank.sites))}
        bank.load('a', make_adapter_factors(bank, 1))
        assert bank.describe_statics() == st0

    def test_device_arrays_shapes_and_zero_base_row(self, gpt):
        bank = AdapterBank(gpt, capacity=3, rank=4)
        arrs = bank.device_arrays()
        assert set(arrs) == {'factors', 'scale'}
        assert arrs['scale'].shape == (4,)
        for site, (i, o) in bank.sites.items():
            a, b = arrs['factors'][site]['a'], arrs['factors'][site]['b']
            assert a.shape == (4, i, 4) and b.shape == (4, 4, o)
            assert not np.asarray(a[0]).any()
            assert not np.asarray(b[0]).any()
        assert float(arrs['scale'][0]) == 0.0

    def test_load_lookup_stats(self, gpt):
        bank = _bank(gpt, 2)
        assert bank.lookup('ad0') == (1, 1)
        assert bank.lookup('ad1') == (2, 1)
        assert bank.lookup('ghost') is None
        assert bank.available('ad0') and not bank.available('ghost')
        st = bank.stats()
        assert st['pinned'] == 0
        assert set(st['resident']) == {'ad0', 'ad1'}
        assert st['resident']['ad0'] == {'slot': 1, 'version': 1,
                                         'refs': 0}

    def test_pin_unpin_refcounts(self, gpt):
        bank = _bank(gpt, 1)
        slot, ver = bank.pin('ad0')
        assert (slot, ver) == (1, 1)
        bank.pin('ad0')
        assert bank.stats()['resident']['ad0']['refs'] == 2
        bank.unpin(slot)
        bank.unpin(slot)
        assert bank.stats()['pinned'] == 0
        with pytest.raises(RuntimeError):
            bank.unpin(slot)
        bank.unpin(0)          # the base slot is never refcounted

    def test_pin_unknown_raises_typed(self, gpt):
        bank = _bank(gpt, 1)
        with pytest.raises(AdapterUnavailable) as ei:
            bank.pin('ghost')
        assert ei.value.adapter_id == 'ghost'

    def test_lru_evicts_oldest_zero_ref_slot(self, gpt):
        """Bank full of zero-ref adapters: the least-recently-pinned
        one is evicted for the newcomer, with an adapter_evict event."""
        bank = _bank(gpt, 2, capacity=2)
        # ad0 older than ad1 by load order; touching ad0 makes ad1 LRU
        s0, _ = bank.pin('ad0')
        bank.unpin(s0)
        log = obs.get_event_log()
        ev0 = len(log.events())
        slot, _ = bank.load('ad2', make_adapter_factors(bank, 9))
        assert slot == 2                       # ad1's old slot
        assert bank.lookup('ad1') is None
        assert bank.lookup('ad0') == (1, 1)    # survivor untouched
        evs = _events_since(log, ev0, 'adapter_evict')
        assert len(evs) == 1 and evs[0]['attrs']['adapter'] == 'ad1'

    def test_bank_full_of_pins_is_typed_unavailable(self, gpt):
        bank = _bank(gpt, 2, capacity=2)
        bank.pin('ad0')
        bank.pin('ad1')
        with pytest.raises(AdapterUnavailable) as ei:
            bank.load('ad2', make_adapter_factors(bank, 9))
        assert 'bank full' in ei.value.detail

    def test_factor_validation(self, gpt):
        bank = AdapterBank(gpt, capacity=2, rank=4)
        good = make_adapter_factors(bank, 1)
        site = next(iter(bank.sites))
        # wrong rank (rank is a static — all adapters share it)
        bad = dict(good)
        a, b = good[site]
        bad[site] = (a[:, :2], b[:2, :])
        with pytest.raises(ValueError, match='rank'):
            bank.load('x', bad)
        # unknown target site
        with pytest.raises(ValueError, match='unknown target site'):
            bank.load('x', {**good, 'nowhere.qkv_proj': good[site]})
        # missing site
        missing = dict(good)
        del missing[site]
        with pytest.raises(ValueError, match='missing'):
            bank.load('x', missing)

    def test_make_adapter_factors_deterministic(self, gpt):
        bank = AdapterBank(gpt, capacity=2, rank=4)
        f1 = make_adapter_factors(bank, seed=5)
        f2 = make_adapter_factors(bank, seed=5)
        f3 = make_adapter_factors(bank, seed=6)
        assert set(f1) == set(bank.sites)
        for site in f1:
            assert np.array_equal(f1[site][0], f2[site][0])
            assert np.array_equal(f1[site][1], f2[site][1])
            assert not np.array_equal(f1[site][0], f3[site][0])

    def test_hot_reload_same_slot_new_version(self, gpt):
        """Reloading a resident adapter writes its EXISTING slot (a
        functional .at[slot].set — same avals) and bumps the version."""
        bank = _bank(gpt, 1)
        arrs0 = bank.device_arrays()
        slot, ver = bank.load('ad0', make_adapter_factors(bank, 50),
                              version=2)
        assert (slot, ver) == (1, 2)
        arrs1 = bank.device_arrays()
        site = next(iter(bank.sites))
        a0, a1 = arrs0['factors'][site]['a'], arrs1['factors'][site]['a']
        assert a0.shape == a1.shape and a0.dtype == a1.dtype
        assert not np.array_equal(np.asarray(a0[1]), np.asarray(a1[1]))


# ---------------------------------------------------------------------------
# hot-load / publish / rollback through the WeightStore plane
# ---------------------------------------------------------------------------

class TestAdapterStore:
    def test_publish_then_pin_loads_latest(self, gpt, tmp_path):
        bank = AdapterBank(gpt, capacity=2, rank=4,
                           store_dir=str(tmp_path))
        assert not bank.available('ad0')
        v1 = bank.publish('ad0', _factors(bank, 1))
        assert bank.available('ad0')           # servable from the store
        assert bank.lookup('ad0') is None      # but NOT resident yet
        slot, ver = bank.pin('ad0')            # lazy load on first pin
        assert ver == v1
        assert bank.lookup('ad0') == (slot, v1)

    def test_publish_v2_never_touches_pinned_v1_slot(self, gpt, tmp_path):
        """The rollback-safety core: v1 keeps decoding bit-exact out of
        its own slot while v2 lands in a FRESH slot for new pins."""
        bank = AdapterBank(gpt, capacity=3, rank=4,
                           store_dir=str(tmp_path))
        v1 = bank.publish('ad0', _factors(bank, 1))
        s1, _ = bank.pin('ad0')                # in-flight request on v1
        site = next(iter(bank.sites))
        a_v1 = np.asarray(bank.device_arrays()['factors'][site]['a'][s1])
        v2 = bank.publish('ad0', _factors(bank, 2))
        # publish is lazy: nothing moved until someone pins
        assert np.array_equal(
            np.asarray(bank.device_arrays()['factors'][site]['a'][s1]),
            a_v1)
        s2, ver2 = bank.pin('ad0')
        assert ver2 == v2 and s2 != s1
        # v1's slot bytes are still exactly v1's
        assert np.array_equal(
            np.asarray(bank.device_arrays()['factors'][site]['a'][s1]),
            a_v1)
        assert bank.stats()['resident']['ad0']['version'] == v2
        bank.unpin(s1)
        bank.unpin(s2)

    def test_corrupt_manifest_quarantined_bank_keeps_serving(
            self, gpt, tmp_path):
        """A corrupt v2 payload: pin() quarantines it with an
        adapter_load_reject event and keeps serving resident v1 —
        the fleet never swaps onto bytes that fail their sha256."""
        bank = AdapterBank(gpt, capacity=2, rank=4,
                           store_dir=str(tmp_path))
        v1 = bank.publish('ad0', _factors(bank, 1))
        bank.unpin(bank.pin('ad0')[0])         # v1 resident
        v2 = bank.publish('ad0', _factors(bank, 2))
        payload = tmp_path / 'ad0' / f'step_{v2}' / 'tree.npz'
        raw = bytearray(payload.read_bytes())
        raw[len(raw) // 2] ^= 0xFF             # one flipped bit
        payload.write_bytes(bytes(raw))
        log = obs.get_event_log()
        ev0 = len(log.events())
        slot, ver = bank.pin('ad0')
        assert ver == v1                       # still serving v1
        evs = _events_since(log, ev0, 'adapter_load_reject')
        assert len(evs) == 1 and evs[0]['attrs']['version'] == v2
        store = WeightStore(str(tmp_path / 'ad0'))
        assert store.quarantined() == [v2]
        # quarantine sticks: the next pin never re-probes v2
        ev1 = len(log.events())
        assert bank.pin('ad0')[1] == v1
        assert not _events_since(log, ev1, 'adapter_load_reject')

    def test_corrupt_only_version_is_typed_unavailable(self, gpt,
                                                       tmp_path):
        bank = AdapterBank(gpt, capacity=2, rank=4,
                           store_dir=str(tmp_path))
        v1 = bank.publish('ad0', _factors(bank, 1))
        payload = tmp_path / 'ad0' / f'step_{v1}' / 'tree.npz'
        raw = bytearray(payload.read_bytes())
        raw[0] ^= 0xFF
        payload.write_bytes(bytes(raw))
        with pytest.raises(AdapterUnavailable):
            bank.pin('ad0')
        assert not bank.available('ad0')       # quarantine made it moot

    def test_bad_adapter_id_rejected_before_touching_disk(self, gpt,
                                                          tmp_path):
        bank = AdapterBank(gpt, capacity=2, rank=4,
                           store_dir=str(tmp_path))
        with pytest.raises(ValueError, match='bad adapter id'):
            bank.publish('../escape', make_adapter_factors(bank, 1))


# ---------------------------------------------------------------------------
# the engine: heterogeneous-adapter batched decode
# ---------------------------------------------------------------------------

class TestEngineAdapters:
    def _engine(self, gpt, bank, **kw):
        kw.setdefault('num_slots', 4)
        kw.setdefault('max_length', 64)
        kw.setdefault('decode_block', 2)
        return InferenceEngine(gpt, adapter_bank=bank, **kw)

    def test_mixed_batch_bit_identical_to_each_adapter_alone(self, gpt):
        """THE acceptance bar: one mixed wave (base + ad0 + ad1 in the
        same decode block) produces, per request, exactly the tokens
        that request gets when its adapter is served alone."""
        prompts = _prompts([4, 6, 5, 7], seed=1)
        ids = [None, 'ad0', 'ad1', 'ad0']
        sp = [_sp(5)] * 4
        # references: each adapter alone on its own engine + bank
        refs = {}
        for aid in ('ad0', 'ad1'):
            eng = self._engine(gpt, _bank(gpt, 2))
            refs[aid] = [h.tokens for h in
                         eng.generate_many(prompts, sp, adapter_ids=aid)]
        base_refs = [_ref_generate(gpt, p, 5) for p in prompts]
        mixed = self._engine(gpt, _bank(gpt, 2)).generate_many(
            prompts, sp, adapter_ids=ids)
        for j, (h, aid) in enumerate(zip(mixed, ids)):
            assert h.status == FINISHED
            want = base_refs[j] if aid is None else refs[aid][j]
            assert h.tokens == want, (j, aid)
            assert h.adapter_id == aid
        # the adapters actually did something: outputs differ per
        # adapter on at least one shared prompt
        assert refs['ad0'][1] != base_refs[1]
        assert refs['ad0'][1] != refs['ad1'][1]

    def test_base_requests_bit_identical_to_bank_less_engine(self, gpt):
        """Attaching a bank must not perturb adapter-less traffic: the
        slot-0 zero adapter gives an exactly-zero delta."""
        prompts = _prompts([5, 3], seed=2)
        sp = [_sp(4)] * 2
        bare = InferenceEngine(gpt, num_slots=2, max_length=64,
                               decode_block=2)
        want = [h.tokens for h in bare.generate_many(prompts, sp)]
        banked = self._engine(gpt, _bank(gpt, 2), num_slots=2)
        got = [h.tokens for h in banked.generate_many(prompts, sp)]
        assert got == want

    def test_zero_recompiles_across_mixes_and_hot_load(self, gpt):
        """After one mixed warmup wave: permuted mixes, base-only
        waves, AND a hot adapter reload all replay the same programs —
        python trace counters and the jit compile counter both flat."""
        bank = _bank(gpt, 2)
        eng = self._engine(gpt, bank)
        prompts = _prompts([4, 5, 6, 4], seed=3)
        sp = [_sp(4)] * 4
        eng.generate_many(prompts, sp,
                          adapter_ids=[None, 'ad0', 'ad1', 'ad0'])
        traces = dict(eng.stats()['traces'])
        compiles0 = obs.get_registry().value('paddle_jit_compiles_total')
        eng.generate_many(prompts, sp,
                          adapter_ids=['ad1', None, 'ad0', 'ad1'])
        eng.generate_many(prompts, sp)                    # base-only
        bank.load('ad0', make_adapter_factors(bank, 77), version=2)
        bank.load('ad2', make_adapter_factors(bank, 78))  # fresh slot
        eng.generate_many(prompts, sp,
                          adapter_ids=['ad2', 'ad0', 'ad2', None])
        assert eng.stats()['traces'] == traces
        assert obs.get_registry().value('paddle_jit_compiles_total') \
            == compiles0

    def test_submit_validation(self, gpt):
        bare = InferenceEngine(gpt, num_slots=2, max_length=64)
        with pytest.raises(ValueError, match='adapter_bank'):
            bare.submit([1, 2, 3], _sp(2), adapter_id='ad0')
        banked = self._engine(gpt, _bank(gpt, 1))
        with pytest.raises(AdapterUnavailable):
            banked.submit([1, 2, 3], _sp(2), adapter_id='ghost')

    def test_pins_released_and_stats_exposed(self, gpt):
        bank = _bank(gpt, 2)
        eng = self._engine(gpt, bank)
        prompts = _prompts([4, 5], seed=4)
        hs = eng.generate_many(prompts, [_sp(3)] * 2,
                               adapter_ids=['ad0', 'ad1'])
        assert all(h.status == FINISHED for h in hs)
        assert all(h.adapter_version == 1 for h in hs)
        st = eng.stats()['adapters']
        assert st['pinned'] == 0               # every pin unwound
        assert set(st['resident']) == {'ad0', 'ad1'}
        reg = obs.get_registry()
        assert reg.value('paddle_adapter_requests_total',
                         adapter='ad0') >= 1

    def test_hot_publish_in_flight_v1_bit_exact_new_requests_v2(
            self, gpt, tmp_path):
        """The engine-level hot-swap/rollback contract: publish v2
        while a v1 request is mid-decode — the v1 request finishes with
        EXACTLY the tokens a pure-v1 run gives; a request submitted
        after the publish decodes under v2."""
        prompt = _prompts([6], seed=5)[0]
        f1 = _factors(AdapterBank(gpt, capacity=2, rank=4), 1)
        f2 = _factors(AdapterBank(gpt, capacity=2, rank=4), 2)
        # pure-v1 / pure-v2 references
        tok = {}
        for name, f in (('v1', f1), ('v2', f2)):
            b = AdapterBank(gpt, capacity=2, rank=4)
            b.load('ad0', f)
            tok[name] = self._engine(gpt, b).generate_many(
                [prompt], [_sp(8)], adapter_ids='ad0')[0].tokens
        assert tok['v1'] != tok['v2']
        # live run: v1 decoding when v2 publishes
        bank = AdapterBank(gpt, capacity=3, rank=4,
                           store_dir=str(tmp_path))
        v1 = bank.publish('ad0', f1)
        eng = self._engine(gpt, bank)
        h1 = eng.submit(prompt, _sp(8), adapter_id='ad0')
        for _ in range(3):
            eng.step()                         # h1 is mid-decode on v1
        assert h1.adapter_version == v1
        v2 = bank.publish('ad0', f2)
        h2 = eng.submit(prompt, _sp(8), adapter_id='ad0')
        eng.run()
        assert h1.status == FINISHED and h2.status == FINISHED
        assert h1.tokens == tok['v1']          # bit-exact through swap
        assert h2.adapter_version == v2
        assert h2.tokens == tok['v2']
        assert eng.stats()['adapters']['pinned'] == 0

    def test_chunked_prefill_composes(self, gpt):
        prompts = _prompts([17, 9], seed=6)
        sp = [_sp(4)] * 2
        want = [h.tokens for h in self._engine(
            gpt, _bank(gpt, 2)).generate_many(
                prompts, sp, adapter_ids=['ad0', 'ad1'])]
        chunked = self._engine(gpt, _bank(gpt, 2),
                               prefill_chunk_tokens=8)
        got = [h.tokens for h in chunked.generate_many(
            prompts, sp, adapter_ids=['ad0', 'ad1'])]
        assert got == want

    def test_speculative_decode_composes(self, gpt):
        """Spec decode with adapters: the scope wraps ONLY the target
        verify, so greedy outputs stay bit-identical to plain decode
        under the same adapter (the spec contract, adapter or not)."""
        paddle.seed(11)
        draft = GPTForCausalLM(GPTConfig.tiny(num_hidden_layers=1)).eval()
        prompts = _prompts([5, 7], seed=7)
        sp = [_sp(6)] * 2
        want = [h.tokens for h in self._engine(
            gpt, _bank(gpt, 2), num_slots=2).generate_many(
                prompts, sp, adapter_ids=['ad0', None])]
        spec = self._engine(gpt, _bank(gpt, 2), num_slots=2,
                            draft_model=draft, num_draft_tokens=3)
        got = [h.tokens for h in spec.generate_many(
            prompts, sp, adapter_ids=['ad0', None])]
        assert got == want

    def test_paged_pool_composes(self, gpt):
        prompts = _prompts([6, 9], seed=8)
        sp = [_sp(4)] * 2
        want = [h.tokens for h in self._engine(
            gpt, _bank(gpt, 2), num_slots=2).generate_many(
                prompts, sp, adapter_ids=['ad0', 'ad1'])]
        paged = self._engine(gpt, _bank(gpt, 2), num_slots=2,
                             kv_page_size=8, kv_pages=24)
        got = [h.tokens for h in paged.generate_many(
            prompts, sp, adapter_ids=['ad0', 'ad1'])]
        assert got == want


# ---------------------------------------------------------------------------
# prefix cache: (adapter_id, version) namespacing
# ---------------------------------------------------------------------------

class TestPrefixCacheAdapterScope:
    """The satellite-2 regression: two tenants with IDENTICAL prompts
    but different adapters must never share a cached prefix (the KV
    under an adapter carries that adapter's deltas); base requests keep
    deduplicating exactly as before."""

    def test_identical_prompts_different_adapters_never_share(self, gpt):
        prompt = _prompts([12], seed=9)[0]
        sp = _sp(4)
        # alone references (no cache in play)
        ref = {}
        for aid in (None, 'ad0', 'ad1'):
            eng = InferenceEngine(gpt, num_slots=2, max_length=64,
                                  decode_block=2,
                                  adapter_bank=_bank(gpt, 2))
            ref[aid] = eng.generate_many([prompt], [sp],
                                         adapter_ids=aid)[0].tokens
        # slots = 2x requests so wave-2 admissions never reclaim the
        # retained wave-1 prefixes under pool pressure
        eng = InferenceEngine(gpt, num_slots=6, max_length=64,
                              decode_block=2, prefix_cache=0.9,
                              adapter_bank=_bank(gpt, 2))
        ids = [None, 'ad0', 'ad1']
        # wave 1 seeds three namespaces: same tokens, ZERO cross-hits
        hs = eng.generate_many([prompt] * 3, [sp] * 3, adapter_ids=ids)
        st = eng.stats()['prefix_cache']
        assert st['hits'] == 0
        # the base namespace is the root trie itself; each (adapter,
        # version) pair got its OWN root
        assert st['namespaces'] >= 2
        assert [h.tokens for h in hs] == [ref[a] for a in ids]
        # wave 2 hits WITHIN each namespace — outputs still bit-exact
        hs2 = eng.generate_many([prompt] * 3, [sp] * 3, adapter_ids=ids)
        assert eng.stats()['prefix_cache']['hits'] >= 3
        assert [h.tokens for h in hs2] == [ref[a] for a in ids]

    def test_base_requests_still_dedupe_on_a_banked_engine(self, gpt):
        prompt = _prompts([12], seed=10)[0]
        eng = InferenceEngine(gpt, num_slots=2, max_length=64,
                              decode_block=2, prefix_cache=0.9,
                              adapter_bank=_bank(gpt, 1))
        h1 = eng.generate_many([prompt], [_sp(3)])[0]
        h2 = eng.generate_many([prompt], [_sp(3)])[0]
        assert h1.tokens == h2.tokens == _ref_generate(gpt, prompt, 3)
        assert eng.stats()['prefix_cache']['hits'] >= 1

    def test_publish_changes_namespace_old_kv_unreachable(self, gpt,
                                                          tmp_path):
        """Version rides the namespace key: after a publish, new
        requests get a FRESH namespace — v1's cached prefixes (KV with
        v1's deltas baked in) can never serve a v2 request."""
        prompt = _prompts([12], seed=11)[0]
        bank = AdapterBank(gpt, capacity=3, rank=4,
                           store_dir=str(tmp_path))
        bank.publish('ad0', _factors(bank, 1))
        eng = InferenceEngine(gpt, num_slots=4, max_length=64,
                              decode_block=2, prefix_cache=0.9,
                              adapter_bank=bank)
        eng.generate_many([prompt], [_sp(3)], adapter_ids='ad0')
        eng.generate_many([prompt], [_sp(3)], adapter_ids='ad0')
        hits1 = eng.stats()['prefix_cache']['hits']
        assert hits1 >= 1                      # same version dedupes
        bank.publish('ad0', _factors(bank, 2))
        h = eng.generate_many([prompt], [_sp(3)], adapter_ids='ad0')[0]
        assert eng.stats()['prefix_cache']['hits'] == hits1  # no hit
        # and the output is v2's, proving no v1 KV leaked in
        b2 = AdapterBank(gpt, capacity=2, rank=4)
        b2.load('ad0', _factors(b2, 2))
        want = InferenceEngine(gpt, num_slots=2, max_length=64,
                               decode_block=2, adapter_bank=b2
                               ).generate_many(
            [prompt], [_sp(3)], adapter_ids='ad0')[0].tokens
        assert h.tokens == want


# ---------------------------------------------------------------------------
# tenancy + router: adapter defaults, typed fast-fail
# ---------------------------------------------------------------------------

class TestRouterTenancyAdapters:
    def test_parse_tenant_spec_adapter_field(self):
        reg = parse_tenant_spec(
            'paid:priority=high,adapter=ad0;free:priority=low')
        assert reg.get('paid').adapter == 'ad0'
        assert reg.get('free').adapter is None
        assert reg.get('paid').spec()['adapter'] == 'ad0'
        # round-trip: a spec()'d tenant re-parses to the same adapter
        reparsed = TenantRegistry({'paid': reg.get('paid').spec()})
        assert reparsed.get('paid').adapter == 'ad0'

    def _router(self, gpt, tenants, bank=None, n=1):
        kw = dict(num_slots=2, max_length=64, decode_block=2)
        if bank is not None:
            kw['adapter_bank'] = bank
        return Router(ReplicaSet(gpt, n, **kw), tenants=tenants)

    def test_tenant_default_adapter_applies_and_overrides(self, gpt):
        bank = _bank(gpt, 2)
        router = self._router(
            gpt, 'paid:priority=high,adapter=ad0;free:priority=low',
            bank=bank)
        p = _prompts([4], seed=12)[0]
        h_dflt = router.submit(p, _sp(3), tenant='paid')
        h_ovr = router.submit(p, _sp(3), tenant='paid',
                              adapter_id='ad1')
        h_base = router.submit(p, _sp(3), tenant='free')
        router.run()
        assert h_dflt.adapter_id == 'ad0'
        assert h_ovr.adapter_id == 'ad1'
        assert h_base.adapter_id is None
        assert all(h.status == FINISHED for h in (h_dflt, h_ovr, h_base))
        assert h_dflt.adapter_version == 1
        assert h_dflt.tokens != h_base.tokens

    def test_unknown_adapter_fast_fails_typed_before_qos(self, gpt):
        """The satellite-1 contract: a request for a missing adapter
        rejects synchronously with reason='adapter_unavailable' and
        consumes NO rate-bucket token and NO model work."""
        bank = _bank(gpt, 1)
        tenants = TenantRegistry(
            {'metered': {'rate': 1.0, 'burst': 1.0, 'adapter': 'ghost'}})
        router = self._router(gpt, tenants, bank=bank)
        p = _prompts([4], seed=13)[0]
        prefills0 = router._by_id[0].engine._counts['prefills']
        with pytest.raises(AdmissionRejected) as ei:
            router.submit(p, _sp(2), tenant='metered')
        assert ei.value.reason == 'adapter_unavailable'
        assert router._by_id[0].engine._counts['prefills'] == prefills0
        assert router.stats()['rejected'] == {'adapter_unavailable': 1}
        # the reject spent no rate token: an available-adapter request
        # from the same 1-token bucket still goes through
        h = router.submit(p, _sp(2), tenant='metered', adapter_id='ad0')
        router.run()
        assert h.status == FINISHED

    def test_bank_less_fleet_rejects_adapter_requests(self, gpt):
        router = self._router(gpt, 'paid:priority=high')
        with pytest.raises(AdmissionRejected) as ei:
            router.submit(_prompts([4], seed=14)[0], _sp(2),
                          tenant='paid', adapter_id='ad0')
        assert ei.value.reason == 'adapter_unavailable'


# ---------------------------------------------------------------------------
# loadgen: per-tenant adapter mixes
# ---------------------------------------------------------------------------

class TestLoadgenAdapterMixes:
    def _trace(self, seed=42):
        return loadgen.make_trace(
            loadgen.PoissonSchedule(12.0), 6.0, seed=seed,
            prompt_lengths=loadgen.FixedLength(6),
            tenants=[
                loadgen.TenantClass('paid', 2.0, 0, adapters=(
                    ('ad0', 2.0), ('ad1', 1.0), (None, 1.0))),
                loadgen.TenantClass('free', 1.0, 2)],
            vocab_size=96)

    def test_mix_validation(self):
        with pytest.raises(ValueError, match='adapter mix'):
            loadgen.TenantClass('t', adapters=(('ad0', 0.0),))
        with pytest.raises(ValueError, match='adapter mix'):
            loadgen.TenantClass('t', adapters=(('ad0',),))

    def test_traces_bit_identical_from_one_seed(self):
        t1, t2 = self._trace(), self._trace()
        assert t1 == t2
        assert self._trace(seed=43) != t1

    def test_mix_draws_only_for_declaring_tenants(self):
        trace = self._trace()
        paid = [r for r in trace if r.tenant == 'paid']
        free = [r for r in trace if r.tenant == 'free']
        assert paid and free
        assert all(r.adapter is None for r in free)
        drawn = {r.adapter for r in paid}
        assert {'ad0', 'ad1'} <= drawn         # mix actually mixes
        # weights bite: ad0 (weight 2) drawn more than ad1 (weight 1)
        n0 = sum(1 for r in paid if r.adapter == 'ad0')
        n1 = sum(1 for r in paid if r.adapter == 'ad1')
        assert n0 > n1

    def test_trace_stats_by_adapter(self):
        st = loadgen.trace_stats(self._trace())
        by = st['by_adapter']
        assert set(by) <= {'ad0', 'ad1'}
        paid_with = sum(1 for r in self._trace()
                        if r.adapter is not None)
        assert sum(by.values()) == paid_with

    def test_replay_threads_adapter_through_router(self, gpt):
        """End-to-end: a mixed trace replays against a bank-attached
        fleet — every adapter request decodes under its adapter, zero
        drops."""
        bank = _bank(gpt, 2)
        trace = loadgen.make_trace(
            loadgen.PoissonSchedule(6.0), 2.0, seed=5,
            prompt_lengths=loadgen.FixedLength(5),
            output_lengths=loadgen.FixedLength(3),
            tenants=[loadgen.TenantClass('t', 1.0, 1, adapters=(
                ('ad0', 1.0), (None, 1.0)))],
            vocab_size=96)
        router = Router(ReplicaSet(gpt, 1, num_slots=2, max_length=64,
                                   decode_block=2, adapter_bank=bank))
        rep = loadgen.LoadReplayer(router, trace, time_scale=0.05,
                                   max_wall_s=60.0)
        report = rep.run().report(slo_ttft_s=30.0)
        assert report['completed'] == len(trace)
        assert report['dropped'] == 0
        served = obs.get_registry().value(
            'paddle_adapter_requests_total', adapter='ad0')
        want = sum(1 for r in trace if r.adapter == 'ad0')
        assert want == 0 or served >= want


# ---------------------------------------------------------------------------
# bench guards (slow tier): the adapter_ab acceptance numbers
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_adapter_ab_guard():
    """Runs the real bench at reduced scale and asserts the ISSUE-19
    acceptance fields: per-tenant parity vs alone, zero recompiles
    across mixes + a hot swap, and mixed >= sequential throughput
    structure present."""
    import bench
    out = bench.adapter_ab(num_adapters=2, requests_per_group=2,
                           num_slots=3, max_length=64, decode_block=4,
                           max_new=6, trials=1)
    assert out['parity'] is True
    assert out['recompiles_after_warmup'] == 0
    assert out['jit_compiles_delta'] == 0
    assert out['hot_swap_outputs_changed'] is True
    assert out['hot_swap_others_bit_exact'] is True
    assert out['tokens_per_sec_mixed'] > 0
    assert out['tokens_per_sec_sequential'] > 0


@pytest.mark.slow
def test_bench_adapters_smoke_guard():
    import bench
    out = bench.adapters_smoke(duration_s=2.0, rate=6.0, seed=77,
                               time_scale=0.1)
    assert out['trace_deterministic'] is True
    assert out['dropped'] == 0
    assert out['completed'] == out['offered']
    assert out['adapters_served'] >= 1
