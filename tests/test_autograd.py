"""DyGraph autograd: tape backward vs jax.grad ground truth."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle


def npt(x):
    return np.asarray(x.numpy())


def test_simple_chain():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x + x).sum()
    y.backward()
    assert np.allclose(npt(x.grad), [5.0, 7.0])  # 2x + 1


def test_matmul_grad_vs_jax():
    a = np.random.randn(3, 4).astype('float32')
    b = np.random.randn(4, 2).astype('float32')
    pa = paddle.to_tensor(a, stop_gradient=False)
    pb = paddle.to_tensor(b, stop_gradient=False)
    loss = paddle.matmul(pa, pb).sum()
    loss.backward()
    ga, gb = jax.grad(lambda x, y: (x @ y).sum(), argnums=(0, 1))(
        jnp.asarray(a), jnp.asarray(b))
    assert np.allclose(npt(pa.grad), ga, atol=1e-5)
    assert np.allclose(npt(pb.grad), gb, atol=1e-5)


def test_broadcast_grad():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    b = paddle.to_tensor([10.0, 20.0], stop_gradient=False)
    (x + b).sum().backward()
    assert np.allclose(npt(x.grad), np.ones((2, 2)))
    assert np.allclose(npt(b.grad), [2.0, 2.0])  # summed over broadcast dim


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    assert np.allclose(npt(x.grad), [5.0])


def test_reuse_in_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x  # used twice below
    z = (y + y).sum()
    z.backward()
    assert np.allclose(npt(x.grad), [8.0])  # d/dx 2x^2


def test_no_grad_blocks_tape():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_stop_gradient_cuts_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * 3
    d = y.detach()
    z = (d * x).sum()
    z.backward()
    assert np.allclose(npt(x.grad), [6.0])  # only through the second factor


def test_multi_output_op_grad():
    x = paddle.to_tensor([[4.0, 1.0, 3.0]], stop_gradient=False)
    vals, idx = paddle.topk(x, 2, axis=1)
    vals.sum().backward()
    assert np.allclose(npt(x.grad), [[1.0, 0.0, 1.0]])


def test_getitem_grad():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    (x[1:] * 2).sum().backward()
    assert np.allclose(npt(x.grad), [0.0, 2.0, 2.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x ** 3
    (g,) = paddle.grad(y.sum(), [x])
    assert np.allclose(npt(g), [12.0])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_nonscalar_backward_needs_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    try:
        y.backward()
        raised = False
    except RuntimeError:
        raised = True
    assert raised
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 0.5]))
    assert np.allclose(npt(x.grad), [2.0, 1.0])


def test_double_backward_raises_without_retain():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()  # second time ok with retained graph from first call
    assert np.allclose(npt(x.grad), [4.0])


def test_deep_chain_and_mixed_ops():
    x = paddle.to_tensor(np.linspace(0.1, 1, 8).astype('float32'),
                         stop_gradient=False)
    y = paddle.tanh(paddle.exp(x * 0.5) + paddle.log(x))
    loss = (y * y).mean()
    loss.backward()

    def ref(v):
        yy = jnp.tanh(jnp.exp(v * 0.5) + jnp.log(v))
        return (yy * yy).mean()
    g = jax.grad(ref)(jnp.asarray(npt(x)))
    assert np.allclose(npt(x.grad), g, atol=1e-5)


def test_grad_through_reshape_transpose_concat():
    a = np.random.randn(2, 6).astype('float32')
    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.reshape(x, [3, 4]).transpose([1, 0])
    z = paddle.concat([y, y], axis=0)
    z.sum().backward()
    assert np.allclose(npt(x.grad), 2 * np.ones((2, 6)))
