// Parallel checkpoint shard writer/reader (upstream analogue: the fleet
// checkpoint sharding utilities under
// python/paddle/distributed/fleet/utils/ + the C++ save/load kernels in
// paddle/fluid/framework/io/).
//
// TPU-native design: checkpoints are pytrees of host numpy arrays (see
// paddle_tpu/serialization.py). The npz container is single-stream and
// pays zip CRC per byte; here each shard file is written/read by its own
// thread as raw bytes — the manifest (JSON, python-side) records
// name -> (shard, offset, size, dtype, shape). No framing in the binary
// files, so reads are plain pread-style sequential fread into
// preallocated buffers.
//
// Error contract: returns 0 on success, or (index of the failing file
// + 1). Each thread touches only its own file, so the first error per
// file wins and no partial state is shared.

#include <cstdio>
#include <cstring>
#include <atomic>
#include <thread>
#include <vector>

namespace {

// one shard file = arrays [starts[f], starts[f+1]) written back-to-back
void write_one(const char* path, const void* const* ptrs,
               const unsigned long long* sizes, long long lo, long long hi,
               std::atomic<int>* err, int fidx) {
  FILE* fp = std::fopen(path, "wb");
  if (!fp) {
    err->store(fidx + 1);
    return;
  }
  for (long long i = lo; i < hi; ++i) {
    if (sizes[i] == 0) continue;
    if (std::fwrite(ptrs[i], 1, sizes[i], fp) != sizes[i]) {
      err->store(fidx + 1);
      std::fclose(fp);
      return;
    }
  }
  if (std::fclose(fp) != 0) err->store(fidx + 1);
}

void read_one(const char* path, void* const* ptrs,
              const unsigned long long* sizes, long long lo, long long hi,
              std::atomic<int>* err, int fidx) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) {
    err->store(fidx + 1);
    return;
  }
  for (long long i = lo; i < hi; ++i) {
    if (sizes[i] == 0) continue;
    if (std::fread(ptrs[i], 1, sizes[i], fp) != sizes[i]) {
      err->store(fidx + 1);
      std::fclose(fp);
      return;
    }
  }
  std::fclose(fp);
}

}  // namespace

extern "C" {

int ckpt_write(const char** paths, int n_files, const long long* starts,
               const void* const* ptrs, const unsigned long long* sizes) {
  std::atomic<int> err{0};
  std::vector<std::thread> threads;
  threads.reserve(n_files);
  for (int f = 0; f < n_files; ++f)
    threads.emplace_back(write_one, paths[f], ptrs, sizes, starts[f],
                         starts[f + 1], &err, f);
  for (auto& t : threads) t.join();
  return err.load();
}

int ckpt_read(const char** paths, int n_files, const long long* starts,
              void* const* ptrs, const unsigned long long* sizes) {
  std::atomic<int> err{0};
  std::vector<std::thread> threads;
  threads.reserve(n_files);
  for (int f = 0; f < n_files; ++f)
    threads.emplace_back(read_one, paths[f], ptrs, sizes, starts[f],
                         starts[f + 1], &err, f);
  for (auto& t : threads) t.join();
  return err.load();
}

}  // extern "C"
