// Host staging runtime for the input pipeline (upstream analogue:
// paddle/fluid's pinned-memory allocator + DataLoader C++ workers).
//
// Two pieces, bound from Python via ctypes (no pybind11 in this image):
//
// 1. Staging ring buffer: N fixed-size, 64-byte-aligned host slots
//    recycled producer->consumer with a mutex/condvar handshake. The
//    DataLoader assembles each device batch directly into one slot (no
//    per-sample numpy concatenation), then hands the contiguous buffer
//    to the device transfer and recycles the slot.
//
// 2. Decoder pool: a fixed team of C++ threads executing sample-decode
//    jobs (strided memcpy, u8->f32 normalize) WITHOUT the Python GIL —
//    the Python side only enqueues pointers. This is where multi-core
//    decode parallelism comes from (Python threads would serialize on
//    the GIL for the copy loop).
//
// Build: g++ -O3 -fPIC -shared (see paddle_tpu/io/native.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// staging ring buffer
// ---------------------------------------------------------------------------

struct Staging {
  std::vector<uint8_t*> slots;
  size_t slot_bytes;
  std::deque<int> free_q;     // slots available to producers
  std::deque<int> ready_q;    // committed slots awaiting the consumer
  std::vector<size_t> committed_bytes;
  std::mutex mu;
  std::condition_variable cv_free, cv_ready;
  bool closed = false;
};

void* staging_create(size_t slot_bytes, int n_slots) {
  auto* s = new Staging();
  s->slot_bytes = slot_bytes;
  s->slots.resize(n_slots);
  s->committed_bytes.resize(n_slots, 0);
  for (int i = 0; i < n_slots; ++i) {
    void* p = nullptr;
    if (posix_memalign(&p, 64, slot_bytes) != 0) {
      for (int j = 0; j < i; ++j) free(s->slots[j]);
      delete s;
      return nullptr;
    }
    s->slots[i] = static_cast<uint8_t*>(p);
    s->free_q.push_back(i);
  }
  return s;
}

// producer: block until a free slot; returns slot index or -1 if closed
int staging_acquire(void* h) {
  auto* s = static_cast<Staging*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_free.wait(lk, [&] { return !s->free_q.empty() || s->closed; });
  if (s->free_q.empty()) return -1;
  int idx = s->free_q.front();
  s->free_q.pop_front();
  return idx;
}

uint8_t* staging_ptr(void* h, int slot) {
  return static_cast<Staging*>(h)->slots[slot];
}

size_t staging_slot_bytes(void* h) {
  return static_cast<Staging*>(h)->slot_bytes;
}

void staging_commit(void* h, int slot, size_t nbytes) {
  auto* s = static_cast<Staging*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->committed_bytes[slot] = nbytes;
    s->ready_q.push_back(slot);
  }
  s->cv_ready.notify_one();
}

// consumer: block until a committed slot; returns index or -1 if closed+empty
int staging_pop(void* h, size_t* nbytes_out) {
  auto* s = static_cast<Staging*>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_ready.wait(lk, [&] { return !s->ready_q.empty() || s->closed; });
  if (s->ready_q.empty()) return -1;
  int idx = s->ready_q.front();
  s->ready_q.pop_front();
  if (nbytes_out) *nbytes_out = s->committed_bytes[idx];
  return idx;
}

void staging_release(void* h, int slot) {
  auto* s = static_cast<Staging*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->free_q.push_back(slot);
  }
  s->cv_free.notify_one();
}

void staging_close(void* h) {
  auto* s = static_cast<Staging*>(h);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->closed = true;
  }
  s->cv_free.notify_all();
  s->cv_ready.notify_all();
}

void staging_destroy(void* h) {
  auto* s = static_cast<Staging*>(h);
  staging_close(h);
  for (auto* p : s->slots) free(p);
  delete s;
}

// ---------------------------------------------------------------------------
// decoder pool
// ---------------------------------------------------------------------------

enum JobKind : int {
  JOB_MEMCPY = 0,       // raw copy src -> dst
  JOB_U8_TO_F32 = 1,    // dst_f32[i] = (src_u8[i] - shift) * scale
  JOB_F32_SCALE = 2,    // dst_f32[i] = (src_f32[i] - shift) * scale
};

// a ticket is a counter + condvar the submitter blocks on (a bare atomic
// would force pool_ticket_wait to busy-spin, pinning a host core for the
// whole batch assembly and competing with the decoder threads)
struct Ticket {
  std::mutex mu;
  std::condition_variable cv;
  int count = 0;
};

struct Job {
  int kind;
  const uint8_t* src;
  uint8_t* dst;
  size_t n;            // element count
  float scale, shift;
  Ticket* done_flag;
};

struct Pool {
  std::vector<std::thread> threads;
  std::deque<Job> q;
  std::mutex mu;
  std::condition_variable cv;
  bool stop = false;
};

static void run_job(const Job& j) {
  switch (j.kind) {
    case JOB_MEMCPY:
      memcpy(j.dst, j.src, j.n);
      break;
    case JOB_U8_TO_F32: {
      const uint8_t* s = j.src;
      float* d = reinterpret_cast<float*>(j.dst);
      for (size_t i = 0; i < j.n; ++i)
        d[i] = (static_cast<float>(s[i]) - j.shift) * j.scale;
      break;
    }
    case JOB_F32_SCALE: {
      const float* s = reinterpret_cast<const float*>(j.src);
      float* d = reinterpret_cast<float*>(j.dst);
      for (size_t i = 0; i < j.n; ++i) d[i] = (s[i] - j.shift) * j.scale;
      break;
    }
  }
  if (j.done_flag) {
    // notify while still holding the mutex: the waiter may destroy the
    // ticket the moment its predicate is satisfied, so an unlocked
    // notify_all could touch freed memory
    std::lock_guard<std::mutex> lk(j.done_flag->mu);
    ++j.done_flag->count;
    j.done_flag->cv.notify_all();
  }
}

void* pool_create(int n_threads) {
  auto* p = new Pool();
  for (int i = 0; i < n_threads; ++i) {
    p->threads.emplace_back([p] {
      for (;;) {
        Job j;
        {
          std::unique_lock<std::mutex> lk(p->mu);
          p->cv.wait(lk, [&] { return !p->q.empty() || p->stop; });
          if (p->q.empty()) return;
          j = p->q.front();
          p->q.pop_front();
        }
        run_job(j);
      }
    });
  }
  return p;
}

void* pool_ticket_create() { return new Ticket(); }
int pool_ticket_count(void* t) {
  auto* tk = static_cast<Ticket*>(t);
  std::lock_guard<std::mutex> lk(tk->mu);
  return tk->count;
}
void pool_ticket_destroy(void* t) {
  delete static_cast<Ticket*>(t);
}

void pool_submit(void* h, int kind, const void* src, void* dst, size_t n,
                 float scale, float shift, void* ticket) {
  auto* p = static_cast<Pool*>(h);
  Job j{kind, static_cast<const uint8_t*>(src), static_cast<uint8_t*>(dst),
        n, scale, shift, static_cast<Ticket*>(ticket)};
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->q.push_back(j);
  }
  p->cv.notify_one();
}

// block (in C++, GIL released by ctypes) until `count` jobs completed
void pool_ticket_wait(void* t, int count) {
  auto* tk = static_cast<Ticket*>(t);
  std::unique_lock<std::mutex> lk(tk->mu);
  tk->cv.wait(lk, [&] { return tk->count >= count; });
}

void pool_destroy(void* h) {
  auto* p = static_cast<Pool*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv.notify_all();
  for (auto& t : p->threads) t.join();
  delete p;
}

}  // extern "C"
