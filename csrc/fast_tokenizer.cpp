// Fast BPE tokenizer core (upstream analogue: PaddleNLP's
// faster_tokenizer C++ lib). Implements the hot path of
// paddle_tpu.nlp.tokenizer.BPETokenizer.tokenize — whitespace split,
// per-word greedy lowest-rank merge loop, vocab lookup with byte
// fallback — as a ctypes-bound shared library so batch encoding does not
// pay the Python interpreter per merge step.
//
// Semantics mirror the python implementation exactly:
//   symbols = utf8_codepoints(word) + ["</w>"]
//   repeat: merge the adjacent pair with the LOWEST merge rank
//   per final symbol: vocab id, else per-byte <0xNN> fallback, else unk.
//
// Build: g++ -O3 -fPIC -shared (see paddle_tpu/nlp/fast_tokenizer.py).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<std::string, std::string>& p) const {
    std::hash<std::string> h;
    return h(p.first) * 1000003u ^ h(p.second);
  }
};

struct BPE {
  std::unordered_map<std::string, int> vocab;
  std::unordered_map<std::pair<std::string, std::string>, int, PairHash>
      ranks;
  int unk_id = 0;
  std::string word_end = "</w>";
};

const int kNoRank = INT32_MAX;

// split a UTF-8 string into code points (mirrors python list(word))
void utf8_split(const std::string& s, std::vector<std::string>* out) {
  size_t i = 0;
  while (i < s.size()) {
    unsigned char c = s[i];
    size_t n = 1;
    if ((c & 0x80) == 0) n = 1;
    else if ((c & 0xE0) == 0xC0) n = 2;
    else if ((c & 0xF0) == 0xE0) n = 3;
    else if ((c & 0xF8) == 0xF0) n = 4;
    if (i + n > s.size()) n = 1;  // truncated sequence: take the byte
    out->push_back(s.substr(i, n));
    i += n;
  }
}

void emit_symbol(const BPE* t, const std::string& sym,
                 std::vector<int>* out) {
  auto it = t->vocab.find(sym);
  if (it != t->vocab.end()) {
    out->push_back(it->second);
    return;
  }
  // byte fallback: <0xNN> per utf-8 byte
  char buf[8];
  for (unsigned char b : sym) {
    snprintf(buf, sizeof(buf), "<0x%02X>", b);
    auto bit = t->vocab.find(buf);
    out->push_back(bit != t->vocab.end() ? bit->second : t->unk_id);
  }
}

void bpe_word(const BPE* t, const std::string& word,
              std::vector<int>* out) {
  std::vector<std::string> syms;
  utf8_split(word, &syms);
  syms.push_back(t->word_end);
  while (syms.size() > 1) {
    int best_rank = kNoRank;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < syms.size(); ++i) {
      auto it = t->ranks.find({syms[i], syms[i + 1]});
      if (it != t->ranks.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == kNoRank) break;
    syms[best_i] += syms[best_i + 1];
    syms.erase(syms.begin() + best_i + 1);
  }
  for (const auto& s : syms) emit_symbol(t, s, out);
}

}  // namespace

extern "C" {

void* bpe_create() { return new BPE(); }

void bpe_destroy(void* h) { delete static_cast<BPE*>(h); }

void bpe_set_unk(void* h, int unk_id) {
  static_cast<BPE*>(h)->unk_id = unk_id;
}

void bpe_add_token(void* h, const char* tok, int id) {
  static_cast<BPE*>(h)->vocab.emplace(tok, id);
}

void bpe_add_merge(void* h, const char* a, const char* b, int rank) {
  static_cast<BPE*>(h)->ranks.emplace(std::make_pair(a, b), rank);
}

// Encode whitespace-split `text` of `text_len` bytes (explicit length:
// embedded NUL bytes are word bytes, matching python str semantics — the
// python wrapper pre-normalizes unicode whitespace to ' ' so only ASCII
// separators appear here); writes up to max_out ids, returns the number
// of ids the full encoding needs (caller re-calls with a larger buffer
// when the return value exceeds max_out).
int bpe_encode(void* h, const char* text, int32_t text_len, int32_t* out_ids,
               int max_out) {
  const BPE* t = static_cast<BPE*>(h);
  std::vector<int> ids;
  std::string word;
  for (int32_t i = 0; i <= text_len; ++i) {
    char c = (i < text_len) ? text[i] : ' ';
    if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
        c == '\v') {
      if (!word.empty()) {
        bpe_word(t, word, &ids);
        word.clear();
      }
    } else {
      word.push_back(c);
    }
  }
  int n = static_cast<int>(ids.size());
  for (int i = 0; i < n && i < max_out; ++i) out_ids[i] = ids[i];
  return n;
}

}  // extern "C"
