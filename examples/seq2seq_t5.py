"""Seq2seq with T5: learn a toy transduction (reverse the input
sequence), then decode it back with the encoder-decoder generate path.

    python examples/seq2seq_t5.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nlp import T5Config, T5ForConditionalGeneration


def main(steps=300):
    paddle.seed(0)
    cfg = T5Config.tiny(vocab_size=64, d_model=96, d_ff=192, num_layers=2,
                        num_heads=4)
    model = T5ForConditionalGeneration(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())

    # a FINITE dataset of 64 fixed pairs: sequence reversal on fresh
    # random data every step needs far more capacity/steps than a demo
    # (an equal-size torch T5 plateaus at ln(V) too); 64 fixed pairs
    # train to ~0.9 exact-token accuracy in 300 steps
    rng = np.random.RandomState(0)
    data = rng.randint(2, cfg.vocab_size, (64, 8))  # ids 0/1 reserved

    loss = None
    for step in range(steps):
        src = data[rng.randint(0, len(data), 16)]
        tgt = src[:, ::-1].copy()
        loss, _ = model(input_ids=src, labels=tgt)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 50 == 0:
            print(f'step {step:4d}  loss {float(loss.numpy()):.4f}')

    model.eval()
    src = data[:8]
    tgt = src[:, ::-1]
    out, _ = model.generate(src, max_new_tokens=src.shape[1],
                            decode_strategy='greedy_search',
                            eos_token_id=-1)
    acc = float((out.numpy() == tgt).mean())
    print(f'reverse accuracy: {acc:.3f}')
    print('src:', src[0].tolist())
    print('out:', out.numpy()[0].tolist())
    return float(loss.numpy()), acc


if __name__ == '__main__':
    main()
