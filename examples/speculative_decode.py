"""Speculative decoding with a distilled draft: train a 1-layer draft to
mimic a 2-layer target on its own greedy continuations, then decode with
draft-and-verify — same tokens as plain greedy, fewer target forwards.

    python examples/speculative_decode.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM


def main(distill_steps=150):
    paddle.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=64, hidden_size=64,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=4, intermediate_size=128)
    target = LlamaForCausalLM(cfg).eval()
    paddle.seed(1)
    draft_cfg = LlamaConfig.tiny(vocab_size=64, hidden_size=64,
                                 num_hidden_layers=1,
                                 num_attention_heads=4,
                                 num_key_value_heads=4,
                                 intermediate_size=128)
    draft = LlamaForCausalLM(draft_cfg)

    # distill: the draft learns the target's next-token distribution on
    # random contexts (soft cross-entropy on the target's logits)
    opt = paddle.optimizer.AdamW(learning_rate=2e-3,
                                 parameters=draft.parameters())
    rng = np.random.RandomState(0)
    for step in range(distill_steps):
        ids = rng.randint(3, cfg.vocab_size, (8, 12))
        with paddle.no_grad():
            t_logits = target(ids)
        d_logits = draft(ids)
        teacher = F.softmax(t_logits.reshape([-1, cfg.vocab_size]), axis=-1)
        loss = -paddle.sum(
            teacher * F.log_softmax(
                d_logits.reshape([-1, cfg.vocab_size]), axis=-1),
            axis=-1).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 50 == 0:
            print(f'distill step {step:4d}  loss {float(loss.numpy()):.4f}')

    draft.eval()
    prompt = rng.randint(3, cfg.vocab_size, (1, 6))
    plain, _ = target.generate(prompt, max_new_tokens=24,
                               decode_strategy='greedy_search',
                               eos_token_id=-1)
    out, stats = target.speculative_generate(
        draft, prompt, max_new_tokens=24, num_draft_tokens=4,
        eos_token_id=-1)
    assert (out.numpy() == plain.numpy()).all(), 'speculative != greedy'
    print('tokens        :', out.numpy()[0].tolist())
    print('rounds        :', stats['rounds'], '(plain greedy: 24 forwards)')
    print('forwards saved:', stats['target_forwards_saved'])
    print(f"acceptance    : {stats['acceptance_rate']:.2f}")
    return stats


if __name__ == '__main__':
    main()
