"""BERT sequence-classification finetune (BASELINE config #2 shape).

    python examples/finetune_bert.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.nlp import BertConfig, BertForSequenceClassification


def main(steps=40, n_classes=2):
    paddle.seed(0)
    cfg = BertConfig(vocab_size=1000, hidden_size=64,
                     num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=128, max_position_embeddings=64)
    model = BertForSequenceClassification(cfg, num_classes=n_classes)
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())

    rng = np.random.RandomState(0)
    # synthetic "sentiment": the leading marker token decides the class
    def make_batch(n=16):
        ids = rng.randint(10, 1000, (n, 32))
        labels = rng.randint(0, 2, n)
        ids[:, 0] = np.where(labels == 1, 7, 8)
        return ids, labels

    for i in range(steps):
        ids, labels = make_batch()
        logits = model(paddle.to_tensor(ids))
        loss = F.cross_entropy(logits, paddle.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if i % 10 == 0 or i == steps - 1:
            acc = (logits.numpy().argmax(1) == labels).mean()
            print(f'step {i:3d}  loss {float(loss.numpy()):.4f}  '
                  f'acc {acc:.2f}')
    return acc


if __name__ == '__main__':
    main()
