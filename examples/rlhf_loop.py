"""RLHF-shaped post-training loop: the trainer→serving circle, live.

The composed scenario the whole stack exists for: a serving fleet
(Router over 2 replicas) generates rollouts, a reward function scores
them, the trainer fine-tunes on the best (best-of-n / rejection
sampling), and the fresh weights HOT-SWAP back into the running
replicas — versioned, sha256-manifested, health-gated, zero downtime,
zero dropped requests, zero XLA recompiles. The next iteration's
rollouts come from the weights the previous iteration just learned.

The toy objective: reward = fraction of response tokens equal to a
TARGET token. A few best-of-n iterations visibly push the policy
toward emitting it — watch `mean_reward` climb while
`paddle_router_weight_version` ticks up in lockstep on both replicas:

    JAX_PLATFORMS=cpu python examples/rlhf_loop.py
    JAX_PLATFORMS=cpu python examples/rlhf_loop.py --metrics-port 8000
    # curl :8000/healthz   -> weight_versions per replica
    # curl :8000/goodput   -> weight_swap as a first-class category
"""
import argparse
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import debug, observability
from paddle_tpu.jit import TrainStep
from paddle_tpu.loop import RolloutLoop, response_lm_loss
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (ReplicaSet, ReplicaUpdater, Router,
                                WeightPublisher, WeightStore)

TARGET = 7          # the token the reward function loves
VOCAB = 32
PROMPT_LEN = 6
MAX_NEW = 8


def reward_fn(prompt, response):
    """Fraction of response tokens equal to TARGET."""
    if not response:
        return 0.0
    return float(np.mean([t == TARGET for t in response]))


def make_prompt_fn(n_per_iter):
    def prompt_fn(i):
        rng = np.random.RandomState(1000 + i)
        return [rng.randint(1, VOCAB, (PROMPT_LEN,)).tolist()
                for _ in range(n_per_iter)]
    return prompt_fn


def main(iters=8, store_dir=None, publish_every=2, metrics_port=None):
    paddle.seed(0)
    server = None
    if metrics_port is not None:
        server = observability.start_server(metrics_port)
        print(f'observability endpoint at {server.url}')
    if store_dir is None:
        store_dir = tempfile.mkdtemp(prefix='rlhf_weights_')

    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=48,
                    num_hidden_layers=1, num_attention_heads=4,
                    intermediate_size=96, max_position_embeddings=64,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    train_model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=train_model.parameters())
    train_step = TrainStep(train_model, response_lm_loss(VOCAB), opt)

    # the storage hop: versioned, sha256-manifested weight snapshots
    store = WeightStore(store_dir, keep_versions=4)
    publisher = WeightPublisher(train_model, store,
                                interval_steps=publish_every)
    v1 = publisher.publish(step=0)      # the fleet's starting weights

    # the serving fleet: its OWN model instance, aligned to v1 through
    # the store — the only coupling between trainer and servers
    serve_model = GPTForCausalLM(cfg).eval()
    serve_model.set_state_dict(store.load(v1))
    router = Router(ReplicaSet(serve_model, 2, num_slots=4,
                               max_length=64, decode_block=4,
                               weight_version=v1))
    updater = ReplicaUpdater(router, store)

    loop = RolloutLoop(
        train_step=train_step, router=router, publisher=publisher,
        updater=updater, prompt_fn=make_prompt_fn(8),
        reward_fn=reward_fn, rollouts_per_iter=8, keep_best=4,
        max_new_tokens=MAX_NEW, temperature=1.0, train_passes=2)

    print(f'weight store at {store.directory}; fleet starts at v{v1}')
    for _ in range(iters):
        s = loop.iteration()
        swap = s['swap']
        print(f"iter {s['iteration']}: mean_reward={s['mean_reward']:.3f}"
              f" best={s['best_reward']:.3f} loss={s['loss']:.3f}"
              f" step={s['global_step']}"
              + (f" published=v{s['published_version']}"
                 if s['published_version'] else '')
              + (f" swap->v{swap['version']} ({swap['outcome']})"
                 if swap else '')
              + f" fleet=v{s['fleet_version']}")

    print()
    print(f'fleet converged on v{updater.fleet_version} '
          f'(store: {store.stats()["versions"]})')
    r = router.stats()
    print(f"router: {r['completed']} completed / {r['failed']} failed; "
          f"replica versions "
          f"{[p['weight_version'] for p in r['replicas']]}")
    print()
    print(observability.get_ledger().report_text())
    if server is not None:
        server.stop()
    return loop.history


if __name__ == '__main__':
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--iters', type=int, default=8)
    ap.add_argument('--store-dir', default=None,
                    help='weight store directory (default: tmpdir)')
    ap.add_argument('--publish-every', type=int, default=2,
                    help='trainer steps between published versions')
    ap.add_argument('--metrics-port', type=int, default=None)
    args = ap.parse_args()
    main(iters=args.iters, store_dir=args.store_dir,
         publish_every=args.publish_every,
         metrics_port=args.metrics_port)
