"""Text generation with the KV-cache decode path: greedy, sampling,
and beam search through GenerationMixin.

    python examples/generate.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      intermediate_size=128, max_position_embeddings=64)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = paddle.to_tensor(np.array([[5, 17, 31]]))

    for mode, kw in [
        ('greedy', dict(decode_strategy='greedy_search')),
        ('top-p sampling', dict(decode_strategy='sampling', top_p=0.9,
                                temperature=0.8, seed=0)),
        ('beam search', dict(decode_strategy='beam_search', num_beams=3)),
    ]:
        out = model.generate(prompt, max_new_tokens=8, **kw)
        ids = out[0] if isinstance(out, tuple) else out
        print(f'{mode:16s} ->', np.asarray(ids.numpy())[0].tolist())


if __name__ == '__main__':
    main()
