"""Hybrid-parallel training over a device mesh (dp x mp), the pod-scale
path of the BASELINE GPT/Llama configs.

On one host this runs over whatever chips are visible; to try the
multi-chip schedule without hardware:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/train_distributed.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM


def main(steps=10):
    import jax
    n = jax.device_count()
    mp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // mp
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {'dp_degree': dp, 'mp_degree': mp,
                               'pp_degree': 1, 'sep_degree': 1}
    strategy.sharding = True          # ZeRO over dp
    strategy.sharding_configs = {'stage': 2}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      intermediate_size=128, max_position_embeddings=32,
                      tensor_parallel=(mp > 1))
    model = LlamaForCausalLM(cfg)
    fleet.distributed_model(model)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())
    step = fleet.DistTrainStep(
        model,
        # next-token objective: logits at t predict token t+1
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, cfg.vocab_size]),
            labels[:, 1:].reshape([-1])),
        opt, strategy=strategy)

    rng = np.random.RandomState(0)
    for i in range(steps):
        ids = rng.randint(0, cfg.vocab_size, (2 * dp, 32))
        loss = step(paddle.to_tensor(ids), paddle.to_tensor(ids))
        print(f'step {i}  loss {float(loss.numpy()):.4f}  '
              f'(mesh dp={dp} mp={mp})')
    return float(loss.numpy())


if __name__ == '__main__':
    main()
