"""Hybrid-parallel training over a device mesh (dp x mp), the pod-scale
path of the BASELINE GPT/Llama configs.

On one host this runs over whatever chips are visible; to try the
multi-chip schedule without hardware:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/train_distributed.py

`--elastic` drives the same hybrid step through
`resilience.ElasticTrainLoop` and simulates losing half the hosts
mid-run: the run checkpoints, re-meshes over the survivors (dp absorbs
the change, mp stays fixed), reshards, and continues — then grows back.
See `examples/train_gpt.py --elastic` for the single-model flavor and
the README "Elastic training" section for the semantics.
"""
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM


def _build(strategy, mp):
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      intermediate_size=128, max_position_embeddings=32,
                      tensor_parallel=(mp > 1))
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        # next-token objective: logits at t predict token t+1
        return F.cross_entropy(
            logits[:, :-1].reshape([-1, cfg.vocab_size]),
            labels[:, 1:].reshape([-1]))
    return model, opt, loss_fn


def main(steps=10, elastic=False):
    import jax
    n = jax.device_count()
    mp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // mp
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {'dp_degree': dp, 'mp_degree': mp,
                               'pp_degree': 1, 'sep_degree': 1}
    strategy.sharding = True          # ZeRO over dp
    strategy.sharding_configs = {'stage': 2}
    fleet.init(is_collective=True, strategy=strategy)
    model, opt, loss_fn = _build(strategy, mp)

    if elastic:
        import tempfile

        from paddle_tpu.resilience import ElasticTrainLoop
        devs = list(jax.devices())
        world = {'n': n}
        loop = ElasticTrainLoop(
            model, loss_fn, opt, strategy=strategy,
            ckpt_dir=tempfile.mkdtemp(prefix='dist_elastic_ckpt_'),
            device_source=lambda: devs[:world['n']])
        # global batch fixed at 2*dp rows: divisible by every dp the
        # shrink/grow visits, so the trajectory is preserved up to
        # reduction-order ulps
        batch = 2 * dp
        can = dp % 2 == 0 and batch % (n // 2) == 0
        rng = np.random.RandomState(0)
        for i in range(steps):
            if can and i == steps // 2 and world['n'] == n:
                world['n'] = n // 2   # half the hosts preempted
                print(f'--- host loss: re-meshing over {n // 2} '
                      f'devices ---')
            ids = rng.randint(0, 256, (batch, 32))
            loss = loop.step(paddle.to_tensor(ids), paddle.to_tensor(ids))
            print(f'step {i}  loss {float(loss.numpy()):.4f}  '
                  f'(mesh {dict(loop.mesh.shape)})')
        return float(loss.numpy())

    fleet.distributed_model(model)
    step = fleet.DistTrainStep(model, loss_fn, opt, strategy=strategy)
    rng = np.random.RandomState(0)
    for i in range(steps):
        ids = rng.randint(0, 256, (2 * dp, 32))
        loss = step(paddle.to_tensor(ids), paddle.to_tensor(ids))
        print(f'step {i}  loss {float(loss.numpy()):.4f}  '
              f'(mesh dp={dp} mp={mp})')
    return float(loss.numpy())


if __name__ == '__main__':
    main(elastic='--elastic' in sys.argv)
