"""Minimal GPT pretraining loop (the framework's flagship path).

Runs on any backend; on TPU the same script is the single-chip version
of the BASELINE GPT-3 config — scale hidden/layers and add
fleet.DistTrainStep for the pod version (see examples/train_distributed.py).

Fault tolerance is on by default: the step rides a FaultTolerantStep
(NaN/spike rollback + skip), SIGTERM/SIGINT force a final checkpoint,
and `--resume auto` continues from the latest committed step:

    python examples/train_gpt.py --ckpt-dir /tmp/gpt_ckpt
    # ... preempted ...
    python examples/train_gpt.py --ckpt-dir /tmp/gpt_ckpt --resume auto

Live introspection: `--metrics-port 8000` serves /metrics (Prometheus),
/healthz (hang-aware liveness), /summary, /events, /trace, and
/programs (per-program XLA cost attribution) from a daemon thread while
the loop trains:

    python examples/train_gpt.py --metrics-port 8000 &
    curl localhost:8000/healthz; curl localhost:8000/metrics

Elastic demo: `--elastic` trains through an ElasticTrainLoop over the
fleet mesh and simulates a mid-run host loss (shrink to half the
devices at 1/3 of the run) and capacity return (grow back at 2/3) —
checkpoint, re-mesh, reshard, resume, with `topology_change` events,
flight bundles, and the /summary resize history. Global batch is
preserved across the resizes, so the loss trajectory matches the
fixed-topology run to reduction-order ulps:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/train_gpt.py --elastic
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import debug, observability, resilience
from paddle_tpu.jit import TrainStep
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.utils.checkpoint import CheckpointManager


def main(steps=80, vocab=512, seq=64, batch=8, ckpt_dir=None, resume=None,
         ckpt_interval=20, metrics_port=None, program_store=None):
    paddle.seed(0)
    if program_store:
        # persistent program store: executables serialize next to the
        # checkpoints, so `--resume auto` restarts pay zero XLA compiles
        from paddle_tpu import programs
        programs.configure(program_store)
        pre = programs.get_store().preload(match='train')
        print(f'program store at {program_store}: '
              f"{pre['loaded']} warm program(s) in {pre['seconds']:.2f}s"
              + (f", {pre['rejected']} rejected" if pre['rejected']
                 else ''))
    server = None
    if metrics_port is not None:
        server = observability.start_server(metrics_port)
        print(f'observability endpoint at {server.url} '
              f'(/metrics /healthz /summary /events /trace /programs)')
    cfg = GPTConfig(vocab_size=vocab, hidden_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=256, max_position_embeddings=seq)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    raw_step = TrainStep(
        model,
        # next-token objective: logits at t predict token t+1
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, vocab]),
            labels[:, 1:].reshape([-1])),
        opt)
    # NaN/spike steps roll back and the batch is skipped; transient PjRt
    # errors are retried with backoff
    step = resilience.FaultTolerantStep(
        raw_step, retry_policy=resilience.RetryPolicy())

    mgr = None
    start = 0
    if ckpt_dir:
        mgr = CheckpointManager(ckpt_dir, backend='npz',
                                save_interval_steps=ckpt_interval)
        if resume == 'auto' and mgr.latest_step() is not None:
            tree = mgr.restore()
            model.set_state_dict(tree['model'])
            raw_step._opt_state = tree['opt']
            raw_step._n_calls = int(np.asarray(tree['n_calls']))
            start = int(np.asarray(tree['step']))
            print(f'resumed from step {start}')

    def save(i, force=False):
        if mgr is None:
            return
        mgr.save(i, {'model': dict(model.state_dict()),
                     'opt': raw_step._opt_state,
                     'n_calls': raw_step._n_calls, 'step': i}, force=force)

    # toy corpus: next-token-predictable arithmetic sequences; keyed by
    # step index so a resumed run replays the identical batch stream
    def batch_ids(i):
        r = np.random.RandomState(i)
        start_tok = r.randint(0, vocab - seq, (batch, 1))
        return (start_tok + np.arange(seq)) % vocab

    # per-step telemetry into the shared observability registry:
    # steps/sec, tokens/sec, loss, device-memory watermark
    telemetry = observability.StepTelemetry()
    loss = None
    with resilience.PreemptionHandler() as preempt:
        for i in range(start, steps):
            ids = batch_ids(i)
            loss = step(ids, ids)
            telemetry.step(loss=float(loss.numpy()), tokens=batch * seq)
            if not step.last_step_skipped:
                save(i + 1)
            if i % 10 == 0 or i == steps - 1:
                print(f'step {i:3d}  loss {float(loss.numpy()):.4f}')
            if preempt.requested:
                save(i + 1, force=True)
                print(f'preempted at step {i}: checkpoint forced, '
                      f'exiting cleanly')
                break
    # one call reports dispatch hit-rate, jit compiles, comm/offload
    # bytes, throughput, memory — and now resilience/checkpoint activity
    print(debug.observability_summary())
    # the exit ledger: where every wall-clock second of this run went
    print(observability.get_ledger().report_text())
    return float(loss.numpy()) if loss is not None else float('nan')


def main_elastic(steps=60, vocab=512, seq=64, batch=8, ckpt_dir=None,
                 resume=None, ckpt_interval=10, metrics_port=None):
    """--elastic: the same pretraining loop through ElasticTrainLoop,
    with a simulated shrink (half the devices "preempted") at steps/3
    and a grow-back at 2*steps/3. Run it under a forced multi-device
    CPU mesh to watch both transitions on /summary."""
    import tempfile

    import jax

    paddle.seed(0)
    server = None
    if metrics_port is not None:
        server = observability.start_server(metrics_port)
        print(f'observability endpoint at {server.url}')
    devs = list(jax.devices())
    n = len(devs)
    world = {'n': n}
    can_resize = n >= 2 and batch % n == 0 and batch % (n // 2) == 0
    if not can_resize:
        print(f'({n} device(s), batch {batch}: running elastic-wrapped '
              f'without simulated resizes)')
    cfg = GPTConfig(vocab_size=vocab, hidden_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=256, max_position_embeddings=seq)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    loop = resilience.ElasticTrainLoop(
        model,
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, vocab]),
            labels[:, 1:].reshape([-1])),
        opt,
        ckpt_dir=ckpt_dir or tempfile.mkdtemp(prefix='gpt_elastic_ckpt_'),
        ckpt_interval=ckpt_interval,
        device_source=lambda: devs[:world['n']],
        resume=resume)

    def batch_ids(i):
        r = np.random.RandomState(i)
        start_tok = r.randint(0, vocab - seq, (batch, 1))
        return (start_tok + np.arange(seq)) % vocab

    shrink_at, grow_at = steps // 3, (2 * steps) // 3
    loss = None
    with resilience.PreemptionHandler() as preempt:
        while loop.global_step < steps:
            i = loop.global_step
            if can_resize and i == shrink_at and world['n'] == n:
                world['n'] = n // 2
                print(f'--- simulating host loss: {n} -> {n // 2} '
                      f'devices ---')
            if can_resize and i == grow_at and world['n'] < n:
                world['n'] = n
                print(f'--- capacity returned: {n // 2} -> {n} '
                      f'devices ---')
            ids = batch_ids(i)
            loss = loop.step(ids, ids)
            if i % 10 == 0 or i == steps - 1:
                print(f'step {i:3d}  loss {float(loss.numpy()):.4f}  '
                      f'mesh {dict(loop.mesh.shape)}')
            if preempt.requested:
                loop.save(force=True)
                print(f'preempted at step {i}: checkpoint forced, '
                      f'exiting cleanly')
                break
    print(debug.observability_summary())
    # the exit ledger: where every wall-clock second of this run went
    print(observability.get_ledger().report_text())
    return float(loss.numpy()) if loss is not None else float('nan')


if __name__ == '__main__':
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--steps', type=int, default=80)
    p.add_argument('--ckpt-dir', default=None,
                   help='directory for step-indexed training checkpoints')
    p.add_argument('--resume', choices=['auto'], default=None,
                   help="'auto': continue from the latest committed step")
    p.add_argument('--ckpt-interval', type=int, default=20)
    p.add_argument('--metrics-port', type=int, default=None,
                   help='serve the HTTP observability endpoint '
                        '(/metrics /healthz /summary /events /trace '
                        '/programs) on this port while training')
    p.add_argument('--elastic', action='store_true',
                   help='train through ElasticTrainLoop with a simulated '
                        'mid-run shrink/grow of the device mesh')
    p.add_argument('--program-store', default=None,
                   help='persistent program-store directory: compiled '
                        'executables survive restarts, so a resumed run '
                        'pays zero XLA compiles (pair with --resume auto)')
    args = p.parse_args()
    if args.elastic:
        if args.program_store:
            from paddle_tpu import programs
            programs.configure(args.program_store)
        main_elastic(steps=args.steps, ckpt_dir=args.ckpt_dir,
                     resume=args.resume, ckpt_interval=args.ckpt_interval,
                     metrics_port=args.metrics_port)
    else:
        main(steps=args.steps, ckpt_dir=args.ckpt_dir, resume=args.resume,
             ckpt_interval=args.ckpt_interval,
             metrics_port=args.metrics_port,
             program_store=args.program_store)
