"""Minimal GPT pretraining loop (the framework's flagship path).

Runs on any backend; on TPU the same script is the single-chip version
of the BASELINE GPT-3 config — scale hidden/layers and add
fleet.DistTrainStep for the pod version (see examples/train_distributed.py).

    python examples/train_gpt.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import debug, observability
from paddle_tpu.jit import TrainStep
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM


def main(steps=80, vocab=512, seq=64, batch=8):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=256, max_position_embeddings=seq)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(
        model,
        # next-token objective: logits at t predict token t+1
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, vocab]),
            labels[:, 1:].reshape([-1])),
        opt)

    rng = np.random.RandomState(0)
    # toy corpus: next-token-predictable arithmetic sequences
    def batch_ids():
        start = rng.randint(0, vocab - seq, (batch, 1))
        return (start + np.arange(seq)) % vocab

    # per-step telemetry into the shared observability registry:
    # steps/sec, tokens/sec, loss, device-memory watermark
    telemetry = observability.StepTelemetry()
    for i in range(steps):
        ids = batch_ids()
        loss = step(ids, ids)
        telemetry.step(loss=float(loss.numpy()), tokens=batch * seq)
        if i % 10 == 0 or i == steps - 1:
            print(f'step {i:3d}  loss {float(loss.numpy()):.4f}')
    # one call reports dispatch hit-rate, jit compiles, comm/offload
    # bytes, throughput, and memory — all from the single registry
    print(debug.observability_summary())
    return float(loss.numpy())


if __name__ == '__main__':
    main()
