"""Minimal GPT pretraining loop (the framework's flagship path).

Runs on any backend; on TPU the same script is the single-chip version
of the BASELINE GPT-3 config — scale hidden/layers and add
fleet.DistTrainStep for the pod version (see examples/train_distributed.py).

Fault tolerance is on by default: the step rides a FaultTolerantStep
(NaN/spike rollback + skip), SIGTERM/SIGINT force a final checkpoint,
and `--resume auto` continues from the latest committed step:

    python examples/train_gpt.py --ckpt-dir /tmp/gpt_ckpt
    # ... preempted ...
    python examples/train_gpt.py --ckpt-dir /tmp/gpt_ckpt --resume auto

Live introspection: `--metrics-port 8000` serves /metrics (Prometheus),
/healthz (hang-aware liveness), /summary, /events, /trace, and
/programs (per-program XLA cost attribution) from a daemon thread while
the loop trains:

    python examples/train_gpt.py --metrics-port 8000 &
    curl localhost:8000/healthz; curl localhost:8000/metrics
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import debug, observability, resilience
from paddle_tpu.jit import TrainStep
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.utils.checkpoint import CheckpointManager


def main(steps=80, vocab=512, seq=64, batch=8, ckpt_dir=None, resume=None,
         ckpt_interval=20, metrics_port=None):
    paddle.seed(0)
    server = None
    if metrics_port is not None:
        server = observability.start_server(metrics_port)
        print(f'observability endpoint at {server.url} '
              f'(/metrics /healthz /summary /events /trace /programs)')
    cfg = GPTConfig(vocab_size=vocab, hidden_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=256, max_position_embeddings=seq)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    raw_step = TrainStep(
        model,
        # next-token objective: logits at t predict token t+1
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, vocab]),
            labels[:, 1:].reshape([-1])),
        opt)
    # NaN/spike steps roll back and the batch is skipped; transient PjRt
    # errors are retried with backoff
    step = resilience.FaultTolerantStep(
        raw_step, retry_policy=resilience.RetryPolicy())

    mgr = None
    start = 0
    if ckpt_dir:
        mgr = CheckpointManager(ckpt_dir, backend='npz',
                                save_interval_steps=ckpt_interval)
        if resume == 'auto' and mgr.latest_step() is not None:
            tree = mgr.restore()
            model.set_state_dict(tree['model'])
            raw_step._opt_state = tree['opt']
            raw_step._n_calls = int(np.asarray(tree['n_calls']))
            start = int(np.asarray(tree['step']))
            print(f'resumed from step {start}')

    def save(i, force=False):
        if mgr is None:
            return
        mgr.save(i, {'model': dict(model.state_dict()),
                     'opt': raw_step._opt_state,
                     'n_calls': raw_step._n_calls, 'step': i}, force=force)

    # toy corpus: next-token-predictable arithmetic sequences; keyed by
    # step index so a resumed run replays the identical batch stream
    def batch_ids(i):
        r = np.random.RandomState(i)
        start_tok = r.randint(0, vocab - seq, (batch, 1))
        return (start_tok + np.arange(seq)) % vocab

    # per-step telemetry into the shared observability registry:
    # steps/sec, tokens/sec, loss, device-memory watermark
    telemetry = observability.StepTelemetry()
    loss = None
    with resilience.PreemptionHandler() as preempt:
        for i in range(start, steps):
            ids = batch_ids(i)
            loss = step(ids, ids)
            telemetry.step(loss=float(loss.numpy()), tokens=batch * seq)
            if not step.last_step_skipped:
                save(i + 1)
            if i % 10 == 0 or i == steps - 1:
                print(f'step {i:3d}  loss {float(loss.numpy()):.4f}')
            if preempt.requested:
                save(i + 1, force=True)
                print(f'preempted at step {i}: checkpoint forced, '
                      f'exiting cleanly')
                break
    # one call reports dispatch hit-rate, jit compiles, comm/offload
    # bytes, throughput, memory — and now resilience/checkpoint activity
    print(debug.observability_summary())
    return float(loss.numpy()) if loss is not None else float('nan')


if __name__ == '__main__':
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--steps', type=int, default=80)
    p.add_argument('--ckpt-dir', default=None,
                   help='directory for step-indexed training checkpoints')
    p.add_argument('--resume', choices=['auto'], default=None,
                   help="'auto': continue from the latest committed step")
    p.add_argument('--ckpt-interval', type=int, default=20)
    p.add_argument('--metrics-port', type=int, default=None,
                   help='serve the HTTP observability endpoint '
                        '(/metrics /healthz /summary /events /trace '
                        '/programs) on this port while training')
    args = p.parse_args()
    main(steps=args.steps, ckpt_dir=args.ckpt_dir, resume=args.resume,
         ckpt_interval=args.ckpt_interval, metrics_port=args.metrics_port)
