"""Continuous-batching inference with paddle_tpu.serving: submit a
mixed-length burst of requests against the tiny GPT, stream one of them
token by token, and print the engine's serving telemetry.

    python examples/serve_gpt.py

Replicated serving: `--replicas N` puts the health-checked `Router` in
front of N engine replicas (least-outstanding-tokens placement,
per-replica circuit breakers, mid-flight failover), and `--tenants`
adds per-tenant QoS — priority classes, token-bucket rates, concurrency
caps — with fast-fail load shedding past `--shed-queue-depth`:

    python examples/serve_gpt.py --replicas 2 \\
        --tenants 'paid:priority=high;free:priority=low,rate=5,concurrency=2' \\
        --shed-queue-depth 8

Tenant spec format: `name:key=value,...;name2:...` with keys
priority (high|normal|low), rate (requests/sec), burst, concurrency.

Serving latency stack: `--prefix-cache` retains finished prompts' KV in
a radix cache so shared prefixes (system prompts) prefill once,
`--prefill-chunk N` splits long prefills into N-token chunks that
interleave with decode rounds (bounded TTFT for the short requests
behind them), and `--draft-model tiny|self` enables per-slot
speculative decoding (greedy outputs stay bit-identical):

    python examples/serve_gpt.py --prefix-cache 0.5 --prefill-chunk 16 \\
        --draft-model tiny

Multi-tenant adapter serving: `--adapters N` packs N LoRA adapters
into a device-resident `AdapterBank` and round-robins requests across
base + every adapter — one compiled decode block serves the whole
heterogeneous mix (zero recompiles across any adapter assignment).
Combine with `--tenants` using the `adapter=` spec key to pin a
tenant's default adapter:

    python examples/serve_gpt.py --adapters 3
    python examples/serve_gpt.py --replicas 2 --adapters 2 \\
        --tenants 'paid:priority=high,adapter=ad0;free:priority=low'

Live introspection: `--metrics-port 8000` serves the HTTP observability
endpoint while the engine decodes — /metrics (Prometheus, incl. the
paddle_serving_* and paddle_router_* families), /healthz (decode-round
liveness + per-replica degraded states), /trace (queue/prefill/decode
spans with per-request trace ids), /programs (decode block + per-bucket
prefill FLOPs/bytes attribution):

    python examples/serve_gpt.py --metrics-port 8000
"""
import argparse

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import debug, observability
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import (AdmissionRejected, InferenceEngine,
                                ReplicaSet, Router, SamplingParams)


def _make_requests(model, num_requests):
    rng = np.random.RandomState(0)
    out = []
    for i in range(num_requests):
        prompt = rng.randint(1, model.config.vocab_size,
                             (int(rng.randint(3, 20)),)).tolist()
        params = SamplingParams(
            max_new_tokens=int(rng.randint(4, 16)),
            # mix greedy and seeded sampling in the SAME batch
            strategy='sampling' if i % 3 == 2 else 'greedy_search',
            temperature=1.2, top_k=40, seed=i, eos_token_id=-1)
        out.append((prompt, params))
    return out


def _adapter_for(i, adapter_ids):
    return adapter_ids[i % len(adapter_ids)] if adapter_ids else None


def _serve_single(model, requests, engine_kwargs=None, adapter_ids=None):
    # one engine = one slot pool + scheduler; 4 slots serve the whole
    # burst by admitting queued requests as running ones retire
    engine = InferenceEngine(model, num_slots=4, max_length=64,
                             decode_block=4, **(engine_kwargs or {}))
    handles = [engine.submit(p, sp,
                             adapter_id=_adapter_for(i, adapter_ids))
               for i, (p, sp) in enumerate(requests)]

    # stream the FIRST request token-by-token; the engine advances every
    # running request under the hood on each step
    print('streaming request 0:', end=' ', flush=True)
    for tok in handles[0].stream():
        print(tok, end=' ', flush=True)
    print()

    engine.run()   # drain the rest
    for h in handles:
        ad = f' adapter={h.adapter_id}' if h.adapter_id else ''
        print(f'req {h.request_id}: {h.status.lower():8s} '
              f'prompt={len(h.prompt_tokens):2d} tokens={h.tokens}{ad}')

    stats = engine.stats()
    print(f"\n{stats['completed']}/{stats['submitted']} served, "
          f"{stats['tokens']} tokens, {stats['decode_rounds']} decode "
          f"rounds, prefill buckets traced: "
          f"{sorted(k for k in stats['traces'] if k.startswith('prefill'))}")
    if 'prefix_cache' in stats:
        px = stats['prefix_cache']
        print(f"prefix cache: {px['hits']} hits / {px['misses']} misses, "
              f"{px['tokens_reused']} tokens reused, "
              f"{px['retained_slots']}/{px['budget_slots']} retained")
    if stats['chunk_rounds']:
        print(f"chunked prefill: {stats['chunked_prefills']} prompts in "
              f"{stats['chunk_rounds']} chunk rounds")
    if 'spec' in stats:
        sp = stats['spec']
        print(f"speculation (k={sp['k']}): {sp['rounds']} rounds, "
              f"acceptance {sp['acceptance_rate']:.1%}")
    if 'adapters' in stats:
        ad = stats['adapters']
        resident = ', '.join(f"{k}(v{v['version']})"
                             for k, v in ad['resident'].items())
        print(f"adapter bank: {len(ad['resident'])}/{ad['capacity']} "
              f"slots resident [{resident}], rank {ad['rank']}, "
              f"{ad['pinned']} pinned")
    return handles


def _serve_routed(model, requests, replicas, tenants, shed_queue_depth,
                  engine_kwargs=None, adapter_ids=None):
    router = Router(
        ReplicaSet(model, replicas, num_slots=4, max_length=64,
                   decode_block=4, **(engine_kwargs or {})),
        tenants=tenants, shed_queue_depth=shed_queue_depth)
    tenant_names = (sorted(router.tenants.tenants()) or ['default'])
    handles, rejected = [], 0
    for i, (p, sp) in enumerate(requests):
        tenant = tenant_names[i % len(tenant_names)]
        try:
            # explicit per-request adapter; unset, the tenant's
            # `adapter=` spec default applies inside the router
            handles.append((tenant, router.submit(
                p, sp, tenant=tenant,
                adapter_id=_adapter_for(i, adapter_ids))))
        except AdmissionRejected as exc:
            rejected += 1
            print(f'req {i}: REJECTED for {exc.tenant!r} '
                  f'({exc.reason}, retry after {exc.retry_after_s})')
    router.run()
    for tenant, h in handles:
        ad = f' adapter={h.adapter_id}' if h.adapter_id else ''
        print(f'req {h.router_id}: {h.status.lower():8s} '
              f'tenant={tenant:8s} replica={h.replica_id} '
              f'failovers={h.failovers} tokens={h.tokens}{ad}')
    st = router.stats()
    print(f"\nrouter: {st['completed']}/{st['accepted']} completed, "
          f"{st['failed']} failed, {rejected} rejected at admission")
    for row in st['replicas']:
        states = ','.join(row['health_states']) or 'healthy'
        print(f"  replica {row['id']}: breaker {row['breaker']}  "
              f"{states}  {row['active_slots']} active slots")
    return [h for _, h in handles]


def main(num_requests=10, metrics_port=None, replicas=1, tenants=None,
         shed_queue_depth=None, program_store=None, prefix_cache=None,
         prefill_chunk=None, draft_model=None, adapters=None):
    paddle.seed(0)
    if program_store:
        # persistent program store: a cold replica loads its decode/
        # prefill executables instead of compiling them (the engine
        # preloads automatically; /healthz holds `warming` meanwhile)
        from paddle_tpu import programs
        programs.configure(program_store)
        print(f'program store at {program_store} '
              f'({programs.get_store().disk_entries()} entries on disk)')
    if metrics_port is not None:
        server = observability.start_server(metrics_port)
        print(f'observability endpoint at {server.url}')
    model = GPTForCausalLM(GPTConfig.tiny()).eval()
    requests = _make_requests(model, num_requests)

    engine_kwargs = {}
    if prefix_cache is not None:
        engine_kwargs['prefix_cache'] = prefix_cache
    if prefill_chunk is not None:
        engine_kwargs['prefill_chunk_tokens'] = prefill_chunk
    if draft_model is not None:
        if draft_model == 'self':
            draft = model      # oracle draft: exercises the machinery
        else:
            paddle.seed(1)
            draft = GPTForCausalLM(
                GPTConfig.tiny(num_hidden_layers=1)).eval()
        engine_kwargs['draft_model'] = draft
        engine_kwargs['num_draft_tokens'] = 3
    adapter_ids = None
    if adapters:
        from paddle_tpu.serving import AdapterBank, make_adapter_factors
        # one packed bank serves every replica in-process: requests
        # round-robin base (None) + ad0..adN-1 through ONE compiled
        # decode block — the heterogeneous-mix demo
        bank = AdapterBank(model, capacity=adapters + 1, rank=4)
        for i in range(adapters):
            bank.load(f'ad{i}', make_adapter_factors(bank, seed=i + 1))
        engine_kwargs['adapter_bank'] = bank
        adapter_ids = [None] + [f'ad{i}' for i in range(adapters)]
        print(f'adapter bank: {adapters} LoRA adapters resident '
              f'(rank {bank.rank}, targets {len(bank.sites)} sites)')

    if replicas > 1 or tenants or shed_queue_depth is not None:
        handles = _serve_routed(model, requests, max(replicas, 1),
                                tenants, shed_queue_depth,
                                engine_kwargs=engine_kwargs,
                                adapter_ids=adapter_ids)
    else:
        handles = _serve_single(model, requests,
                                engine_kwargs=engine_kwargs,
                                adapter_ids=adapter_ids)
    print(debug.observability_summary())
    # the exit ledger: where every wall-clock second of this run went
    print(observability.get_ledger().report_text())
    return handles


if __name__ == '__main__':
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--num-requests', type=int, default=10)
    p.add_argument('--replicas', type=int, default=1,
                   help='serve through a Router over this many engine '
                        'replicas (health checks, failover, breakers)')
    p.add_argument('--tenants', type=str, default=None,
                   help="per-tenant QoS spec, e.g. 'paid:priority=high;"
                        "free:priority=low,rate=5,concurrency=2'")
    p.add_argument('--shed-queue-depth', type=int, default=None,
                   help='queue depth past which low-priority work is '
                        'shed with a typed AdmissionRejected')
    p.add_argument('--prefix-cache', type=float, nargs='?', const=0.5,
                   default=None, metavar='FRACTION',
                   help='radix prefix cache over the slot pool: shared '
                        'prompt prefixes prefill once (optional pool '
                        'fraction for the retention budget, default 0.5)')
    p.add_argument('--prefill-chunk', type=int, default=None,
                   metavar='TOKENS',
                   help='chunked prefill: prompts longer than this '
                        'prefill across decode rounds instead of '
                        'stalling in-flight requests')
    p.add_argument('--draft-model', choices=('tiny', 'self'),
                   default=None,
                   help='per-slot speculative decoding: "tiny" builds a '
                        '1-layer draft, "self" uses the target as an '
                        'oracle draft (high acceptance demo)')
    p.add_argument('--adapters', type=int, default=None, metavar='N',
                   help='pack N LoRA adapters into a device-resident '
                        'bank and round-robin requests across base + '
                        'every adapter (one decode program, any mix); '
                        'tenant specs may pin defaults via adapter=adK')
    p.add_argument('--metrics-port', type=int, default=None,
                   help='serve the HTTP observability endpoint on this '
                        'port while decoding')
    p.add_argument('--program-store', default=None,
                   help='persistent program-store directory: a restarted '
                        'replica loads its compiled decode/prefill '
                        'programs instead of recompiling them')
    args = p.parse_args()
    main(num_requests=args.num_requests, metrics_port=args.metrics_port,
         replicas=args.replicas, tenants=args.tenants,
         shed_queue_depth=args.shed_queue_depth,
         program_store=args.program_store,
         prefix_cache=args.prefix_cache,
         prefill_chunk=args.prefill_chunk,
         draft_model=args.draft_model, adapters=args.adapters)
