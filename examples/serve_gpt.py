"""Continuous-batching inference with paddle_tpu.serving: submit a
mixed-length burst of requests against the tiny GPT, stream one of them
token by token, and print the engine's serving telemetry.

    python examples/serve_gpt.py

Live introspection: `--metrics-port 8000` serves the HTTP observability
endpoint while the engine decodes — /metrics (Prometheus, incl. the
paddle_serving_* family), /healthz (decode-round liveness), /trace
(queue/prefill/decode spans with per-request trace ids), /programs
(decode block + per-bucket prefill FLOPs/bytes attribution):

    python examples/serve_gpt.py --metrics-port 8000
"""
import argparse

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import debug, observability
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import InferenceEngine, SamplingParams


def main(num_requests=10, metrics_port=None):
    paddle.seed(0)
    if metrics_port is not None:
        server = observability.start_server(metrics_port)
        print(f'observability endpoint at {server.url}')
    model = GPTForCausalLM(GPTConfig.tiny()).eval()

    # one engine = one slot pool + scheduler; 4 slots serve the whole
    # burst by admitting queued requests as running ones retire
    engine = InferenceEngine(model, num_slots=4, max_length=64,
                             decode_block=4)

    rng = np.random.RandomState(0)
    handles = []
    for i in range(num_requests):
        prompt = rng.randint(1, model.config.vocab_size,
                             (int(rng.randint(3, 20)),)).tolist()
        params = SamplingParams(
            max_new_tokens=int(rng.randint(4, 16)),
            # mix greedy and seeded sampling in the SAME batch
            strategy='sampling' if i % 3 == 2 else 'greedy_search',
            temperature=1.2, top_k=40, seed=i, eos_token_id=-1)
        handles.append(engine.submit(prompt, params))

    # stream the FIRST request token-by-token; the engine advances every
    # running request under the hood on each step
    print('streaming request 0:', end=' ', flush=True)
    for tok in handles[0].stream():
        print(tok, end=' ', flush=True)
    print()

    engine.run()   # drain the rest
    for h in handles:
        print(f'req {h.request_id}: {h.status.lower():8s} '
              f'prompt={len(h.prompt_tokens):2d} tokens={h.tokens}')

    stats = engine.stats()
    print(f"\n{stats['completed']}/{stats['submitted']} served, "
          f"{stats['tokens']} tokens, {stats['decode_rounds']} decode "
          f"rounds, prefill buckets traced: "
          f"{sorted(k for k in stats['traces'] if k.startswith('prefill'))}")
    print(debug.observability_summary())
    return handles


if __name__ == '__main__':
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument('--num-requests', type=int, default=10)
    p.add_argument('--metrics-port', type=int, default=None,
                   help='serve the HTTP observability endpoint on this '
                        'port while decoding')
    args = p.parse_args()
    main(num_requests=args.num_requests, metrics_port=args.metrics_port)
