"""Single-chip headline benchmark: Llama-flavored decoder pretraining
step — tokens/sec + MFU on the available chip (SURVEY.md §6).

Prints exactly ONE JSON line:
  {"metric": ..., "value": tokens/sec, "unit": "tokens/s",
   "vs_baseline": MFU / 0.40, ...}
vs_baseline normalizes against the reference's A100-class MFU bar
(BASELINE.json: ">= A100 MFU (~40%)" on matmul-dominant decoders).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """Peak bf16 FLOP/s by device kind (public TPU spec sheet numbers)."""
    kind = getattr(device, 'device_kind', '').lower()
    table = {
        'v5 lite': 197e12, 'v5e': 197e12,
        'v5p': 459e12, 'v5': 459e12,
        'v6 lite': 918e12, 'v6e': 918e12,
        'v4': 275e12,
        'v3': 123e12,
        'v2': 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class if unrecognized


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.default_backend() not in ('cpu',)
    # ~740M-param decoder in bf16 on a real chip; thumbnail on CPU CI.
    # h=2048 / head_dim=128 keeps every matmul MXU-shaped; batch chosen to
    # fill HBM with the fused-CE loss (no fp32 logits copy) and the pallas
    # flash-attention path (no [B,H,S,S] materialization).
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=4096)
        batch, seq, steps, warmup = 4, 2048, 10, 2
        dtype = 'bfloat16'
    else:
        cfg = LlamaConfig.tiny()
        batch, seq, steps, warmup = 2, 64, 3, 1
        dtype = 'float32'

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if dtype == 'bfloat16':
        model.bfloat16()
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters(),
        multi_precision=(dtype == 'bfloat16'))

    def loss_fn(logits, labels):
        # fused CE path: fp32 math without materializing fp32 logits
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq))

    for _ in range(warmup):
        loss = step(ids, ids)
    float(loss.numpy())  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, ids)
    final_loss = float(loss.numpy())  # sync on the last step
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt

    # model FLOPs: 3x forward (fwd + 2x bwd); fwd = 2*N_matmul*B*S weight
    # matmuls + 4*B*S^2*H attention matmuls per layer
    h, L = cfg.hidden_size, cfg.num_hidden_layers
    qkvo = h * (cfg.num_attention_heads * cfg.head_dim) * 2 \
        + h * (cfg.num_key_value_heads * cfg.head_dim) * 2
    n_matmul = L * (qkvo + 3 * h * cfg.intermediate_size) \
        + h * cfg.vocab_size  # lm head included, embed gather excluded
    fwd_flops = (2 * n_matmul * batch * seq
                 + L * 4 * batch * seq * seq * h)
    step_flops = 3 * fwd_flops
    mfu = step_flops / dt / _peak_flops(jax.devices()[0])

    print(json.dumps({
        'metric': 'llama_740m_pretrain_tokens_per_sec_per_chip',
        'value': round(tokens_per_sec, 1),
        'unit': 'tokens/s',
        'vs_baseline': round(mfu / 0.40, 4),
        'mfu': round(mfu, 4),
        'step_time_s': round(dt, 4),
        'loss': round(final_loss, 4),
        'device': str(jax.devices()[0].device_kind),
        'config': {'params_m': round(sum(
            int(np.prod(p.shape)) for p in model.parameters()) / 1e6, 1),
            'batch': batch, 'seq': seq, 'dtype': dtype},
    }))


if __name__ == '__main__':
    sys.exit(main())
