"""Single-chip headline benchmark: GPT-3-1.3B-class decoder pretraining
step — tokens/sec + MFU on the available chip (SURVEY.md §6,
BASELINE.json configs[2]).

Prints exactly ONE JSON line:
  {"metric": ..., "value": tokens/sec, "unit": "tokens/s",
   "vs_baseline": MFU / 0.40, ...}
vs_baseline normalizes against the reference's A100-class MFU bar
(BASELINE.json: ">= A100 MFU (~40%)" on matmul-dominant decoders).

The headline model is the GPT-3 XL shape (h=2048, L=24, 16 heads x 128,
seq 2048, ~1.3B params) built on the Llama block (RMSNorm/SwiGLU/RoPE —
the TPU-native decoder this framework optimizes); `use_recompute='dots'`
plus bf16 Adam moments are what fit params+optimizer+activations into a
single v5e's 16 GB HBM. Falls back to the round-2 740M config (and
reports so) if the 1.3B step OOMs on smaller chips.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """Peak bf16 FLOP/s by device kind (public TPU spec sheet numbers)."""
    kind = getattr(device, 'device_kind', '').lower()
    table = {
        'v5 lite': 197e12, 'v5e': 197e12,
        'v5p': 459e12, 'v5': 459e12,
        'v6 lite': 918e12, 'v6e': 918e12,
        'v4': 275e12,
        'v3': 123e12,
        'v2': 45e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12  # assume v5e-class if unrecognized


def _configs(on_tpu):
    from paddle_tpu.nlp import LlamaConfig
    if not on_tpu:
        return [('llama_tiny', LlamaConfig.tiny(), 2, 64, 3, 1, 'float32')]
    # full-block recompute, not 'dots': at 24 layers x batch 8 x seq 2048
    # the dots policy's saved matmul outputs alone (~10 GB) blow the 16 GB
    # HBM; full remat keeps only block inputs (~1.6 GB) and re-runs each
    # block's forward inside backward — the classic memory/FLOPs trade
    gpt3_xl = LlamaConfig(
        vocab_size=50304, hidden_size=2048, intermediate_size=5504,
        num_hidden_layers=24, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=4096,
        use_recompute=True)
    m740 = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5504,
        num_hidden_layers=12, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=4096)
    return [
        ('gpt3_1p3b', gpt3_xl, 8, 2048, 10, 2, 'bfloat16'),
        ('gpt3_1p3b', gpt3_xl, 4, 2048, 10, 2, 'bfloat16'),
        ('llama_740m', m740, 4, 2048, 10, 2, 'bfloat16'),
    ]


def _run_config(name, cfg, batch, seq, steps, warmup, dtype):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if dtype == 'bfloat16':
        model.bfloat16()
    big = sum(int(np.prod(p.shape)) for p in model.parameters()) > 1e9
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters(),
        multi_precision=(dtype == 'bfloat16' and not big),
        # >1B params: bf16 moments are the difference between fitting a
        # single 16GB chip and OOM (fp32 m+v alone would be 10.7 GB)
        moment_dtype=('bfloat16' if big else None))

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1]))

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    batches = [rng.randint(0, cfg.vocab_size, (batch, seq))
               for _ in range(4)]  # rotate data: no single-batch cache luck

    for i in range(warmup):
        loss = step(batches[i % 4], batches[i % 4])
    float(loss.numpy())  # sync

    t0 = time.perf_counter()
    for i in range(steps):
        loss = step(batches[i % 4], batches[i % 4])
    final_loss = float(loss.numpy())  # sync on the last step
    dt = (time.perf_counter() - t0) / steps

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # model FLOPs: 3x forward (fwd + 2x bwd); fwd = 2*N_matmul*B*S weight
    # matmuls + 4*B*S^2*H attention matmuls per layer (remat recompute
    # FLOPs deliberately NOT counted — MFU measures model math only)
    h, L = cfg.hidden_size, cfg.num_hidden_layers
    qkvo = h * (cfg.num_attention_heads * cfg.head_dim) * 2 \
        + h * (cfg.num_key_value_heads * cfg.head_dim) * 2
    n_matmul = L * (qkvo + 3 * h * cfg.intermediate_size) \
        + h * cfg.vocab_size  # lm head included, embed gather excluded
    fwd_flops = (2 * n_matmul * batch * seq
                 + L * 4 * batch * seq * seq * h)
    step_flops = 3 * fwd_flops
    mfu = step_flops / dt / _peak_flops(jax.devices()[0])
    return {
        'tokens_per_sec': batch * seq / dt,
        'mfu': mfu,
        'step_time_s': dt,
        'loss': final_loss,
        'params_m': round(n_params / 1e6, 1),
        'batch': batch, 'seq': seq, 'dtype': dtype,
    }


def _bench_flash_kernels():
    """Own pallas flash (fwd+bwd) vs jax library kernel, one fwd+bwd each
    (VERDICT r2 #8: measured justification for the kernel choice)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk
    rng = np.random.RandomState(0)
    shape = (4, 2048, 16, 128)
    q = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)

    def time_fn(f):
        g = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
            f(a, b, c).astype(jnp.float32)), argnums=(0, 1, 2)))
        r = g(q, k, v)  # compile + warm
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(5):
            r = g(q, k, v)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / 5 * 1e3

    try:
        own_ms = time_fn(lambda a, b, c: pk.flash_attention_own(
            a, b, c, True, 512, 512, False))
        lib_ms = time_fn(lambda a, b, c: pk.flash_attention(a, b, c,
                                                            causal=True))
        return {'flash_own_ms': round(own_ms, 2),
                'flash_lib_ms': round(lib_ms, 2)}
    except Exception as e:  # never let the micro-bench kill the headline
        return {'flash_bench_error': type(e).__name__}


def main():
    import jax
    on_tpu = jax.default_backend() not in ('cpu',)
    result = None
    for name, cfg, batch, seq, steps, warmup, dtype in _configs(on_tpu):
        try:
            result = _run_config(name, cfg, batch, seq, steps, warmup, dtype)
            metric_name = name
            break
        except Exception as e:
            msg = str(e).lower()
            if 'resource' in msg or 'memory' in msg or 'oom' in msg \
                    or 'allocat' in msg or 'compile' in msg:
                # OOM (or a compiler blow-up on the big config): try the
                # next, smaller config and say so in the output
                continue
            raise
    if result is None:
        raise RuntimeError('all bench configs failed')
    # only a different MODEL counts as a fallback (batch shrink within the
    # 1.3B config still benches the 1.3B headline)
    fell_back = on_tpu and metric_name != 'gpt3_1p3b'

    out = {
        'metric': f'{metric_name}_pretrain_tokens_per_sec_per_chip',
        'value': round(result['tokens_per_sec'], 1),
        'unit': 'tokens/s',
        'vs_baseline': round(result['mfu'] / 0.40, 4),
        'mfu': round(result['mfu'], 4),
        'step_time_s': round(result['step_time_s'], 4),
        'loss': round(result['loss'], 4),
        'device': str(jax.devices()[0].device_kind),
        'fell_back_from_1p3b': fell_back,
        'config': {'params_m': result['params_m'],
                   'batch': result['batch'], 'seq': result['seq'],
                   'dtype': result['dtype']},
    }
    if on_tpu:
        out.update(_bench_flash_kernels())
    print(json.dumps(out))


if __name__ == '__main__':
    sys.exit(main())
