"""Single-chip headline benchmark: GPT-3-1.3B-class decoder pretraining
step — tokens/sec + MFU on the available chip (SURVEY.md §6,
BASELINE.json configs[2]).

Prints exactly ONE JSON line:
  {"metric": ..., "value": tokens/sec, "unit": "tokens/s",
   "vs_baseline": MFU / 0.40, ...}
vs_baseline normalizes against the reference's A100-class MFU bar
(BASELINE.json: ">= A100 MFU (~40%)" on matmul-dominant decoders).

The headline model is the GPT-3 XL shape (h=2048, L=24, 16 heads x 128,
seq 2048, ~1.3B params) built on the Llama block (RMSNorm/SwiGLU/RoPE —
the TPU-native decoder this framework optimizes); `use_recompute='dots'`
plus bf16 Adam moments are what fit params+optimizer+activations into a
single v5e's 16 GB HBM. Falls back to the round-2 740M config (and
reports so) if the 1.3B step OOMs on smaller chips.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    """Peak bf16 FLOP/s by device kind. Delegates to the shared
    observability.cost table (one source of truth for the headline MFU
    here and the paddle_mfu/roofline gauges; PADDLE_PEAK_FLOPS
    overrides both identically)."""
    from paddle_tpu.observability.cost import device_peaks
    peaks = device_peaks(device)
    if peaks['peak_flops']:
        return peaks['peak_flops']
    return 197e12  # assume v5e-class if unrecognized


# the GPT-3 XL geometry shared by the headline train phase and the
# decode phase
GPT3_SHAPE = dict(vocab_size=50304, hidden_size=2048,
                  intermediate_size=5504, num_hidden_layers=24,
                  num_attention_heads=16, num_key_value_heads=16,
                  max_position_embeddings=4096)


def _configs(on_tpu):
    from paddle_tpu.nlp import LlamaConfig
    if not on_tpu:
        return [('llama_tiny', LlamaConfig.tiny(), 2, 64, 3, 1, 'float32')]
    # remat policy (r4 sweep on v5e, BENCH experiments E1-E4):
    # 'dots_no_batch' keeps weight-matmul outputs and recomputes only
    # attention + elementwise in backward — at batch 2 the saved outputs
    # (~2.5 GB) fit beside params+moments and MFU jumps 0.50 -> 0.64
    # vs full-block remat at batch 8 (whose extra forward is ~1/4 of
    # step flops). Full-remat rungs remain as OOM fallbacks.
    gpt3_dots = LlamaConfig(use_recompute='dots_no_batch', **GPT3_SHAPE)
    gpt3_full = LlamaConfig(use_recompute=True, **GPT3_SHAPE)
    m740 = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5504,
        num_hidden_layers=12, num_attention_heads=16,
        num_key_value_heads=16, max_position_embeddings=4096)
    return [
        # b4 first: the pallas CE avoids the fp32 [B*S, V] logits buffer,
        # which is what OOMed b4 in r4 — falls through to b2 if it still
        # doesn't fit
        ('gpt3_1p3b', gpt3_dots, 4, 2048, 10, 2, 'bfloat16'),
        ('gpt3_1p3b', gpt3_dots, 2, 2048, 10, 2, 'bfloat16'),
        ('gpt3_1p3b', gpt3_full, 8, 2048, 10, 2, 'bfloat16'),
        ('gpt3_1p3b', gpt3_full, 4, 2048, 10, 2, 'bfloat16'),
        ('llama_740m', m740, 4, 2048, 10, 2, 'bfloat16'),
    ]


def _7b_configs():
    """Llama-2 7B-shaped ladder (BASELINE headline #2): FULL 7B
    hidden/FFN/head geometry (h=4096, ffn=11008, 32 heads, seq 4096).

    r5: Adam moments host-offloaded (optimizer offload='host',
    VERDICT r4 #3 — upstream fleet sharding `offload`) so HBM holds only
    bf16 params + grads + activations: 24 layers ≈ 10.3 GB params and
    16 layers ≈ 7.1 GB now fit where the r4 in-HBM-moments ceiling was
    8 layers. Deepest-first ladder; each rung flags depth + offload, and
    the streamed-moment transfer cost shows up honestly in step_time."""
    from paddle_tpu.nlp import LlamaConfig

    def mk(layers, remat):
        return LlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=11008,
            num_attention_heads=32, num_key_value_heads=32,
            max_position_embeddings=4096, num_hidden_layers=layers,
            use_recompute=remat)
    # throughput ladder: deepest config whose FULL state (params + grads
    # + bf16 moments) lives in HBM — this is the tokens/sec-per-chip
    # number comparable run to run
    fast = [
        ('llama2_7b_shape_8L', mk(8, 'dots_no_batch'), 1, 4096, 6, 2,
         'bfloat16', None),
        ('llama2_7b_shape_8L', mk(8, True), 2, 2048, 6, 2, 'bfloat16',
         None),
    ]
    # depth rung (reported separately): 16L with Adam moments
    # host-offloaded — 2x the in-HBM depth ceiling. The moment streaming
    # crosses the host link every step (on this rig, an RPC tunnel), so
    # its step_time measures the offload tradeoff, not model throughput.
    # No 24L rung: bf16 params+grads alone are 20.6 GB — past the chip's
    # HBM no matter where the moments live.
    deep = [
        ('llama2_7b_shape_16L', mk(16, True), 1, 2048, 3, 1, 'bfloat16',
         'host'),
    ]
    return fast, deep


def _run_config(name, cfg, batch, seq, steps, warmup, dtype,
                offload=None):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import LlamaForCausalLM

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if dtype == 'bfloat16':
        model.bfloat16()
    big = sum(int(np.prod(p.shape)) for p in model.parameters()) > 1e9
    opt = paddle.optimizer.AdamW(
        learning_rate=3e-4, parameters=model.parameters(),
        multi_precision=(dtype == 'bfloat16' and not big),
        # >1B params: bf16 moments are the difference between fitting a
        # single 16GB chip and OOM (fp32 m+v alone would be 10.7 GB)
        moment_dtype=('bfloat16' if big else None),
        offload=offload)

    def loss_fn(logits, labels):
        # true LM objective: predict token t+1 from positions <= t
        lg = logits[:, :-1].reshape([-1, cfg.vocab_size])
        lb = labels[:, 1:].reshape([-1])
        return F.cross_entropy(lg, lb)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    batches = [rng.randint(0, cfg.vocab_size, (batch, seq))
               for _ in range(4)]  # rotate data: no single-batch cache luck

    for i in range(warmup):
        loss = step(batches[i % 4], batches[i % 4])
    float(loss.numpy())  # sync

    t0 = time.perf_counter()
    for i in range(steps):
        loss = step(batches[i % 4], batches[i % 4])
    final_loss = float(loss.numpy())  # sync on the last step
    dt = (time.perf_counter() - t0) / steps

    peak_hbm = 0
    try:
        ma = step.memory_analysis(batches[0], batches[0])
        peak_hbm = int(getattr(ma, 'peak_memory_in_bytes', 0)) or (
            int(ma.argument_size_in_bytes) + int(ma.temp_size_in_bytes)
            + int(ma.output_size_in_bytes) - int(ma.alias_size_in_bytes))
    except Exception:  # paddle-lint: disable=swallowed-exception -- AOT introspection is best-effort; never kill the bench
        pass  # AOT introspection is best-effort; never kill the bench

    result_offload = offload is not None
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # model FLOPs: 3x forward (fwd + 2x bwd); fwd = 2*N_matmul*B*S weight
    # matmuls + 4*B*S^2*H attention matmuls per layer (remat recompute
    # FLOPs deliberately NOT counted — MFU measures model math only)
    h, L = cfg.hidden_size, cfg.num_hidden_layers
    qkvo = h * (cfg.num_attention_heads * cfg.head_dim) * 2 \
        + h * (cfg.num_key_value_heads * cfg.head_dim) * 2
    n_matmul = L * (qkvo + 3 * h * cfg.intermediate_size) \
        + h * cfg.vocab_size  # lm head included, embed gather excluded
    fwd_flops = (2 * n_matmul * batch * seq
                 + L * 4 * batch * seq * seq * h)
    step_flops = 3 * fwd_flops
    mfu = step_flops / dt / _peak_flops(jax.devices()[0])
    return {
        'tokens_per_sec': batch * seq / dt,
        'mfu': mfu,
        'step_time_s': dt,
        'loss': final_loss,
        'params_m': round(n_params / 1e6, 1),
        'batch': batch, 'seq': seq, 'dtype': dtype,
        'peak_hbm_gb': round(peak_hbm / 2**30, 2),
        'offload_optimizer': result_offload,
        'layers': cfg.num_hidden_layers,
    }


def _run_7b_overfit(steps=300, target=7.0):
    """Correctness signal for the 7B geometry (VERDICT r4 Weak #3 / #4):
    up to 300 AdamW steps on ONE fixed small batch must drive the loss well
    under ln(32000)=10.37 — a throughput-shaped block that can't learn
    would stay pinned near random init."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=4096, num_hidden_layers=8,
        use_recompute=True)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(),
        moment_dtype='bfloat16')
    step = TrainStep(
        model, lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, cfg.vocab_size]),
            labels[:, 1:].reshape([-1])),
        opt)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (1, 512))
    first = None
    losses = []
    for i in range(steps):
        loss = float(step(ids, ids).numpy())
        losses.append(loss)
        if first is None:
            first = loss
        if loss < target and i >= 20:
            break
    return {'first_loss': round(first, 4),
            'last_loss': round(losses[-1], 4),
            'steps': len(losses), 'target': target,
            'reached_target': losses[-1] < target}


def _bench_flash_kernels():
    """Own pallas flash (fwd+bwd) vs jax library kernel (VERDICT r2 #8:
    measured justification for the kernel choice). The timing loop runs
    ON DEVICE (lax.fori_loop chaining q through the gradient) — host
    loops over a tunneled TPU measure RPC pipelining/caching, not the
    kernel (r4: host-loop timings swung 11-18 ms run to run)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk
    try:
        rng = np.random.RandomState(0)
        shape = (4, 2048, 16, 128)
        q0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        k0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        v0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
        n = 10

        def time_fn(f):
            def body(i, q):
                dq = jax.grad(lambda a: jnp.sum(
                    f(a, k0, v0).astype(jnp.float32)))(q)
                return (q + dq * jnp.bfloat16(1e-4)).astype(jnp.bfloat16)
            g = jax.jit(lambda q: jax.lax.fori_loop(0, n, body, q))
            jax.block_until_ready(g(q0))  # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(g(q0))
            return (time.perf_counter() - t0) / n * 1e3

        own_ms = time_fn(lambda a, b, c: pk.flash_attention_own(
            a, b, c, True, 512, 512, False))
        lib_ms = time_fn(lambda a, b, c: pk.flash_attention(a, b, c,
                                                            causal=True))
        return {'flash_own_ms': round(own_ms, 2),
                'flash_lib_ms': round(lib_ms, 2)}
    except Exception as e:  # never let the micro-bench kill the headline
        print(f'# flash bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        return {'flash_bench_error': type(e).__name__}


def _bench_fused_ce():
    """Pallas online-softmax CE vs the XLA custom_vjp CE the models
    otherwise use — the real fallback, not a strawman (VERDICT r4 #5:
    a pallas battle XLA can lose — the [B*S, V] logits dominate HBM
    traffic at LM head shapes, and the pallas forward reads them once
    where XLA's max+expsum lowering reads twice). Headline 1.3B LM-head
    shape: [4096 rows, 50304 vocab] bf16."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn.functional import _fused_softmax_ce_xla
    from paddle_tpu.ops import pallas_kernels as pk
    try:
        rng = np.random.RandomState(0)
        n, v = 4096, 50304
        x0 = jnp.asarray(rng.standard_normal((n, v)), jnp.bfloat16)
        lab = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
        valid = jnp.ones((n,), bool)
        reps = 10

        def xla_ce(x):
            return jnp.sum(_fused_softmax_ce_xla(x, lab, valid))

        def time_fn(f):
            def body(i, x):
                dx = jax.grad(f)(x)
                return (x - dx * jnp.bfloat16(1e-4)).astype(jnp.bfloat16)
            g = jax.jit(lambda x: jax.lax.fori_loop(0, reps, body, x))
            jax.block_until_ready(g(x0))  # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(g(x0))
            return (time.perf_counter() - t0) / reps * 1e3

        own = time_fn(lambda x: jnp.sum(
            pk.softmax_cross_entropy(x, lab)))
        ref = time_fn(xla_ce)
        return {'fused_ce_pallas_ms': round(own, 2),
                'fused_ce_xla_ms': round(ref, 2),
                'fused_ce_speedup_pct': round((ref / own - 1) * 100, 1)}
    except Exception as e:
        print(f'# fused_ce bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        return {'fused_ce_bench_error': type(e).__name__}


def _phase_decode():
    """Serving throughput: KV-cache greedy decode on the 1.3B geometry
    (batch 8, prompt 128, 128 new tokens) — decode tokens/sec/chip.
    The whole decode is one XLA program (prefill + while_loop), so this
    measures the incremental-decode path end to end."""
    import time as _t

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.default_backend() not in ('cpu',)
    if on_tpu:
        cfg = LlamaConfig(**GPT3_SHAPE)
        batch, prompt_len, new_tokens, dtype = 8, 128, 128, 'bfloat16'
    else:
        cfg = LlamaConfig.tiny()
        batch, prompt_len, new_tokens, dtype = 2, 8, 8, 'float32'
    paddle.seed(0)
    model = LlamaForCausalLM(cfg).eval()
    if dtype == 'bfloat16':
        model.bfloat16()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, prompt_len))
    t_ids = paddle.to_tensor(ids)
    kw = dict(max_new_tokens=new_tokens,
              decode_strategy='greedy_search', eos_token_id=-1)
    out, _ = model.generate(t_ids, **kw)          # compile + warm
    assert out.shape == [batch, new_tokens]
    t0 = _t.perf_counter()
    reps = 3
    for _ in range(reps):
        out, _ = model.generate(t_ids, **kw)
    float(out.numpy()[0, 0])                      # sync
    dt = (_t.perf_counter() - t0) / reps
    result = {'decode_1p3b': {
        'tokens_per_sec': round(batch * new_tokens / dt, 1),
        'batch': batch, 'prompt_len': prompt_len,
        'new_tokens': new_tokens, 'time_per_call_s': round(dt, 4),
        'dtype': dtype}}

    # speculative decoding (batch-1 latency): same-width 2-layer draft.
    # With a real distilled draft the acceptance rate, and therefore the
    # speedup, would be far higher — this measures the machinery cost +
    # whatever a random-init draft happens to accept.
    try:
        draft_cfg = type(cfg)(**{**cfg.__dict__, 'num_hidden_layers': 2})
        paddle.seed(1)
        draft = LlamaForCausalLM(draft_cfg).eval()
        if dtype == 'bfloat16':
            draft.bfloat16()
        one = ids[:1]
        kw1 = dict(max_new_tokens=new_tokens, num_draft_tokens=4,
                   eos_token_id=-1)
        kw_plain = dict(max_new_tokens=new_tokens,
                        decode_strategy='greedy_search', eos_token_id=-1)
        one_t = paddle.to_tensor(one)
        model.speculative_generate(draft, one, **kw1)   # compile + warm
        model.generate(one_t, **kw_plain)               # batch-1 compile
        t0 = _t.perf_counter()
        _, stats = model.speculative_generate(draft, one, **kw1)
        spec_dt = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        out_plain, _ = model.generate(one_t, **kw_plain)
        float(out_plain.numpy()[0, 0])   # sync: measure execution, not
        plain_dt = _t.perf_counter() - t0  # async dispatch
        result['speculative_decode'] = {
            'tokens_per_sec': round(new_tokens / spec_dt, 1),
            'plain_tokens_per_sec': round(new_tokens / plain_dt, 1),
            'acceptance_rate': round(stats['acceptance_rate'], 3),
            'rounds': stats['rounds'],
            'draft_layers': draft_cfg.num_hidden_layers,
            'note': 'random-init draft = worst case (acceptance ~0); '
                    'speedup requires a distilled draft — this measures '
                    'machinery overhead'}
    except Exception as e:
        print(f'# spec decode bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        result['speculative_decode'] = {'error': type(e).__name__}
    return result


def eager_mlp_loop(steps=20, warmup=3, batch=32, in_dim=64, hidden=128,
                   classes=10, use_cache=True, instrument=False,
                   resilience=False):
    """Eager-dispatch micro-bench loop (also imported by the tier-1
    regression test): a plain DyGraph MLP train step — forward, CE loss,
    tape backward, eager SGD — with NO TrainStep jit, so every op rides
    `apply_op`. Returns wall-clock rates plus the dispatch-cache counter
    window covering only the post-warmup steps; with `use_cache` the
    telemetry must show zero retraces there.

    `instrument=True` runs the SAME loop with the observability layer
    active per step — a span around the step body plus StepTelemetry
    updates — for the obs-overhead A/B (`bench.py obs` phase and the
    tier-1 <3% overhead guard).

    `resilience=True` instead routes every step through a
    FaultTolerantStep wrapper (per-step loss finiteness + spike check,
    host snapshot every 10 steps) for the resilience-overhead A/B
    (`bench.py resilience` phase and its tier-1 <3% guard)."""
    import time as _t

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu import debug as pdebug
    from paddle_tpu import observability as obs

    was_enabled = pdebug.dispatch_stats()['enabled']
    obs_was_enabled = obs.enabled()
    obs.enable(instrument)
    pdebug.enable_dispatch_cache(use_cache)
    pdebug.clear_dispatch_cache()
    try:
        paddle.seed(0)
        model = nn.Sequential(
            nn.Linear(in_dim, hidden), nn.ReLU(),
            nn.Linear(hidden, hidden), nn.ReLU(),
            nn.Linear(hidden, classes))
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(
            rng.standard_normal((batch, in_dim)).astype('float32'))
        y = paddle.to_tensor(rng.randint(0, classes, (batch,)))

        def one_step():
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        telemetry = obs.StepTelemetry(memory_every=10) if instrument \
            else None

        ft = None
        if resilience:
            import jax.numpy as jnp
            from paddle_tpu import resilience as res

            def snap():
                return {n: np.asarray(p.value)
                        for n, p in model.named_parameters()}

            def rest(s):
                pm = dict(model.named_parameters())
                for n, v in s.items():
                    pm[n]._data = jnp.asarray(v)
                    pm[n]._node = None
            ft = res.FaultTolerantStep(
                lambda: one_step(), snapshot_fn=snap, restore_fn=rest,
                snapshot_interval=10)

        for _ in range(warmup):
            loss = one_step()
        float(loss.numpy())                  # drain warmup dispatch
        pdebug.reset_dispatch_stats()
        t0 = _t.perf_counter()
        if ft is not None:
            # resilience arm: the wrapper syncs the loss each step (the
            # finiteness check needs the value on host) — that sync IS
            # part of the fault-tolerance cost being measured
            for _ in range(steps):
                loss = ft()
        elif telemetry is not None:
            # instrumented arm: span + per-step telemetry (loss is NOT
            # synced per step — the A/B measures instrumentation cost,
            # not a forced device round-trip)
            for _ in range(steps):
                with obs.span('bench.eager_step'):
                    loss = one_step()
                telemetry.step(tokens=batch)
        else:
            for _ in range(steps):
                loss = one_step()
        final_loss = float(loss.numpy())     # sync
        dt = _t.perf_counter() - t0
        stats = pdebug.dispatch_stats()
        return {
            'steps_per_sec': round(steps / dt, 1),
            'ops_per_sec': round(stats['calls'] / dt, 1),
            'ops_per_step': stats['calls'] // steps,
            'loss': round(final_loss, 4),
            'cache_enabled': use_cache,
            'hits': stats['hits'], 'misses': stats['misses'],
            'retraces': stats['retraces'],
            'fallbacks': stats['fallbacks'],
            'hit_rate': round(stats['hit_rate'], 4),
        }
    finally:
        pdebug.enable_dispatch_cache(was_enabled)
        pdebug.clear_dispatch_cache()
        obs.enable(obs_was_enabled)


def obs_overhead_ab(steps=30, trials=3):
    """A/B the eager MLP loop with observability instrumentation on vs
    off (also imported by the tier-1 overhead guard). Takes the best
    steps/sec of `trials` alternating runs per arm — min-noise on a
    shared CPU — and reports the on/off overhead ratio."""
    best_on = best_off = 0.0
    for _ in range(trials):
        off = eager_mlp_loop(steps=steps, instrument=False)
        on = eager_mlp_loop(steps=steps, instrument=True)
        best_off = max(best_off, off['steps_per_sec'])
        best_on = max(best_on, on['steps_per_sec'])
    overhead = best_off / best_on - 1 if best_on else float('inf')
    return {
        'instrumented_steps_per_sec': best_on,
        'plain_steps_per_sec': best_off,
        'overhead_ratio': round(best_off / best_on, 4) if best_on else 0.0,
        'overhead_pct': round(overhead * 100, 2),
    }


def scrape_overhead_ab(steps=30, trials=3, hz=4.0):
    """Scrape-under-load A/B (also imported by the tier-1 overhead
    guard): the instrumented eager MLP loop with a background HTTP
    client hitting the live /metrics endpoint at `hz` vs the same loop
    unscraped. Measures what a real Prometheus scraper costs the hot
    path — the registry lock is only held per family copy, so the
    answer should match the instrumentation guard (~0, <3% gated).
    Every scraped body is parse-checked; a single unparseable scrape
    fails the bench (concurrent export must never tear)."""
    import threading
    import urllib.request

    from paddle_tpu import observability as obs

    srv = obs.start_server(0)
    stop = threading.Event()
    counts = {'scrapes': 0, 'failures': 0}

    def scraper():
        url = f'{srv.url}/metrics'
        while not stop.is_set():
            try:
                body = urllib.request.urlopen(url, timeout=2).read()
                if b'# TYPE' not in body:
                    counts['failures'] += 1
                counts['scrapes'] += 1
            except Exception:
                counts['failures'] += 1
            stop.wait(1.0 / hz)

    try:
        best_on = best_off = 0.0
        ratios = []
        for _ in range(trials):
            off = eager_mlp_loop(steps=steps, instrument=True)
            t = threading.Thread(target=scraper, daemon=True)
            stop.clear()
            t.start()
            try:
                on = eager_mlp_loop(steps=steps, instrument=True)
            finally:
                stop.set()
                t.join(timeout=5)
            best_off = max(best_off, off['steps_per_sec'])
            best_on = max(best_on, on['steps_per_sec'])
            # min of adjacent-pair ratios, not best-of-N across arms:
            # on a loaded single-core box the bests can land in
            # different noise regimes and report phantom overhead; the
            # least-noisy pair is closest to the uncontended truth
            if on['steps_per_sec']:
                ratios.append(off['steps_per_sec'] / on['steps_per_sec'])
        overhead = min(ratios) - 1 if ratios else float('inf')
        return {
            'scraped_steps_per_sec': best_on,
            'plain_steps_per_sec': best_off,
            'overhead_pct': round(overhead * 100, 2),
            'scrapes': counts['scrapes'],
            'scrape_failures': counts['failures'],
            'scrape_hz': hz,
        }
    finally:
        stop.set()
        srv.stop()


def sanitizer_overhead_ab(steps=30, trials=3):
    """Concurrency-sanitizer report-mode vs off A/B on the instrumented
    eager MLP loop (also imported by the tier-1 <3% overhead guard).
    Both arms run the SAME instrumentation — spans and StepTelemetry
    take the registry/event-log locks every step — so the ratio
    isolates what the sanitizer's held-stack + acquisition-graph
    tracking costs a lock-heavy hot path. Report-only mode is the
    production posture this guard protects; STRICT mode is reserved
    for tests (the chaos gauntlets), where raising beats speed.
    Min-of-adjacent-pair ratios, same estimator as the scrape guard."""
    from paddle_tpu.analysis import runtime as _rt

    prev = _rt.mode()
    ratios = []
    best_on = best_off = 0.0
    try:
        for _ in range(trials):
            _rt.disable()
            off = eager_mlp_loop(steps=steps, instrument=True)
            _rt.enable('report')
            on = eager_mlp_loop(steps=steps, instrument=True)
            best_off = max(best_off, off['steps_per_sec'])
            best_on = max(best_on, on['steps_per_sec'])
            if on['steps_per_sec']:
                ratios.append(off['steps_per_sec'] / on['steps_per_sec'])
    finally:
        _rt.enable(prev)
    overhead = min(ratios) - 1 if ratios else float('inf')
    return {
        'sanitized_steps_per_sec': best_on,
        'plain_steps_per_sec': best_off,
        'overhead_pct': round(overhead * 100, 2),
        'mode': 'report',
        'lock_classes_observed': _rt.stats()['lock_classes'],
    }


def _phase_obs():
    """Observability overhead phase: instrumentation on vs off on the
    eager hot path, the /metrics scrape-under-load A/B, and the
    concurrency-sanitizer report-mode A/B; the JSON carries the
    measured ratios (the tier-1 guards pin each under 3% on CPU)."""
    out = {}
    try:
        out['obs_overhead'] = obs_overhead_ab()
    except Exception as e:
        print(f'# obs bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['obs_overhead'] = {'error': type(e).__name__}
    try:
        out['scrape_overhead'] = scrape_overhead_ab()
    except Exception as e:
        print(f'# scrape bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['scrape_overhead'] = {'error': type(e).__name__}
    try:
        out['sanitizer_overhead'] = sanitizer_overhead_ab()
    except Exception as e:
        print(f'# sanitizer bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['sanitizer_overhead'] = {'error': type(e).__name__}
    return out


def fleet_obs_overhead_ab(steps=30, trials=3, interval_s=0.1):
    """Fleet-shipper on/off A/B (also imported by the tier-1 <3%
    overhead guard): the instrumented eager MLP loop with a background
    Shipper spooling registry deltas + event segments at `interval_s`
    vs the same loop unshipped. The shipper never touches the hot path
    — it snapshots on its own daemon thread — so the cost is registry
    lock contention during snapshots, which this pins under 3%.
    Min-of-adjacent-pair ratios, same estimator as the scrape guard."""
    import tempfile

    from paddle_tpu import observability as obs

    ratios = []
    best_on = best_off = 0.0
    with tempfile.TemporaryDirectory() as spool:
        for _ in range(trials):
            off = eager_mlp_loop(steps=steps, instrument=True)
            sh = obs.Shipper(spool, interval_s=interval_s).start()
            try:
                on = eager_mlp_loop(steps=steps, instrument=True)
            finally:
                sh.stop(flush=True)
            best_off = max(best_off, off['steps_per_sec'])
            best_on = max(best_on, on['steps_per_sec'])
            if on['steps_per_sec']:
                ratios.append(off['steps_per_sec'] / on['steps_per_sec'])
    overhead = min(ratios) - 1 if ratios else float('inf')
    return {
        'shipped_steps_per_sec': best_on,
        'plain_steps_per_sec': best_off,
        'overhead_pct': round(overhead * 100, 2),
        'ship_interval_s': interval_s,
    }


def fleet_roundtrip_smoke():
    """Spool roundtrip smoke: ship the live registry once, aggregate,
    and check the merged `paddle_steps_total` matches the local truth —
    the single-process degenerate case of the fleet merge invariant
    (the multi-process version lives in tests/test_fleet_obs.py)."""
    import tempfile

    from paddle_tpu import observability as obs

    with tempfile.TemporaryDirectory() as spool:
        sh = obs.Shipper(spool)
        sh.ship_now()
        agg = obs.Aggregator(spool)
        counts = agg.poll()
        merged = agg.merged()
        local = obs.get_registry().value('paddle_steps_total')
        fleet = 0.0
        for m in merged.get('metrics', []):
            if m['name'] == 'paddle_steps_total':
                fleet = sum(s['value'] for s in m['samples'])
        return {
            'segments_applied': counts['applied'],
            'local_steps_total': local,
            'fleet_steps_total': fleet,
            'merged_matches_local': fleet == local,
            'processes': agg.process_uids(),
        }


_FLEET_PROMPTS = [[5, 6, 7], [11, 12], [3, 1, 4, 1, 5],
                  [23, 29, 31, 37], [2, 4], [9, 8, 7, 6, 5, 4]]
_FLEET_ENGINE_KW = dict(num_slots=2, max_length=64, decode_block=2)


def _fleet_proc_factory_spec():
    """Model factory for replica children, addressed by file path so
    the child interpreter needs no installed test package."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'tests', '_fleet_factory.py') + ':tiny_gpt'


def _fleet_proc_supervisor(run_dir, program_store_dir):
    from paddle_tpu.serving import ReplicaSpec, Supervisor
    spec = ReplicaSpec(_fleet_proc_factory_spec(),
                       engine_kwargs=dict(_FLEET_ENGINE_KW),
                       program_store_dir=program_store_dir,
                       drain_deadline_s=20.0)
    return Supervisor(run_dir, spec, spawn_timeout_s=180.0,
                      backoff_base_s=0.05, backoff_cap_s=0.5,
                      max_restarts=5)


def _fleet_proc_local_engine():
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import InferenceEngine
    paddle.seed(7)   # same weights as tests/_fleet_factory.py:tiny_gpt
    model = GPTForCausalLM(GPTConfig.tiny()).eval()
    return InferenceEngine(model, **_FLEET_ENGINE_KW)


def fleet_rpc_overhead_ab(trials=3, max_new_tokens=16):
    """In-process engine vs ONE supervised replica process, same seeded
    tiny-GPT greedy workload (also imported by the tier-1 guard). The
    ratio isolates what the process boundary costs a serving batch:
    framed-RPC round trips per step + JSON mirror updates vs direct
    method calls. Both arms warm first (spawn already blocks on child
    readiness), so compiles never land in a measured window.
    Min-of-adjacent-pair ratios, same estimator as the scrape guard."""
    import tempfile
    import time as _t

    from paddle_tpu.serving import SamplingParams

    sp = SamplingParams(max_new_tokens=max_new_tokens, eos_token_id=-1)
    local = _fleet_proc_local_engine()

    def run_local():
        t0 = _t.perf_counter()
        hs = local.generate_many(_FLEET_PROMPTS, sp)
        dt = _t.perf_counter() - t0
        return dt, [h.tokens for h in hs]

    with tempfile.TemporaryDirectory() as tmp:
        sup = _fleet_proc_supervisor(
            os.path.join(tmp, 'run'), os.path.join(tmp, 'programs'))
        try:
            rr = sup.spawn('bench0')

            def run_remote():
                t0 = _t.perf_counter()
                hs = rr.generate_many(_FLEET_PROMPTS, sp)
                dt = _t.perf_counter() - t0
                return dt, [h.tokens for h in hs]

            _, ref = run_local()       # warm both arms off the clock
            _, remote_toks = run_remote()
            parity = remote_toks == ref
            ratios, best_local, best_remote = [], float('inf'), \
                float('inf')
            for _ in range(trials):
                t_local, _ = run_local()
                t_remote, _ = run_remote()
                best_local = min(best_local, t_local)
                best_remote = min(best_remote, t_remote)
                if t_local > 0:
                    ratios.append(t_remote / t_local)
            overhead = min(ratios) - 1 if ratios else float('inf')
            return {
                'local_s': round(best_local, 4),
                'remote_s': round(best_remote, 4),
                'overhead_pct': round(overhead * 100, 2),
                'tokens_per_arm': len(_FLEET_PROMPTS) * max_new_tokens,
                'parity': parity,
            }
        finally:
            sup.stop_all(deadline_s=10.0)


def fleet_proc_scaling(max_new_tokens=16, repeats=4):
    """The 2-process scaling row: the SAME workload through a Router
    over one replica process vs two. Before this PR a second 'replica'
    shared the parent's Python process (GIL + one runtime): added
    replicas moved latency, never throughput. Two OS processes are the
    first configuration where the scaling ratio can genuinely
    exceed 1."""
    import tempfile
    import time as _t

    from paddle_tpu.serving import Replica, Router, SamplingParams

    sp = SamplingParams(max_new_tokens=max_new_tokens, eos_token_id=-1)
    prompts = _FLEET_PROMPTS * repeats

    def run(router):
        t0 = _t.perf_counter()
        handles = [router.submit(p, sp) for p in prompts]
        while any(not h.done for h in handles):
            router.step()
        dt = _t.perf_counter() - t0
        done = sum(1 for h in handles if h.status == 'FINISHED')
        return dt, done

    with tempfile.TemporaryDirectory() as tmp:
        sup = _fleet_proc_supervisor(
            os.path.join(tmp, 'run'), os.path.join(tmp, 'programs'))
        try:
            ra, rb = sup.spawn('s0'), sup.spawn('s1')
            ra.generate_many(_FLEET_PROMPTS, sp)   # warm off the clock
            rb.generate_many(_FLEET_PROMPTS, sp)
            t1, done1 = run(Router([Replica(0, ra)]))
            t2, done2 = run(Router([Replica(0, ra), Replica(1, rb)]))
            return {
                'offered': len(prompts),
                'one_proc_s': round(t1, 4), 'one_proc_completed': done1,
                'two_proc_s': round(t2, 4), 'two_proc_completed': done2,
                'speedup': round(t1 / t2, 3) if t2 > 0 else 0.0,
            }
        finally:
            sup.stop_all(deadline_s=10.0)


def fleet_proc_kill_smoke(max_new_tokens=8):
    """Kill-mid-trace smoke (also imported by the tier-1 guard):
    SIGKILL one of two replica processes mid-decode under live traffic
    and count what the fleet lost. The contract is ZERO: every accepted
    request fails over to the survivor and finishes bit-exact."""
    import tempfile

    from paddle_tpu.serving import Replica, Router, SamplingParams

    sp = SamplingParams(max_new_tokens=max_new_tokens, eos_token_id=-1)
    with tempfile.TemporaryDirectory() as tmp:
        sup = _fleet_proc_supervisor(
            os.path.join(tmp, 'run'), os.path.join(tmp, 'programs'))
        try:
            ra, rb = sup.spawn('k0'), sup.spawn('k1')
            ref = [h.tokens
                   for h in ra.generate_many(_FLEET_PROMPTS, sp)]
            router = Router([Replica(0, ra), Replica(1, rb)])
            handles = [router.submit(p, sp) for p in _FLEET_PROMPTS]
            for _ in range(200):
                router.step()
                if ra._slot_req and rb._slot_req \
                        and any(not h.done and h.tokens for h in handles):
                    break
            sup.kill('k0')
            rounds = 0
            while any(not h.done for h in handles) and rounds < 3000:
                router.step()
                rounds += 1
            finished = sum(1 for h in handles if h.status == 'FINISHED')
            return {
                'offered': len(handles),
                'finished': finished,
                'lost_requests': len(handles) - finished,
                'bit_exact': [h.tokens for h in handles] == ref,
            }
        finally:
            sup.stop_all(deadline_s=10.0)


def _phase_fleet_proc():
    """Process fleet runtime phase (ISSUE 18): in-proc vs cross-process
    RPC overhead A/B, the 2-process scaling row, and the kill-mid-trace
    zero-loss smoke."""
    out = {}
    for key, fn in (('fleet_rpc_overhead', fleet_rpc_overhead_ab),
                    ('fleet_scaling', fleet_proc_scaling),
                    ('fleet_kill', fleet_proc_kill_smoke)):
        try:
            out[key] = fn()
        except Exception as e:
            print(f'# {key} bench failed: {type(e).__name__}: {e}',
                  file=sys.stderr)
            out[key] = {'error': type(e).__name__}
    return out


def _phase_fleet_obs():
    """Fleet observability plane phase: shipper on/off overhead A/B on
    the eager hot path (tier-1 pins it <3%) plus a single-process spool
    roundtrip smoke (ship -> aggregate -> merged equals local)."""
    out = {}
    try:
        out['fleet_obs_overhead'] = fleet_obs_overhead_ab()
    except Exception as e:
        print(f'# fleet_obs bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['fleet_obs_overhead'] = {'error': type(e).__name__}
    try:
        out['fleet_roundtrip'] = fleet_roundtrip_smoke()
    except Exception as e:
        print(f'# fleet roundtrip smoke failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['fleet_roundtrip'] = {'error': type(e).__name__}
    return out


def resilience_overhead_ab(steps=30, trials=3):
    """A/B the eager MLP loop through a FaultTolerantStep wrapper vs
    plain (also imported by the tier-1 overhead guard). Same best-of-N
    protocol as obs_overhead_ab."""
    best_on = best_off = 0.0
    for _ in range(trials):
        off = eager_mlp_loop(steps=steps, resilience=False)
        on = eager_mlp_loop(steps=steps, resilience=True)
        best_off = max(best_off, off['steps_per_sec'])
        best_on = max(best_on, on['steps_per_sec'])
    overhead = best_off / best_on - 1 if best_on else float('inf')
    return {
        'ft_steps_per_sec': best_on,
        'plain_steps_per_sec': best_off,
        'overhead_ratio': round(best_off / best_on, 4) if best_on else 0.0,
        'overhead_pct': round(overhead * 100, 2),
    }


def elastic_overhead_ab(steps=30, trials=3, batch=32):
    """A/B a fleet DistTrainStep driven bare vs through
    ElasticTrainLoop.step (also imported by the tier-1 overhead guard).

    The elastic per-step cost is the device-source poll + mesh
    comparison + checkpoint-interval check; the transition itself
    (checkpoint/re-mesh/restore) only happens when topology actually
    moves, so the steady-state wrapper must be ~free. Checkpoint writes
    are excluded (interval >> steps) — the guard targets the wrapper,
    not disk bandwidth."""
    import tempfile

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import fleet
    from paddle_tpu.resilience.elastic import ElasticTrainLoop

    if not fleet._fleet.initialized:
        fleet.init(is_collective=True)
    rng = np.random.RandomState(0)
    x = rng.standard_normal((batch, 64)).astype('float32')
    y = rng.randint(0, 10, (batch,))

    def loss_fn(out, lab):
        return F.cross_entropy(out, lab)

    def run(elastic):
        import time as _t
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                              nn.Linear(128, 10))
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        if elastic:
            loop = ElasticTrainLoop(model, loss_fn, opt,
                                    ckpt_dir=tempfile.mkdtemp(),
                                    ckpt_interval=10 ** 9)
            step = loop.step
        else:
            fleet.distributed_model(model)
            step = fleet.DistTrainStep(model, loss_fn, opt)
        xs, ys = paddle.to_tensor(x), paddle.to_tensor(y)
        loss = step(xs, ys)          # compile outside the timed window
        float(loss.numpy())
        t0 = _t.perf_counter()
        for _ in range(steps):
            loss = step(xs, ys)
        float(loss.numpy())          # sync
        return steps / (_t.perf_counter() - t0)

    best_on = best_off = 0.0
    ratios = []
    for _ in range(trials):
        off = run(elastic=False)
        on = run(elastic=True)
        best_off = max(best_off, off)
        best_on = max(best_on, on)
        # overhead from the MIN of adjacent-pair ratios: shared-box
        # contention noise is strictly additive and drift moves both
        # members of a pair together, so the least-noisy pair is the
        # closest to the uncontended truth (best-of-N across arms can
        # land its bests in different noise regimes and report phantom
        # overhead); a real regression shows up in every pair
        if on:
            ratios.append(off / on)
    overhead = min(ratios) - 1 if ratios else float('inf')
    return {
        'elastic_steps_per_sec': round(best_on, 1),
        'plain_steps_per_sec': round(best_off, 1),
        'overhead_ratio': round(best_off / best_on, 4) if best_on else 0.0,
        'overhead_pct': round(overhead * 100, 2),
    }


def _phase_resilience():
    """Fault-tolerance overhead phase: FaultTolerantStep wrapper on vs
    off on the eager hot path, plus the elastic-wrapper A/B on the
    fleet step (mirrors the obs phase; tier-1 guards each ratio under
    3% on CPU)."""
    out = {}
    try:
        out['resilience_overhead'] = resilience_overhead_ab()
    except Exception as e:
        print(f'# resilience bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['resilience_overhead'] = {'error': type(e).__name__}
    try:
        out['elastic_overhead'] = elastic_overhead_ab()
    except Exception as e:
        print(f'# elastic bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['elastic_overhead'] = {'error': type(e).__name__}
    return out


def serving_trace(num_requests=24, seed=0, vocab=512):
    """Deterministic mixed-length request trace for the serving A/B:
    (prompt tokens, max_new_tokens) pairs cycling through a few length
    buckets so both arms compile a bounded shape set."""
    rng = np.random.RandomState(seed)
    lens = [4, 7, 12, 15, 20, 28]
    news = [32, 40, 48]
    return [(rng.randint(0, vocab, (lens[i % len(lens)],)).tolist(),
             news[i % len(news)])
            for i in range(num_requests)]


def serving_ab(num_requests=24, num_slots=12, max_length=96, decode_block=8,
               trials=3):
    """Continuous batching vs a sequential `generate()` loop on a
    mixed-length trace (also imported by the tier-1 serving guard).

    Both arms decode the SAME requests greedily with eos disabled (fixed
    token counts — a throughput comparison, not an early-exit lottery).
    Reports tokens/sec for each arm, the speedup, engine mean TTFT, and
    two correctness fields the tier-1 test asserts: `parity` (engine
    tokens bit-identical to per-request generate()) and
    `recompiles_after_warmup` (compile-trace growth across the timed
    run — continuous batching must admit into freed slots without
    recompiling).

    The model is deliberately weight-heavy for its size (h=256, 4L —
    ~3M params, past L2): single-stream decode is then memory-bound on
    weight streaming, so batched slots amortize each weight read — the
    same physics that makes continuous batching the serving unlock on
    real accelerators. (At toy widths the weights sit in cache and
    batching shows nothing.)"""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=384, num_hidden_layers=4,
                    num_attention_heads=4, max_position_embeddings=128,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForCausalLM(cfg).eval()
    trace = serving_trace(num_requests, vocab=cfg.vocab_size)
    params = [SamplingParams(max_new_tokens=mn, eos_token_id=-1)
              for _, mn in trace]
    prompts = [p for p, _ in trace]

    # --- sequential arm: one generate() call per request ----------------
    def run_sequential():
        outs = []
        for p, mn in trace:
            out, _ = model.generate(
                paddle.to_tensor(np.array([p])), max_new_tokens=mn,
                decode_strategy='greedy_search', eos_token_id=-1)
            outs.append(out.numpy()[0].tolist())
        return outs

    expected = run_sequential()          # compile + warm every shape
    best_seq = float('inf')
    for _ in range(trials):
        t0 = time.perf_counter()
        run_sequential()
        best_seq = min(best_seq, time.perf_counter() - t0)

    # --- engine arm: ONE engine, warmed, timed over the same trace ------
    engine = InferenceEngine(model, num_slots=num_slots,
                             max_length=max_length,
                             decode_block=decode_block)
    engine.generate_many(prompts[:num_slots + 1],
                         params[:num_slots + 1])   # warm all buckets
    traces_after_warmup = dict(engine.stats()['traces'])
    best_eng, handles = float('inf'), None
    for _ in range(trials):
        engine.reset_stats()
        t0 = time.perf_counter()
        hs = engine.generate_many(prompts, params)
        dt = time.perf_counter() - t0
        if dt < best_eng:
            best_eng, handles = dt, hs

    tokens = sum(mn for _, mn in trace)
    got = [h.tokens for h in handles]
    parity = got == expected
    recompiles = sum(engine.stats()['traces'].values()) \
        - sum(traces_after_warmup.values())
    ttfts = [h.ttft for h in handles if h.ttft is not None]
    return {
        'engine_tokens_per_sec': round(tokens / best_eng, 1),
        'sequential_tokens_per_sec': round(tokens / best_seq, 1),
        'speedup': round(best_seq / best_eng, 2),
        'mean_ttft_ms': round(sum(ttfts) / len(ttfts) * 1e3, 2),
        'num_requests': num_requests, 'num_slots': num_slots,
        'decode_block': decode_block, 'tokens': tokens,
        'parity': parity,
        'recompiles_after_warmup': recompiles,
    }


def _serving_model(max_pos=128):
    """The weight-heavy serving-bench GPT (see serving_ab's physics
    note: single-stream decode is weight-streaming-bound at this width,
    so batching/speculation/caching effects measure what they measure
    on real accelerators)."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=384, num_hidden_layers=4,
                    num_attention_heads=4, max_position_embeddings=max_pos,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    return GPTForCausalLM(cfg).eval()


def _ref_outputs(model, trace):
    """Per-request generate() greedy references for an engine trace."""
    import paddle_tpu as paddle
    outs = []
    for p, mn in trace:
        out, _ = model.generate(
            paddle.to_tensor(np.array([p])), max_new_tokens=mn,
            decode_strategy='greedy_search', eos_token_id=-1)
        outs.append(out.numpy()[0].tolist())
    return outs


def prefix_trace(num_requests=16, system_len=48, seed=0, vocab=512):
    """Shared-system-prompt trace: every request is the SAME system_len
    prefix + a short unique suffix — the production shape RadixAttention
    targets (the prefix cache should collapse prefill to suffixes)."""
    rng = np.random.RandomState(seed)
    system = rng.randint(0, vocab, (system_len,)).tolist()
    suffix_lens = [4, 10, 7, 13]
    news = [24, 32]
    return [(system + rng.randint(0, vocab,
                                  (suffix_lens[i % 4],)).tolist(),
             news[i % 2])
            for i in range(num_requests)]


def prefix_ab(num_requests=12, num_slots=16, max_length=96,
              decode_block=8, system_len=48, trials=2):
    """Prefix-cache A/B on the shared-system-prompt trace (also imported
    by the tier-1 prefix guard): the same engine config with the radix
    cache off (cold: every prompt prefills in full) vs on (the system
    prefix prefills once; later requests gather the retained KV row and
    prefill only their suffix). Reports the prefill-token reduction and
    the TTFT ratio, plus the tier-1 fields: bit-exact greedy parity vs
    per-request generate() on BOTH arms and zero recompiles across the
    timed trace.

    Measured at SUB-SATURATION concurrency (slots >= burst + retention
    budget) — the TTFT-sensitive regime the cache targets, where every
    admission's prefill is on the first-token critical path. At full
    slot saturation, retained entries displace decode concurrency
    instead (the decode block computes every slot each round, occupied
    or not), so the win shrinks: size num_slots = target concurrency +
    retention budget (the README runbook)."""
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    model = _serving_model()
    trace = prefix_trace(num_requests, system_len=system_len)
    prompts = [p for p, _ in trace]
    params = [SamplingParams(max_new_tokens=mn, eos_token_id=-1)
              for _, mn in trace]
    expected = _ref_outputs(model, trace)
    tokens = sum(mn for _, mn in trace)

    def run(cache_on):
        eng = InferenceEngine(
            model, num_slots=num_slots, max_length=max_length,
            decode_block=decode_block,
            prefix_cache=0.25 if cache_on else None)
        # warmup compiles every program the trace needs AND seeds the
        # cache: request 0 alone first (inserts happen at retirement,
        # so a concurrent warmup wave would all miss), then a wave that
        # HITS it — compiling both suffix chunk buckets and the
        # full-prompt-hit row copy
        eng.generate_many(prompts[:1], params[:1])
        eng.generate_many(prompts[:4], params[:4])
        warm_traces = dict(eng.stats()['traces'])
        best = None
        for _ in range(trials):
            eng.reset_stats()
            t0 = time.perf_counter()
            hs = eng.generate_many(prompts, params)
            dt = time.perf_counter() - t0
            if best is None or dt < best[0]:
                best = (dt, hs, dict(eng.stats()))
        dt, hs, st = best
        ttfts = sorted(h.ttft for h in hs)
        return {
            'dt': dt, 'parity': [h.tokens for h in hs] == expected,
            'recompiles': sum(eng.stats()['traces'].values())
            - sum(warm_traces.values()),
            'prefill_tokens': st['prefill_tokens'],
            'ttft_mean_ms': sum(ttfts) / len(ttfts) * 1e3,
            'ttft_p50_ms': ttfts[len(ttfts) // 2] * 1e3,
            'stats': st,
        }

    cold = run(cache_on=False)
    cached = run(cache_on=True)
    px = cached['stats'].get('prefix_cache', {})
    reduction = (1 - cached['prefill_tokens'] / cold['prefill_tokens']
                 if cold['prefill_tokens'] else 0.0)
    return {
        'prefill_tokens_cold': cold['prefill_tokens'],
        'prefill_tokens_cached': cached['prefill_tokens'],
        'prefill_token_reduction': round(reduction, 4),
        'ttft_mean_ms_cold': round(cold['ttft_mean_ms'], 2),
        'ttft_mean_ms_cached': round(cached['ttft_mean_ms'], 2),
        'ttft_ratio': round(cached['ttft_mean_ms']
                            / cold['ttft_mean_ms'], 4)
        if cold['ttft_mean_ms'] else 0.0,
        'tokens_per_sec_cold': round(tokens / cold['dt'], 1),
        'tokens_per_sec_cached': round(tokens / cached['dt'], 1),
        'cache_hits': px.get('hits', 0),
        'cache_tokens_reused': px.get('tokens_reused', 0),
        'parity': cold['parity'] and cached['parity'],
        'recompiles_after_warmup': cold['recompiles']
        + cached['recompiles'],
        'num_requests': num_requests, 'system_len': system_len,
    }


def chunked_ab(num_short=10, long_len=224, short_len=6, short_new=16,
               long_new=8, num_slots=12, max_length=256, decode_block=2,
               chunk=32, trials=2):
    """Chunked-prefill A/B on the long-plus-shorts trace (also imported
    by the tier-1 chunk guard): ONE long prompt arrives first, then
    many short requests. Unchunked, every short request's TTFT eats the
    whole long prefill (head-of-line); chunked, the long prompt
    prefills one bucket-shaped chunk per decode round and the shorts
    start streaming immediately. Reports p50 short-request TTFT for
    both arms; tier-1 guards parity + zero recompiles (the latency
    ratio is asserted on the full bench run, where the gap is x-large,
    not in the noise-prone tier-1 environment)."""
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    model = _serving_model(max_pos=max_length)
    rng = np.random.RandomState(3)
    vocab = model.config.vocab_size
    long_prompt = rng.randint(0, vocab, (long_len,)).tolist()
    shorts = [rng.randint(0, vocab, (short_len,)).tolist()
              for _ in range(num_short)]
    trace = [(long_prompt, long_new)] + [(p, short_new) for p in shorts]
    prompts = [p for p, _ in trace]
    params = [SamplingParams(max_new_tokens=mn, eos_token_id=-1)
              for _, mn in trace]
    expected = _ref_outputs(model, trace)

    def run(chunk_on):
        eng = InferenceEngine(
            model, num_slots=num_slots, max_length=max_length,
            decode_block=decode_block,
            prefill_chunk_tokens=chunk if chunk_on else None)
        eng.generate_many(prompts[:2], params[:2])   # warm both shapes
        warm_traces = dict(eng.stats()['traces'])
        best = None
        for _ in range(trials):
            eng.reset_stats()
            hs = [eng.submit(p, sp) for p, sp in zip(prompts, params)]
            eng.run()
            short_ttfts = sorted(h.ttft for h in hs[1:])
            sample = (short_ttfts[len(short_ttfts) // 2],
                      hs[0].ttft, hs)
            if best is None or sample[0] < best[0]:
                best = sample
        p50_short, long_ttft, hs = best
        return {
            'p50_short_ttft_ms': p50_short * 1e3,
            'long_ttft_ms': long_ttft * 1e3,
            'parity': [h.tokens for h in hs] == expected,
            'recompiles': sum(eng.stats()['traces'].values())
            - sum(warm_traces.values()),
            'chunk_rounds': eng.stats()['chunk_rounds'],
        }

    plain = run(chunk_on=False)
    chunked = run(chunk_on=True)
    return {
        'p50_short_ttft_ms_unchunked': round(plain['p50_short_ttft_ms'],
                                             2),
        'p50_short_ttft_ms_chunked': round(chunked['p50_short_ttft_ms'],
                                           2),
        'short_ttft_ratio': round(chunked['p50_short_ttft_ms']
                                  / plain['p50_short_ttft_ms'], 4)
        if plain['p50_short_ttft_ms'] else 0.0,
        'long_ttft_ms_unchunked': round(plain['long_ttft_ms'], 2),
        'long_ttft_ms_chunked': round(chunked['long_ttft_ms'], 2),
        'chunk_rounds': chunked['chunk_rounds'],
        'parity': plain['parity'] and chunked['parity'],
        'recompiles_after_warmup': plain['recompiles']
        + chunked['recompiles'],
        'long_len': long_len, 'num_short': num_short, 'chunk': chunk,
    }


def distill_draft(model, sequences, hidden=128, steps=150, lr=3e-3,
                  seed=123):
    """Train a 1-layer draft on the TARGET's own greedy continuations —
    the standard draft-model construction (distill on the serving
    distribution) shrunk to bench scale. Returns the draft in eval()."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM

    cfg = model.config
    paddle.seed(seed)
    draft = GPTForCausalLM(GPTConfig(
        vocab_size=cfg.vocab_size, hidden_size=hidden,
        num_hidden_layers=1, num_attention_heads=4,
        intermediate_size=2 * hidden,
        max_position_embeddings=cfg.max_position_embeddings,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=draft.parameters())
    step = TrainStep(
        draft,
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, cfg.vocab_size]),
            labels[:, 1:].reshape([-1])),
        opt)
    seqs = np.asarray(sequences, np.int32)
    for _ in range(steps):
        step(seqs, seqs)
    return draft.eval()


def spec_ab(num_requests=12, prompt_len=12, max_new=32, num_slots=6,
            max_length=96, decode_block=8, k=4, distill_steps=150,
            trials=2):
    """Speculative-decoding A/B (also imported by the tier-1 spec
    guard): the same continuous-batching trace decoded by a plain
    engine vs a speculating one whose 1-layer draft was distilled on
    the target's greedy continuations of these prompts (the
    draft-for-the-serving-distribution construction). The weight-heavy
    target makes each decode round weight-streaming-bound, so a
    k+1-position verify costs about one round — accepted drafts are
    nearly free tokens. Reports acceptance rate and the tokens/sec
    ratio (>= 1 on the full bench run); tier-1 guards bit-exact greedy
    parity + zero recompiles + nonzero acceptance."""
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    model = _serving_model()
    rng = np.random.RandomState(11)
    vocab = model.config.vocab_size
    prompts = [rng.randint(0, vocab, (prompt_len,)).tolist()
               for _ in range(num_requests)]
    trace = [(p, max_new) for p in prompts]
    params = [SamplingParams(max_new_tokens=max_new, eos_token_id=-1)
              for _ in trace]
    expected = _ref_outputs(model, trace)
    sequences = [p + out for (p, _), out in zip(trace, expected)]
    draft = distill_draft(model, sequences, steps=distill_steps)
    tokens = sum(mn for _, mn in trace)

    def run(draft_model):
        eng = InferenceEngine(
            model, num_slots=num_slots, max_length=max_length,
            decode_block=decode_block, draft_model=draft_model,
            num_draft_tokens=k)
        eng.generate_many(prompts[:2], params[:2])
        warm_traces = dict(eng.stats()['traces'])
        best = None
        for _ in range(trials):
            eng.reset_stats()
            t0 = time.perf_counter()
            hs = eng.generate_many(prompts, params)
            dt = time.perf_counter() - t0
            if best is None or dt < best[0]:
                best = (dt, hs, dict(eng.stats()))
        dt, hs, st = best
        return {
            'dt': dt,
            'parity': [h.tokens for h in hs] == expected,
            'recompiles': sum(eng.stats()['traces'].values())
            - sum(warm_traces.values()),
            'stats': st,
        }

    plain = run(None)
    spec = run(draft)
    sp = spec['stats'].get('spec', {})
    return {
        'tokens_per_sec_plain': round(tokens / plain['dt'], 1),
        'tokens_per_sec_spec': round(tokens / spec['dt'], 1),
        'speedup': round(plain['dt'] / spec['dt'], 4),
        'acceptance_rate': round(sp.get('acceptance_rate', 0.0), 4),
        'spec_rounds': sp.get('rounds', 0),
        'k': k, 'distill_steps': distill_steps,
        'parity': plain['parity'] and spec['parity'],
        'recompiles_after_warmup': plain['recompiles']
        + spec['recompiles'],
        'num_requests': num_requests, 'tokens': tokens,
    }


def stack_ab(num_requests=12, num_slots=10, max_length=96,
             decode_block=4, chunk=16, k=3, system_len=24):
    """The COMPOSED latency stack (also imported by the tier-1 stack
    guard): prefix cache + chunked prefill + speculative decoding all
    enabled on one engine, driven over a mixed trace — shared-prefix
    prompts, chunk-spanning prompts, greedy AND seeded-sampling
    requests. The guard fields: greedy outputs bit-identical to
    per-request generate(), and compiles after warmup zero by BOTH
    counters (python trace counts and `paddle_jit_compiles_total`)."""
    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    model = _serving_model()
    paddle.seed(5)
    draft = GPTForCausalLM(GPTConfig(
        vocab_size=model.config.vocab_size, hidden_size=96,
        num_hidden_layers=1, num_attention_heads=4,
        intermediate_size=192,
        max_position_embeddings=model.config.max_position_embeddings,
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)).eval()
    rng = np.random.RandomState(17)
    vocab = model.config.vocab_size
    system = rng.randint(0, vocab, (system_len,)).tolist()
    suffix_lens = [3, 30, 9, 44]     # short suffixes AND chunk-spanners
    trace = []
    for i in range(num_requests):
        prompt = system + rng.randint(
            0, vocab, (suffix_lens[i % 4],)).tolist()
        if i % 3 == 2:
            sp = SamplingParams(max_new_tokens=10, strategy='sampling',
                                temperature=1.2, top_k=32, seed=i,
                                eos_token_id=-1)
        else:
            sp = SamplingParams(max_new_tokens=12, eos_token_id=-1)
        trace.append((prompt, sp))
    greedy_refs = {
        i: _ref_outputs(model, [(p, sp.max_new_tokens)])[0]
        for i, (p, sp) in enumerate(trace) if sp.strategy != 'sampling'}

    eng = InferenceEngine(
        model, num_slots=num_slots, max_length=max_length,
        decode_block=decode_block, prefix_cache=0.3,
        prefill_chunk_tokens=chunk, draft_model=draft,
        num_draft_tokens=k)
    # warmup: seed the cache, then a wave touching every program shape
    # (chunk buckets, suffix hits, full hit, spec round, draft buckets)
    eng.generate_many([trace[0][0]], [trace[0][1]])
    eng.generate_many([p for p, _ in trace[:5]],
                      [sp for _, sp in trace[:5]])
    warm_traces = dict(eng.stats()['traces'])
    reg = obs.get_registry()
    compiles0 = reg.value('paddle_jit_compiles_total')

    eng.reset_stats()
    t0 = time.perf_counter()
    handles = eng.generate_many([p for p, _ in trace],
                                [sp for _, sp in trace])
    dt = time.perf_counter() - t0
    parity = all(handles[i].tokens == ref
                 for i, ref in greedy_refs.items())
    st = eng.stats()
    return {
        'parity': parity,
        'recompiles_after_warmup': sum(eng.stats()['traces'].values())
        - sum(warm_traces.values()),
        'jit_compiles_delta': reg.value('paddle_jit_compiles_total')
        - compiles0,
        'tokens_per_sec': round(sum(len(h.tokens) for h in handles)
                                / dt, 1),
        'completed': sum(1 for h in handles if h.status == 'FINISHED'),
        'prefix_hits': st['prefix_cache']['hits'],
        'chunk_rounds': st['chunk_rounds'],
        'spec_acceptance': round(st['spec']['acceptance_rate'], 4),
        'num_requests': num_requests,
    }


# int8 KV quality bound: relative decode-logit RMSE vs the float32 cache,
# measured by paged_int8_rmse below and documented in the README Paged-KV
# section. Guarded in tier-1 (tests/test_paged_kv.py) with the same value.
PAGED_INT8_RMSE_BOUND = 0.05


def paged_int8_rmse(prompt_len=56, steps=8, page_size=16, seed=0):
    """Teacher-forced decode-logit drift for int8 KV: prefill one prompt
    through the shared `cached_forward` contract, roundtrip the KV slab
    page-wise through the per-(page, head) absmax int8 path (exactly
    what the quantized paged pool stores), then decode `steps` tokens
    against BOTH caches teacher-forced on the float32 greedy trajectory.
    Reports absolute and relative logit RMSE — the README's documented
    int8 quality bound (relative RMSE <= PAGED_INT8_RMSE_BOUND) is the
    number this function measures. The quantized arm re-roundtrips its
    cache after every step, matching the pool (every settled page lives
    in int8; nothing stays float between rounds)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.jit import functional_state
    from paddle_tpu.nlp.generation import cached_forward
    from paddle_tpu.quantization import (kv_dequantize_page,
                                         kv_page_scales, kv_quantize_page)

    model = _serving_model()
    params, frozen, buffers = functional_state(model)
    fwd = cached_forward(model, params, frozen, buffers)
    maxlen = -(-(prompt_len + steps) // page_size) * page_size

    def roundtrip(cache):
        def rt(leaf):
            b, length, h, d = leaf.shape
            pages = leaf.reshape(b * (length // page_size),
                                 page_size, h, d)
            scales = kv_page_scales(pages)
            dq = kv_dequantize_page(
                kv_quantize_page(pages, scales), scales, leaf.dtype)
            return dq.reshape(leaf.shape)
        return jax.tree_util.tree_map(rt, cache)

    rng = np.random.RandomState(seed)
    ids = jnp.asarray(rng.randint(0, model.config.vocab_size,
                                  (1, prompt_len)), jnp.int32)
    cache = model.init_cache(1, maxlen)
    logits, cache = fwd(ids, cache, jnp.int32(0), jnp.int32(0), None)
    cache_q = roundtrip(cache)
    k_pos = jnp.arange(maxlen, dtype=jnp.int32)

    tok = int(np.asarray(logits[0, -1]).argmax())
    sq_err = sq_ref = 0.0
    agree = 0
    for i in range(steps):
        pos = jnp.full((1,), prompt_len + i, jnp.int32)
        mask = (k_pos[None, :] <= pos[:, None])[:, None, None, :]
        tok_dev = jnp.full((1, 1), tok, jnp.int32)
        la, cache = fwd(tok_dev, cache, pos, pos, mask)
        lq, cache_q = fwd(tok_dev, cache_q, pos, pos, mask)
        cache_q = roundtrip(cache_q)
        la = np.asarray(la[0, -1], np.float64)
        lq = np.asarray(lq[0, -1], np.float64)
        sq_err += float(((la - lq) ** 2).sum())
        sq_ref += float(((la - la.mean()) ** 2).sum())
        agree += int(la.argmax() == lq.argmax())
        tok = int(la.argmax())      # teacher-force the float32 path
    n = steps * la.shape[-1]
    rmse = (sq_err / n) ** 0.5
    rel = (sq_err / sq_ref) ** 0.5 if sq_ref else 0.0
    return {
        'logit_rmse': round(rmse, 6),
        'logit_rmse_rel': round(rel, 6),
        'rmse_bound': PAGED_INT8_RMSE_BOUND,
        'within_bound': rel <= PAGED_INT8_RMSE_BOUND,
        'greedy_agree_rate': round(agree / steps, 4),
        'prompt_len': prompt_len, 'steps': steps,
    }


def paged_ab(num_requests=12, system_len=48, max_length=96,
             decode_block=8, page_size=16, cap_requests=24, trials=2):
    """Row-vs-paged KV A/B at EQUAL HBM budget (also imported by the
    tier-1 paged guard). Both arms get the same number of KV rows:
    the row arm as 4 monolithic max_length slots, the paged arm as
    (4 * max_length / page_size) pages shared by 16 seats — the pool
    byte counts are asserted equal-or-better so the comparison is
    capacity-per-byte, never extra memory.

    Three sections:
    - capacity: a burst of short requests is submitted to both arms and
      stepped once; the row arm seats at most its 4 slots (every seat
      strands max_length - ~14 rows), the paged arm seats one page per
      request — the >= 3x concurrent-admission acceptance bar.
    - throughput/reuse: the shared-system-prompt trace (prefix_trace)
      with the prefix cache on in both arms. The paged arm retains the
      system prefix as SHARED pages (COW refcounts) instead of a whole
      retained slot, so reuse survives at equal HBM. Reports tokens/sec,
      prefill tokens reused, bit-exact greedy parity vs generate(), and
      zero recompiles after warmup per arm.
    - int8: the paged_int8_rmse teacher-forced logit-drift measurement
      for the quantized-KV mode, with the documented bound.
    """
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    model = _serving_model()
    vocab = model.config.vocab_size
    kv_pages = (4 * max_length) // page_size
    row_kw = dict(num_slots=4, max_length=max_length,
                  decode_block=decode_block)
    paged_kw = dict(num_slots=16, max_length=max_length,
                    decode_block=decode_block,
                    kv_page_size=page_size, kv_pages=kv_pages)

    # --- capacity: short-request burst, peak seats after one step ----
    # each request spans exactly ONE page (prompt + max_new == page
    # size) and outlives the first decode block, so seats are read
    # while everyone is still resident
    cap_new = decode_block + 4
    cap_len = max(1, page_size - cap_new)
    rng = np.random.RandomState(11)
    cap_prompts = [rng.randint(0, vocab, (cap_len,)).tolist()
                   for _ in range(cap_requests)]

    def capacity(kw):
        eng = InferenceEngine(model, **kw)
        hs = [eng.submit(p, SamplingParams(max_new_tokens=cap_new,
                                           eos_token_id=-1))
              for p in cap_prompts]
        eng.step()
        seated = eng.pool.used_count
        eng.run()
        done = sum(1 for h in hs if h.status == 'FINISHED')
        return seated, done, eng.pool.pool_bytes

    row_seated, row_done, row_bytes = capacity(row_kw)
    paged_seated, paged_done, paged_bytes = capacity(paged_kw)

    # --- throughput + prefill reuse on the shared-prefix trace -------
    trace = prefix_trace(num_requests, system_len=system_len,
                         vocab=vocab)
    prompts = [p for p, _ in trace]
    sparams = [SamplingParams(max_new_tokens=mn, eos_token_id=-1)
               for _, mn in trace]
    expected = _ref_outputs(model, trace)
    tokens = sum(mn for _, mn in trace)

    def run_arm(kw):
        eng = InferenceEngine(model, prefix_cache=0.25, **kw)
        # warmup: request 0 alone seeds the cache (inserts happen at
        # retirement), then a wave that HITS it — compiling the suffix
        # chunk buckets, the hit path, and the decode step
        eng.generate_many(prompts[:1], sparams[:1])
        eng.generate_many(prompts[:4], sparams[:4])
        warm = dict(eng.stats()['traces'])
        best = None
        for _ in range(trials):
            eng.reset_stats()
            t0 = time.perf_counter()
            hs = eng.generate_many(prompts, sparams)
            dt = time.perf_counter() - t0
            if best is None or dt < best[0]:
                best = (dt, hs, dict(eng.stats()))
        dt, hs, st = best
        return {
            'dt': dt, 'parity': [h.tokens for h in hs] == expected,
            'recompiles': sum(eng.stats()['traces'].values())
            - sum(warm.values()),
            'reused': st.get('prefix_cache', {}).get('tokens_reused', 0),
        }

    row = run_arm(row_kw)
    paged = run_arm(paged_kw)

    return {
        'row_pool_bytes': row_bytes,
        'paged_pool_bytes': paged_bytes,
        'equal_hbm': paged_bytes <= row_bytes,
        'concurrent_row': row_seated,
        'concurrent_paged': paged_seated,
        'capacity_ratio': round(paged_seated / row_seated, 2)
        if row_seated else 0.0,
        'cap_completed': min(row_done, paged_done),
        'tokens_per_sec_row': round(tokens / row['dt'], 1),
        'tokens_per_sec_paged': round(tokens / paged['dt'], 1),
        'prefill_reuse_row': row['reused'],
        'prefill_reuse_paged': paged['reused'],
        'parity': row['parity'] and paged['parity'],
        'recompiles_after_warmup': row['recompiles']
        + paged['recompiles'],
        'int8': paged_int8_rmse(page_size=page_size),
        'num_requests': num_requests, 'cap_requests': cap_requests,
        'page_size': page_size, 'kv_pages': kv_pages,
    }


def adapter_ab(num_adapters=3, requests_per_group=3, num_slots=4,
               max_length=96, decode_block=8, max_new=12, trials=2):
    """Heterogeneous-adapter batched-decode A/B (also imported by the
    tier-1 adapter guard). One base GPT + `num_adapters` LoRA adapters
    in a packed `AdapterBank`, over a deterministic mixed trace that
    round-robins base + every adapter. Three guard fields:

    - parity: every request's greedy output in the MIXED batch is
      bit-identical to running its adapter alone on a fresh
      single-adapter engine (base requests check against generate()).
    - zero recompiles after warmup — by python trace counters AND
      `paddle_jit_compiles_total` — across arbitrary adapter mixes
      AND a store-backed hot-swap (publish v2 of one adapter mid-run:
      new pins pick it up, outputs under it change, nothing retraces).
    - throughput: the mixed batch beats sequential per-adapter group
      serving on tokens/sec (homogeneous groups under-fill the slots;
      the packed bank lets one decode wave serve any mix).
    """
    import shutil
    import tempfile

    from paddle_tpu import observability as obs
    from paddle_tpu.serving import (AdapterBank, InferenceEngine,
                                    SamplingParams, make_adapter_factors)

    store_dir = tempfile.mkdtemp(prefix='adapter_bench_')
    try:
        model = _serving_model()
        vocab = model.config.vocab_size
        ids = [None] + [f'ad{i}' for i in range(num_adapters)]
        bank = AdapterBank(model, capacity=num_adapters + 1, rank=8,
                           store_dir=store_dir)
        for i, aid in enumerate(ids[1:]):
            bank.load(aid, make_adapter_factors(bank, seed=i + 1))

        # deterministic mixed trace: round-robin base + every adapter
        rng = np.random.RandomState(23)
        plens = [5, 11, 8, 14]
        trace = []
        for i in range(len(ids) * requests_per_group):
            prompt = rng.randint(1, vocab, (plens[i % 4],)).tolist()
            trace.append((prompt, ids[i % len(ids)]))
        sp = SamplingParams(max_new_tokens=max_new, eos_token_id=-1)

        # alone references: each adapter on a FRESH single-adapter
        # engine (identical weights — _serving_model reseeds), base
        # against per-request generate()
        expected = {}
        for gi, aid in enumerate(ids):
            group = [(j, p) for j, (p, a) in enumerate(trace) if a == aid]
            if aid is None:
                refs = _ref_outputs(model, [(p, max_new) for _, p in group])
                for (j, _), ref in zip(group, refs):
                    expected[j] = ref
                continue
            m = _serving_model()
            b = AdapterBank(m, capacity=2, rank=8)
            b.load(aid, make_adapter_factors(b, seed=gi))
            e = InferenceEngine(m, num_slots=num_slots,
                                max_length=max_length,
                                decode_block=decode_block, adapter_bank=b)
            for j, p in group:
                h = e.submit(p, sp, adapter_id=aid)
                e.run()
                expected[j] = h.tokens

        eng = InferenceEngine(model, num_slots=num_slots,
                              max_length=max_length,
                              decode_block=decode_block, adapter_bank=bank)

        def run_mixed(order=None):
            picks = order if order is not None else range(len(trace))
            t0 = time.perf_counter()
            hs = {j: eng.submit(trace[j][0], sp, adapter_id=trace[j][1])
                  for j in picks}
            eng.run()
            return time.perf_counter() - t0, hs

        # warmup covers every prompt bucket under every adapter, then
        # both compile counters must stay FLAT to the end
        run_mixed()
        warm = dict(eng.stats()['traces'])
        reg = obs.get_registry()
        compiles0 = reg.value('paddle_jit_compiles_total')

        best_mixed, hs = min((run_mixed() for _ in range(trials)),
                             key=lambda t: t[0])
        parity = all(hs[j].tokens == expected[j] for j in hs)

        # a PERMUTED mix, still zero recompiles
        perm = list(reversed(range(len(trace))))
        _, hs_perm = run_mixed(perm)
        parity = parity and all(hs_perm[j].tokens == expected[j]
                                for j in hs_perm)

        # sequential per-adapter-group serving: same engine, same
        # requests, but homogeneous waves (what an engine without
        # heterogeneous batching is forced into)
        def run_sequential():
            t0 = time.perf_counter()
            for aid in ids:
                for j, (p, a) in enumerate(trace):
                    if a == aid:
                        eng.submit(p, sp, adapter_id=aid)
                eng.run()
            return time.perf_counter() - t0

        best_seq = min(run_sequential() for _ in range(trials))

        # store-backed hot-swap: publish ad0 v2; the next pins load it
        # into a fresh slot — outputs under ad0 change, every other
        # request stays bit-exact, and NOTHING retraces
        bank.publish('ad0', make_adapter_factors(bank, seed=101))
        _, hs_swap = run_mixed()
        swap_changed = any(hs_swap[j].tokens != expected[j]
                           for j in hs_swap if trace[j][1] == 'ad0')
        swap_others_exact = all(hs_swap[j].tokens == expected[j]
                                for j in hs_swap if trace[j][1] != 'ad0')

        tokens = len(trace) * max_new
        return {
            'parity': parity,
            'recompiles_after_warmup': sum(eng.stats()['traces'].values())
            - sum(warm.values()),
            'jit_compiles_delta': reg.value('paddle_jit_compiles_total')
            - compiles0,
            'tokens_per_sec_mixed': round(tokens / best_mixed, 1),
            'tokens_per_sec_sequential': round(tokens / best_seq, 1),
            'mixed_speedup': round(best_seq / best_mixed, 2),
            'hot_swap_outputs_changed': swap_changed,
            'hot_swap_others_bit_exact': swap_others_exact,
            'num_adapters': num_adapters,
            'num_requests': len(trace),
            'bank': bank.stats(),
        }
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def adapters_smoke(duration_s=4.0, rate=8.0, seed=77, time_scale=0.2):
    """Tier-1 smoke (`bench.py adapters --smoke`): a deterministic
    mixed-adapter loadgen trace — two tenants, one with a per-tenant
    adapter mix, one pure base — replayed through a Router onto a
    bank-backed engine. The guard asserts the trace is bit-identical
    across two builds from the same seed (adapter draws included),
    zero requests dropped, and at least two different adapters
    actually served."""
    from paddle_tpu.loadgen import (FixedLength, LoadReplayer,
                                    PoissonSchedule, TenantClass,
                                    make_trace, trace_stats)
    from paddle_tpu.serving import (AdapterBank, InferenceEngine,
                                    PRIORITY_HIGH, PRIORITY_LOW,
                                    Replica, Router,
                                    make_adapter_factors)
    from paddle_tpu.serving.tenancy import TenantRegistry

    model = _serving_model()
    bank = AdapterBank(model, capacity=4, rank=8)
    bank.load('ad0', make_adapter_factors(bank, seed=1))
    bank.load('ad1', make_adapter_factors(bank, seed=2))
    eng = InferenceEngine(model, num_slots=4, max_length=96,
                          decode_block=8, adapter_bank=bank)

    tenants = [
        TenantClass(name='paid', weight=2.0, priority=PRIORITY_HIGH,
                    adapters=(('ad0', 2.0), ('ad1', 1.0), (None, 1.0))),
        TenantClass(name='free', weight=1.0, priority=PRIORITY_LOW),
    ]
    kw = dict(schedule=PoissonSchedule(rate), duration_s=duration_s,
              seed=seed, prompt_lengths=FixedLength(8),
              output_lengths=FixedLength(6), tenants=tenants,
              vocab_size=model.config.vocab_size)
    trace = make_trace(**kw)
    deterministic = make_trace(**kw) == trace

    reg = TenantRegistry()
    reg.add('paid', priority=PRIORITY_HIGH)
    reg.add('free', priority=PRIORITY_LOW)
    router = Router([Replica(0, eng)], tenants=reg)
    report = LoadReplayer(router, trace, time_scale=time_scale,
                          max_wall_s=60.0).run().report(slo_ttft_s=2.0)
    stats = trace_stats(trace)
    return {
        'trace_deterministic': deterministic,
        'offered': report['offered'],
        'completed': report['completed'],
        'dropped': report['dropped'],
        'by_adapter': stats.get('by_adapter', {}),
        'adapters_served': len(stats.get('by_adapter', {})),
        'bank': bank.stats(),
    }


def _phase_adapters():
    """Multi-tenant adapter phase: the heterogeneous-adapter batched
    decode A/B (parity / zero-recompile / mixed-vs-sequential — the
    ISSUE 19 acceptance fields) plus the loadgen mixed-adapter smoke."""
    out = {}
    for key, fn in (('adapter_ab', adapter_ab),
                    ('adapters_smoke', adapters_smoke)):
        try:
            out[key] = fn()
        except Exception as e:
            print(f'# {key} bench failed: {type(e).__name__}: {e}',
                  file=sys.stderr)
            out[key] = {'error': type(e).__name__}
    return out


def _phase_serving():
    """Serving phase: continuous-batching throughput vs the sequential
    generate() loop, then the latency stack — prefix-cache, chunked-
    prefill, and speculative-decoding A/Bs plus the composed-stack
    guard (tier-1 guards parity + zero recompiles on each; the
    speedup/reduction/TTFT numbers are the headline serving figures)."""
    out = {}
    for key, fn in (('serving', serving_ab), ('prefix', prefix_ab),
                    ('chunked', chunked_ab), ('spec', spec_ab),
                    ('stack', stack_ab), ('paged', paged_ab)):
        try:
            out[key] = fn()
        except Exception as e:
            print(f'# {key} bench failed: {type(e).__name__}: {e}',
                  file=sys.stderr)
            out[key] = {'error': type(e).__name__}
    return out


def router_ab(num_requests=24, num_slots=6, max_length=96, decode_block=8,
              trials=2, kill_at_round=3):
    """Replicated-serving A/B on the PR-4 mixed trace (also imported by
    the tier-1 router guard). Four arms over the same weight-heavy GPT:

    - bare: one `InferenceEngine` (num_slots), no router — the overhead
      baseline.
    - router1: the same capacity behind a 1-replica `Router`; the
      no-fault overhead ratio vs bare is tier-1-guarded under 3%.
    - router2: 2 replicas x num_slots — the scaling number (2x the
      slots amortizing each weight stream; the 'add a replica, serve
      more' story).
    - chaos: 2 replicas with replica 0 fault-injected to die (transient
      UNAVAILABLE) mid-trace at decode round `kill_at_round`. Reports
      `lost_requests` — accepted requests that neither finished nor
      failed with a typed error — which the tier-1 guard pins at 0, and
      the throughput-degradation ratio vs the no-fault 2-replica run.

    Plus a `qos` section: a 1-replica overload with a protected
    high-priority tenant and a sheddable low-priority flood
    (shed_queue_depth), reporting per-class p50 TTFT and the shed
    count — the 'rejected fast, paid traffic unaffected' numbers.
    """
    import paddle_tpu as paddle
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.resilience import TransientError
    from paddle_tpu.serving import (AdmissionRejected, InferenceEngine,
                                    ReplicaSet, Router, SamplingParams)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=384, num_hidden_layers=4,
                    num_attention_heads=4, max_position_embeddings=128,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForCausalLM(cfg).eval()
    trace = serving_trace(num_requests, vocab=cfg.vocab_size)
    prompts = [p for p, _ in trace]
    params = [SamplingParams(max_new_tokens=mn, eos_token_id=-1)
              for _, mn in trace]
    tokens = sum(mn for _, mn in trace)
    eng_kw = dict(num_slots=num_slots, max_length=max_length,
                  decode_block=decode_block)

    def timed(fn):
        t0 = time.perf_counter()
        hs = fn()
        return time.perf_counter() - t0, hs

    # warm every arm first, then INTERLEAVE the timed trials: the
    # bare-vs-router overhead ratio is a few percent at most, so drift
    # between non-adjacent runs (CI neighbours, GC) must not land on
    # one arm only (same best-of-N protocol as obs_overhead_ab)
    engine = InferenceEngine(model, **eng_kw)
    engine.generate_many(prompts[:num_slots + 1], params[:num_slots + 1])
    router1 = Router(ReplicaSet(model, 1, **eng_kw))
    router1.generate_many(prompts[:num_slots + 1], params[:num_slots + 1])
    router2 = Router(ReplicaSet(model, 2, **eng_kw))
    router2.generate_many(prompts[:num_slots + 1], params[:num_slots + 1])

    best_bare = best_r1 = best_r2 = float('inf')
    r1_handles = r2_handles = None
    ratios = []
    for _ in range(trials):
        bare_dt, _hs = timed(lambda: engine.generate_many(prompts, params))
        best_bare = min(best_bare, bare_dt)
        dt, hs = timed(lambda: router1.generate_many(prompts, params))
        if dt < best_r1:
            best_r1, r1_handles = dt, hs
        # the overhead estimate pairs ADJACENT runs and takes the MIN
        # ratio: contention noise on a shared (here single-core) box is
        # strictly additive, so the least-noisy pair is the closest to
        # the uncontended truth, and drift moves both members of a pair
        # together — where best-of-N across arms can land its bests in
        # different noise regimes and report phantom overhead. A real
        # regression shows up in EVERY pair, so the min still catches it.
        ratios.append(dt / bare_dt)
        dt, hs = timed(lambda: router2.generate_many(prompts, params))
        if dt < best_r2:
            best_r2, r2_handles = dt, hs
    bare_tps = tokens / best_bare
    r1_tps = tokens / best_r1
    r2_tps = tokens / best_r2
    overhead = min(ratios) - 1

    # --- chaos arm: replica 0 dies mid-trace, failover must lose 0 ----
    rs = ReplicaSet(model, 2, **eng_kw)
    router = Router(rs)
    router.generate_many(prompts[:num_slots + 1], params[:num_slots + 1])
    calls = [0]
    victim = rs[0].engine
    real_step = victim.step

    def dying_step():
        calls[0] += 1
        if calls[0] == kill_at_round:
            raise TransientError('UNAVAILABLE: injected replica loss')
        return real_step()

    victim.step = dying_step
    try:
        t0 = time.perf_counter()
        chaos_handles = router.generate_many(prompts, params)
        chaos_dt = time.perf_counter() - t0
    finally:
        victim.step = real_step
    lost = sum(1 for h in chaos_handles
               if not (h.status == 'FINISHED'
                       or (h.status == 'FAILED' and h.error is not None)))
    chaos_tps = tokens / chaos_dt
    failed_over = sum(1 for h in chaos_handles if h.failovers)

    # --- qos arm: protected high tenant under a sheddable flood -------
    qrouter = Router(
        ReplicaSet(model, 1, **eng_kw),
        tenants=('paid:priority=high;'
                 f'free:priority=low,concurrency={max(num_slots // 2, 1)}'),
        shed_queue_depth=num_slots)
    qrouter.generate_many(prompts[:num_slots + 1], params[:num_slots + 1])
    accepted, shed = [], 0
    for i, (p, sp) in enumerate(zip(prompts, params)):
        tenant = 'paid' if i % 3 == 0 else 'free'
        try:
            accepted.append((tenant, qrouter.submit(p, sp, tenant=tenant)))
        except AdmissionRejected:
            shed += 1
        qrouter.step()    # interleave decode so the queue drains/overloads
    qrouter.run()

    def p50(vals):
        vals = sorted(vals)
        return round(vals[len(vals) // 2] * 1e3, 2) if vals else None

    qos = {
        'shed': shed,
        'accepted': len(accepted),
        'p50_ttft_ms_high': p50([h.ttft for t, h in accepted
                                 if t == 'paid' and h.ttft is not None]),
        'p50_ttft_ms_low': p50([h.ttft for t, h in accepted
                                if t == 'free' and h.ttft is not None]),
    }

    return {
        'bare_tokens_per_sec': round(bare_tps, 1),
        'router1_tokens_per_sec': round(r1_tps, 1),
        'router2_tokens_per_sec': round(r2_tps, 1),
        'scaling_2_replica': round(r2_tps / r1_tps, 2) if r1_tps else 0.0,
        'scaling_note': 'replicas share one driver thread + one CPU '
                        'here, so 2-replica scaling measures router '
                        'overhead at 2x capacity, not hardware scaling; '
                        'on a fleet each replica owns its own chips',
        'router_overhead_pct': round(overhead * 100, 2),
        'num_requests': num_requests, 'num_slots': num_slots,
        'tokens': tokens,
        'parity': ([h.tokens for h in r2_handles]
                   == [h.tokens for h in r1_handles]),
        'chaos': {
            'tokens_per_sec': round(chaos_tps, 1),
            'lost_requests': lost,
            'failed_over_requests': failed_over,
            'completed': sum(1 for h in chaos_handles
                             if h.status == 'FINISHED'),
            'failed_typed': sum(1 for h in chaos_handles
                                if h.status == 'FAILED'),
            'degradation_vs_2_replica': round(chaos_tps / r2_tps, 3)
            if r2_tps else 0.0,
        },
        'qos': qos,
    }


def _phase_router():
    """Replicated-serving phase: router overhead + 2-replica scaling +
    the chaos (replica killed mid-trace) and QoS-shedding numbers
    (tier-1 guards lost_requests == 0 and overhead < 3%)."""
    try:
        return {'router': router_ab()}
    except Exception as e:
        print(f'# router bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        return {'router': {'error': type(e).__name__}}


def coldstart_child(opts):
    """One restart measurement, run IN A FRESH PROCESS (bench.py
    --coldstart-child '<json>'): build the small GPT, preload the
    program store, then measure wall time AND XLA compile counts around
    the first train step and the first served tokens. With an empty
    store dir this is the cold arm (compiles happen inside the measured
    windows); re-run against the now-populated dir it is the warm arm —
    the tier-1 guard asserts the warm windows contain ZERO backend
    compiles (`paddle_jit_compiles_total`) for the unchanged signatures,
    and that losses/tokens are bit-identical to the cold run."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import observability as obs
    from paddle_tpu import programs
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    store_dir = opts.get('store_dir') or None
    steps = int(opts.get('steps', 3))
    vocab, seq, batch = 256, 32, 4
    if store_dir:
        programs.configure(store_dir)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    intermediate_size=256, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(
        model,
        lambda logits, labels: F.cross_entropy(
            logits[:, :-1].reshape([-1, vocab]),
            labels[:, 1:].reshape([-1])),
        opt)
    ids = ((np.random.RandomState(0).randint(0, vocab - seq, (batch, 1))
            + np.arange(seq)) % vocab)
    # warm the incidental non-store programs (RNG fold-in, host<->device
    # converts, optimizer-state zero-fills — a real resume restores opt
    # state from the checkpoint instead) OUTSIDE the measured windows so
    # the compile deltas isolate the store-owned executables — the ones
    # worth minutes at production scale
    from paddle_tpu.jit import functional_state
    _ = jax.random.fold_in(step._step_key_root, 0)
    _ = np.asarray(jnp.asarray(ids))
    _ = float(np.asarray(jnp.asarray(0.001, jnp.float32)))
    _params, _, _ = functional_state(model)
    step._opt_state = opt.init_state(_params)
    reg = obs.get_registry()

    def real_compiles(marks):
        # backend-compile ticks NOT served by the persistent XLA cache
        return int((reg.value('paddle_jit_compiles_total') - marks[0])
                   - (reg.value('paddle_jit_cache_hits_total')
                      - marks[1]))

    def marks():
        return (reg.value('paddle_jit_compiles_total'),
                reg.value('paddle_jit_cache_hits_total'))

    t0 = time.perf_counter()
    pre = programs.get_store().preload()
    m0 = marks()
    losses = [float(step(ids, ids).numpy()) for _ in range(steps)]
    train_compiles = real_compiles(m0)
    t_first_step = time.perf_counter() - t0

    model.eval()
    engine = InferenceEngine(model, num_slots=2, max_length=seq,
                             decode_block=2)
    prompts = [((np.arange(5) + 7) % vocab).tolist(),
               ((np.arange(9) + 3) % vocab).tolist()]
    t1 = time.perf_counter()
    m1 = marks()
    handles = engine.generate_many(
        prompts, [SamplingParams(max_new_tokens=6, eos_token_id=-1)] * 2)
    decode_compiles = real_compiles(m1)
    t_first_tokens = time.perf_counter() - t1

    return {
        'store_dir': store_dir,
        'preload': pre,
        'time_to_first_step_s': round(t_first_step, 4),
        'time_to_first_tokens_s': round(t_first_tokens, 4),
        'train_compiles_measured': train_compiles,
        'decode_compiles_measured': decode_compiles,
        'losses': losses,
        'tokens': [h.tokens for h in handles],
        'store': {k: v for k, v in programs.get_store().stats().items()
                  if k in ('hits_disk', 'misses', 'rejects', 'persisted',
                           'disk_entries', 'coldstart_seconds')},
    }


def coldstart_ab(steps=3, timeout_s=420):
    """A/B process restart against an empty vs populated program store
    (also imported by the tier-1 coldstart guard). Pure orchestration —
    this function never imports jax, so on a single-chip tunnel the
    child processes can attach to the device. Reports the warm/cold
    ratio of time-to-first-(step|tokens) and the two warm-path compile
    counts the guard pins to zero, plus bit-exactness of the warm run's
    losses and greedy tokens vs the cold run's."""
    import subprocess
    import tempfile

    store_dir = tempfile.mkdtemp(prefix='bench_coldstart_')

    def run_child():
        proc = subprocess.run(
            [sys.executable, __file__, '--coldstart-child',
             json.dumps({'store_dir': store_dir, 'steps': steps})],
            capture_output=True, text=True, timeout=timeout_s,
            env=dict(os.environ))
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(f'coldstart child failed: '
                               f'exit {proc.returncode}')
        return json.loads(proc.stdout.strip().splitlines()[-1])

    cold = run_child()
    warm = run_child()
    cold_work = (cold['time_to_first_step_s']
                 + cold['time_to_first_tokens_s'])
    warm_work = (warm['time_to_first_step_s']
                 + warm['time_to_first_tokens_s'])
    return {
        'cold_first_work_s': round(cold_work, 4),
        'warm_first_work_s': round(warm_work, 4),
        'warm_cold_ratio': round(cold_work / warm_work, 2)
        if warm_work else 0.0,
        'warm_train_compiles': warm['train_compiles_measured'],
        'warm_decode_compiles': warm['decode_compiles_measured'],
        'cold_train_compiles': cold['train_compiles_measured'],
        'cold_decode_compiles': cold['decode_compiles_measured'],
        'warm_loaded_from_disk': warm['preload']['loaded'],
        'warm_rejects': warm['store']['rejects'],
        'parity_losses': warm['losses'] == cold['losses'],
        'parity_tokens': warm['tokens'] == cold['tokens'],
        'steps': steps,
    }


def coldstart_overhead_ab(steps=30, trials=3):
    """A/B a jitted TrainStep loop with the program store bypassed
    (FLAGS_program_store=False — the pre-store AOT path) vs enrolled
    (memory tier; no directory), with the same min-of-adjacent-pair-
    ratios estimator as the elastic guard. The store's per-call cost
    after the first signature resolution is one dict hit either way, so
    the steady-state ratio must stay under the tier-1 3% bar."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import flags as _pflags
    from paddle_tpu.jit import TrainStep
    import paddle_tpu.nn as nn

    rng = np.random.RandomState(0)
    x = rng.standard_normal((32, 64)).astype('float32')
    y = rng.randint(0, 10, (32,))

    def run(store_on):
        import time as _t
        _pflags.set_flags({'FLAGS_program_store': bool(store_on)})
        try:
            paddle.seed(0)
            model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                                  nn.Linear(128, 10))
            opt = paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=model.parameters())
            step = TrainStep(model,
                             lambda out, lab: F.cross_entropy(out, lab),
                             opt)
            xs, ys = paddle.to_tensor(x), paddle.to_tensor(y)
            float(step(xs, ys).numpy())   # compile outside the window
            t0 = _t.perf_counter()
            for _ in range(steps):
                loss = step(xs, ys)
            float(loss.numpy())           # sync
            return steps / (_t.perf_counter() - t0)
        finally:
            _pflags.set_flags({'FLAGS_program_store': True})

    best_on = best_off = 0.0
    ratios = []
    for _ in range(trials):
        off = run(store_on=False)
        on = run(store_on=True)
        best_off = max(best_off, off)
        best_on = max(best_on, on)
        if on:
            ratios.append(off / on)
    overhead = min(ratios) - 1 if ratios else float('inf')
    return {
        'store_steps_per_sec': round(best_on, 1),
        'bypass_steps_per_sec': round(best_off, 1),
        'overhead_ratio': round(best_off / best_on, 4) if best_on else 0.0,
        'overhead_pct': round(overhead * 100, 2),
    }


def _phase_coldstart():
    """Cold-restart phase: empty-store vs populated-store process
    restart A/B (warm path guarded to zero XLA compiles + bit-exact),
    then the store-bypassed overhead guard. The restart A/B runs FIRST
    and entirely in subprocesses — this phase process must not touch
    the device before its children have."""
    out = {}
    try:
        out['coldstart'] = coldstart_ab()
    except Exception as e:
        print(f'# coldstart bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['coldstart'] = {'error': type(e).__name__}
    try:
        out['coldstart_overhead'] = coldstart_overhead_ab()
    except Exception as e:
        print(f'# coldstart overhead bench failed: '
              f'{type(e).__name__}: {e}', file=sys.stderr)
        out['coldstart_overhead'] = {'error': type(e).__name__}
    return out


def goodput_overhead_ab(steps=30, trials=3):
    """Goodput-ledger on vs off A/B on the instrumented eager MLP loop
    (also imported by the tier-1 <3% overhead guard). Both arms run the
    SAME instrumentation (spans + StepTelemetry); only the ledger's
    EventLog listener toggles — so the ratio isolates what the ledger's
    interval bookkeeping costs the hot path. Min-of-adjacent-pair
    ratios, same estimator as the scrape guard (best-of-N across arms
    reports phantom overhead on a loaded 1-core box)."""
    from paddle_tpu import observability as obs

    led = obs.get_ledger()
    was_running = led.running
    ratios = []
    best_on = best_off = 0.0
    try:
        for _ in range(trials):
            led.stop()
            off = eager_mlp_loop(steps=steps, instrument=True)
            led.start()
            on = eager_mlp_loop(steps=steps, instrument=True)
            best_off = max(best_off, off['steps_per_sec'])
            best_on = max(best_on, on['steps_per_sec'])
            if on['steps_per_sec']:
                ratios.append(off['steps_per_sec'] / on['steps_per_sec'])
    finally:
        led.start() if was_running else led.stop()
    overhead = min(ratios) - 1 if ratios else float('inf')
    return {
        'ledger_steps_per_sec': best_on,
        'plain_steps_per_sec': best_off,
        'overhead_pct': round(overhead * 100, 2),
    }


def goodput_gpt_mfu(steps=12, warmup=3, batch=4, seq=128,
                    peak_flops=1e12):
    """MFU cross-check on a GPT train loop (also imported by the tier-1
    within-10% guard): the observability layer's windowed aggregate MFU
    (XLA cost_analysis FLOPs over catalog host seconds, compile
    excluded — what `paddle_mfu` publishes) vs the analytic matmul-FLOPs
    MFU this bench derives independently, against the SAME fixed peak.
    Two unrelated estimators agreeing is the evidence the gauge can be
    trusted on the real chip."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import observability as obs
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import LlamaConfig, LlamaForCausalLM

    # matmul-dominant small shape: big enough that weight matmuls dwarf the
    # elementwise/attention FLOPs the analytic formula under-counts
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=688,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, max_position_embeddings=max(2 * seq, 256))
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())

    def loss_fn(logits, labels):
        lg = logits[:, :-1].reshape([-1, cfg.vocab_size])
        lb = labels[:, 1:].reshape([-1])
        return F.cross_entropy(lg, lb)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    batches = [rng.randint(0, cfg.vocab_size, (batch, seq))
               for _ in range(4)]
    for i in range(warmup):
        loss = step(batches[i % 4], batches[i % 4])
    float(loss.numpy())

    peaks = {'device_kind': 'bench-fixed', 'peak_flops': float(peak_flops),
             'peak_hbm_bytes_per_s': None, 'source': 'fixed'}
    with obs.MfuWindow(peaks=peaks) as win:
        t0 = time.perf_counter()
        for i in range(steps):
            loss = step(batches[i % 4], batches[i % 4])
        float(loss.numpy())
        dt = (time.perf_counter() - t0) / steps
    measured = win.result()

    # the same analytic model-FLOPs formula the headline phase uses
    h, L = cfg.hidden_size, cfg.num_hidden_layers
    qkvo = h * (cfg.num_attention_heads * cfg.head_dim) * 2 \
        + h * (cfg.num_key_value_heads * cfg.head_dim) * 2
    n_matmul = L * (qkvo + 3 * h * cfg.intermediate_size) \
        + h * cfg.vocab_size
    fwd_flops = (2 * n_matmul * batch * seq
                 + L * 4 * batch * seq * seq * h)
    bench_mfu = 3 * fwd_flops / dt / peak_flops

    paddle_mfu = measured['mfu'] or 0.0
    rel_err = abs(paddle_mfu / bench_mfu - 1.0) if bench_mfu else 1.0
    return {
        'bench_mfu': round(bench_mfu, 6),
        'paddle_mfu': round(paddle_mfu, 6),
        'rel_err_pct': round(rel_err * 100, 2),
        'step_time_s': round(dt, 5),
        'window_flops': measured['flops_total'],
        'window_wall_s': round(measured['wall_seconds'], 4),
    }


def goodput_fault_ledger(steps=12, step_sleep=0.02, backoff_s=0.3):
    """Fault-injected ledger closure (also imported by the tier-1
    guard): an eager train loop with per-step spans takes exactly one
    transient retry (fixed backoff, no jitter), one NaN rollback, and
    one checkpoint save. Returns the goodput report plus the injected
    ground truth so the guard can assert (a) the books close — category
    seconds + residual == wall within 1% — and (b) each injected second
    landed in ITS category: backoff in retry_backoff, the bad step's
    compute in rollback, the save in checkpoint_save."""
    import tempfile

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu import observability as obs
    from paddle_tpu import resilience as res
    from paddle_tpu.utils.checkpoint import CheckpointManager

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.standard_normal((8, 16)).astype('float32'))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)))

    calls = {'n': 0}
    fail_at, nan_at, ckpt_at = 3, 6, 9

    def one_step():
        calls['n'] += 1
        with obs.span('bench.eager_step'):
            time.sleep(step_sleep)   # give every step deterministic mass
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if calls['n'] == fail_at:
                raise res.TransientError('injected transient blip')
        if calls['n'] == nan_at:
            import jax.numpy as jnp
            return paddle.Tensor(jnp.float32(float('nan')))
        return loss

    def snap():
        return {n: np.asarray(p.value)
                for n, p in model.named_parameters()}

    def rest(s):
        import jax.numpy as jnp
        pm = dict(model.named_parameters())
        for n, v in s.items():
            pm[n]._data = jnp.asarray(v)
            pm[n]._node = None

    policy = res.RetryPolicy(max_retries=1, base_delay=backoff_s,
                             jitter=0.0, multiplier=1.0)
    # check_spikes=False: only the injected NaN triggers a rollback, so
    # the ground truth stays exactly 1 retry + 1 rollback + 1 checkpoint
    ft = res.FaultTolerantStep(one_step, snapshot_fn=snap, restore_fn=rest,
                               retry_policy=policy, skip_budget=2,
                               snapshot_interval=1, check_spikes=False)

    one_step()   # warm the dispatch cache outside the measured window
    calls['n'] = 0

    ledger = obs.get_ledger()
    was_running = ledger.running
    ledger.start(reset=True)
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        i = 0
        while calls['n'] < steps:
            loss = ft()
            i += 1
            if i == ckpt_at:
                mgr.save(i, snap(), force=True)
    wall = time.perf_counter() - t0
    report = ledger.report()
    if not was_running:
        ledger.stop()
    report['loop_wall_seconds'] = wall
    report['injected'] = {'backoff_s': backoff_s,
                          'step_sleep_s': step_sleep,
                          'retries': 1, 'rollbacks': 1, 'checkpoints': 1,
                          'steps': calls['n']}
    report['ft_stats'] = ft.stats()
    return report


def reqledger_overhead_ab(trials=3, n_requests=12, max_new=8):
    """Request-ledger on vs off A/B on a routed serving trace (also
    imported by the tier-1 <3% overhead guard). Both arms run the SAME
    router/engine path; only the per-request ledger toggles — the ratio
    isolates what phase bookkeeping (queue spans, per-round fair-share
    attribution, finalize) costs the serving hot loop. Min-of-
    adjacent-pair ratios, same estimator as the scrape guard
    (best-of-N across arms reports phantom overhead on a loaded
    1-core box)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.serving import (InferenceEngine, Replica, Router,
                                    SamplingParams)

    model = _serving_model()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, model.config.vocab_size, (s,)).tolist()
               for s in ([5, 9, 13, 7, 11, 6] * n_requests)[:n_requests]]

    def run_arm():
        eng = InferenceEngine(model, num_slots=4, max_length=64,
                              decode_block=8)
        router = Router([Replica(0, eng)])
        t0 = time.perf_counter()
        handles = [router.submit(
            p, SamplingParams(max_new_tokens=max_new, eos_token_id=-1))
            for p in prompts]
        while not all(h.done for h in handles):
            router.step()
        wall = time.perf_counter() - t0
        toks = sum(len(h.tokens) for h in handles)
        return toks / wall if wall > 0 else 0.0

    led = obs.get_request_ledger()
    was_on = led.is_enabled
    ratios = []
    best_on = best_off = 0.0
    try:
        run_arm()                      # warm compile caches off-ledger
        for _ in range(trials):
            led.disable()
            off = run_arm()
            led.enable()
            on = run_arm()
            best_off = max(best_off, off)
            best_on = max(best_on, on)
            if on:
                ratios.append(off / on)
    finally:
        led.enable() if was_on else led.disable()
    overhead = min(ratios) - 1 if ratios else float('inf')
    return {
        'ledger_tokens_per_sec': best_on,
        'plain_tokens_per_sec': best_off,
        'overhead_pct': round(overhead * 100, 2),
    }


def _phase_goodput():
    """Goodput/MFU phase: ledger overhead A/B, the MFU cross-check, and
    the fault-injected ledger-closure run — the tier-1 guards pin
    overhead <3%, MFU agreement <10%, and closure-within-1% on CPU."""
    out = {}
    for key, fn in (('goodput_overhead', goodput_overhead_ab),
                    ('reqledger_overhead', reqledger_overhead_ab),
                    ('gpt_mfu', goodput_gpt_mfu),
                    ('fault_ledger', goodput_fault_ledger)):
        try:
            out[key] = fn()
        except Exception as e:
            print(f'# goodput bench {key} failed: {type(e).__name__}: {e}',
                  file=sys.stderr)
            out[key] = {'error': type(e).__name__}
    return out


def donation_ab(n_requests=10, max_new=8, train_steps=4, num_slots=4,
                max_length=64):
    """Donation gauntlet A/B (ISSUE 13): the same serving trace and the
    same train loop with store-served donation FORCED ON vs OFF, both
    through a persistent program store (the export path the gauntlet
    governs — the corruption sentinels guard the donated arm's first K
    invocations).

    Asserted by the tier-1 guard: greedy serving outputs AND train
    losses bit-exact across the arms (donation is value-neutral or it
    is quarantined), and the pool-copy surface accounting — with
    per-slot rows every single-slot op moves `row_bytes`, where the old
    stacked pool moved `pool_bytes`; the reported
    `pool_copy_bytes_saved` is that delta summed over the trace's
    single-slot ops. Tokens/sec for both arms ride along (CPU narrows
    the gap; the number that matters here is parity + bytes)."""
    import tempfile
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu import flags as _pflags, programs
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import InferenceEngine, SamplingParams

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, (s,)).tolist()
               for s in ([5, 9, 13, 7, 21, 11] * 3)[:n_requests]]
    x = rng.standard_normal((16, 32)).astype('float32')
    y = rng.randint(0, 4, (16,))

    def serve_arm(donate):
        paddle.seed(7)
        model = GPTForCausalLM(GPTConfig.tiny()).eval()
        eng = InferenceEngine(model, num_slots=num_slots,
                              max_length=max_length, donate_pool=donate)
        t0 = time.perf_counter()
        handles = eng.generate_many(
            prompts, SamplingParams(max_new_tokens=max_new,
                                    eos_token_id=-1))
        dt = time.perf_counter() - t0
        toks = [list(h.tokens) for h in handles]
        n_tok = sum(len(t) for t in toks)
        return toks, n_tok / dt if dt else 0.0, eng.pool.stats()

    def train_arm():
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 4))
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters())
        step = TrainStep(m, lambda o, l: F.cross_entropy(o, l), opt)
        return [float(step(paddle.to_tensor(x),
                           paddle.to_tensor(y)).numpy())
                for _ in range(train_steps)]

    prev_flag = _pflags.flag('FLAGS_donation')
    try:
        _pflags.set_flags({'FLAGS_donation': 'on'})
        programs.configure(tempfile.mkdtemp(prefix='bench_donation_on_'))
        store = programs.get_store()
        toks_don, tps_don, pool_don = serve_arm(True)
        losses_don = train_arm()
        posture = store.donation_state()
        _pflags.set_flags({'FLAGS_donation': 'off'})
        programs.configure(tempfile.mkdtemp(prefix='bench_donation_off_'))
        toks_und, tps_und, pool_und = serve_arm(False)
        losses_und = train_arm()
    finally:
        _pflags.set_flags({'FLAGS_donation': prev_flag})
        programs.configure(None)
    single_slot_ops = (pool_und['row_writes'] + pool_und['row_copies'])
    saved = (pool_und['pool_bytes'] - pool_und['row_bytes']) \
        * single_slot_ops
    return {
        'parity_tokens': toks_don == toks_und,
        'parity_losses': losses_don == losses_und,
        'donated_tokens_per_sec': round(tps_don, 1),
        'undonated_tokens_per_sec': round(tps_und, 1),
        'speedup': round(tps_don / tps_und, 3) if tps_und else 0.0,
        'row_bytes': pool_und['row_bytes'],
        'pool_bytes': pool_und['pool_bytes'],
        'single_slot_ops': single_slot_ops,
        'pool_copy_bytes_saved': saved,
        'donated_posture': posture.get('posture'),
        'donated_verdict': posture.get('verdict'),
        # honesty note: a short trace sits inside the donated arm's
        # sentinel window (snapshot copies + finiteness checks), which
        # depresses its tokens/sec; steady state begins after
        # FLAGS_donation_sentinel guarded invocations per program
        'donated_arm_includes_sentinel_window': True,
    }


def _phase_donation():
    """Donation phase: probe the installed runtime (recorded as data,
    not asserted — the verdict is the runtime's, not the bench's), then
    the forced-on/off A/B whose parity fields the tier-1 guard pins."""
    out = {}
    try:
        from paddle_tpu.programs import donation as _donation
        probe = _donation.run_probe(runs=4)
        out['donation_probe'] = {
            'verdict': probe.get('verdict'),
            'reason': probe.get('reason', ''),
            'seconds': probe.get('seconds'),
        }
    except Exception as e:
        print(f'# donation probe failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['donation_probe'] = {'error': type(e).__name__}
    try:
        out['donation_ab'] = donation_ab()
    except Exception as e:
        print(f'# donation bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        out['donation_ab'] = {'error': type(e).__name__}
    return out


def _autoscale_schedule(pattern, duration_s, rate):
    """The three traffic shapes of the autoscale A/B, all peaking at
    `rate` req/s so the static comparison fleet is sized once."""
    from paddle_tpu import loadgen
    if pattern == 'poisson':
        return loadgen.PoissonSchedule(rate)
    if pattern == 'diurnal':
        # one full cycle: quiet -> peak (mid-trace) -> quiet, trough at
        # a fifth of the peak — the day/night swing scale-down feeds on
        return loadgen.DiurnalSchedule(rate / 5.0, rate,
                                       period_s=duration_s)
    if pattern == 'burst':
        # flash crowd: a fifth of the trace's volume lands inside 50 ms
        # mid-trace — arrival concentration beats any box's drain rate,
        # so the backlog (and the autoscaler's reaction to it) is real
        # on fast hardware too, unlike a merely-elevated rate
        herd = max(rate * duration_s * 0.2, 8.0)
        return loadgen.BurstSchedule(rate / 4.0, herd / 0.05,
                                     burst_start_s=duration_s * 0.4,
                                     burst_len_s=0.05)
    raise ValueError(f'unknown traffic pattern {pattern!r}')


def autoscale_arm(model, trace, *, autoscaled, replicas, max_replicas,
                  slo_ttft_s, eng_kw, time_scale=1.0, max_wall_s=120.0,
                  signal_window_s=3.0, cooldown_s=0.5,
                  down_stable_s=1.0):
    """Replay ONE trace against a fresh fleet and close the goodput
    books around it (also imported by the tier-1 guards).

    Static arm: `replicas` engines for the whole trace. Autoscaled
    arm: start at 1, let the `Autoscaler` (forced on, flag-independent
    — this IS the A/B) grow to `max_replicas` and shrink back on the
    windowed signals. Both arms report the user-felt numbers (p99-TTFT
    SLO attainment, replica-seconds, attainment per replica-hour) plus
    the ledger's verdict on what the machinery cost: scale_up /
    scale_down seconds, their fraction of wall, and closure — the
    books must still sum to wall within 1% with the new categories in
    play."""
    from paddle_tpu import loadgen, observability as obs
    from paddle_tpu.serving import (Autoscaler, AutoscalerConfig,
                                    InferenceEngine, ReplicaSet, Router)

    router = Router(ReplicaSet(model, 1 if autoscaled else replicas,
                               **eng_kw),
                    signal_window_s=signal_window_s)
    scaler = None
    if autoscaled:
        scaler = Autoscaler(
            router, lambda: InferenceEngine(model, **eng_kw),
            AutoscalerConfig(min_replicas=1, max_replicas=max_replicas,
                             slo_ttft_s=slo_ttft_s,
                             cooldown_s=cooldown_s,
                             down_stable_s=down_stable_s),
            force=True)
    ledger = obs.get_ledger()
    was_running = ledger.running
    ledger.start(reset=True)
    report = loadgen.LoadReplayer(router, trace, autoscaler=scaler,
                                  time_scale=time_scale,
                                  max_wall_s=max_wall_s).run()
    books = ledger.report()
    if not was_running:
        ledger.stop()
    wall = books['wall_seconds']
    closure = abs(sum(books['categories'].values())
                  + books['residual_seconds'] - wall)
    cats = books['categories']
    out = report.report(slo_ttft_s)
    out.update({
        'autoscaled': bool(autoscaled),
        'replicas_start': 1 if autoscaled else replicas,
        'replicas_final': len(router.replicas),
        'ledger': {
            'wall_s': round(wall, 3),
            'closure_err_pct': round(100.0 * closure / wall, 4)
            if wall else 0.0,
            'scale_up_s': round(cats.get('scale_up', 0.0), 4),
            'scale_down_s': round(cats.get('scale_down', 0.0), 4),
            'machinery_pct': round(
                100.0 * (cats.get('scale_up', 0.0)
                         + cats.get('scale_down', 0.0)) / wall, 3)
            if wall else 0.0,
            'serving_decode_s': round(cats.get('serving_decode', 0.0), 3),
        },
    })
    if scaler is not None:
        s = scaler.stats()
        out['autoscaler'] = {'decisions': s['decisions'],
                             'provision_ema_s': s['provision_ema_s']}
    return out


def autoscale_ab(duration_s=10.0, rate=60.0, seed=1234, slo_ttft_s=2.0,
                 max_replicas=3, patterns=('poisson', 'diurnal', 'burst')):
    """The ISSUE-14 headline: p99-TTFT SLO attainment per replica-hour,
    static peak-sized fleet vs autoscaled, across the three traffic
    patterns — with the goodput ledger proving the autoscaling
    machinery costs <3% of wall and the books still close within 1%.

    The static arm runs `max_replicas` engines for the whole trace
    (the 'provision for the peak' posture); the autoscaled arm starts
    at one replica and follows the windowed signals. Same seed ⇒ both
    arms replay bit-identical traces."""
    import paddle_tpu as paddle
    from paddle_tpu import loadgen
    from paddle_tpu.nlp import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny()).eval()
    eng_kw = dict(num_slots=4, max_length=64, decode_block=4)
    # warm every prefill bucket + the decode block OUTSIDE the arms:
    # arms run sequentially in one process and share the in-memory
    # program store, so whichever arm ran first would otherwise eat the
    # compiles and bias the comparison
    from paddle_tpu.serving import InferenceEngine, SamplingParams
    warm_rng = np.random.RandomState(0)
    InferenceEngine(model, **eng_kw).generate_many(
        [warm_rng.randint(1, 64, (l,)).tolist() for l in (4, 8, 16, 32)],
        [SamplingParams(max_new_tokens=6, eos_token_id=-1)] * 4)
    out = {'slo_ttft_s': slo_ttft_s, 'max_replicas': max_replicas,
           'duration_s': duration_s, 'peak_rate': rate}
    for pattern in patterns:
        trace = loadgen.make_trace(
            _autoscale_schedule(pattern, duration_s, rate), duration_s,
            seed=seed,
            prompt_lengths=loadgen.LognormalLengths(10, 0.5, 4, 32),
            output_lengths=loadgen.FixedLength(6),
            tenants=[loadgen.TenantClass('paid', 1, 0),
                     loadgen.TenantClass('free', 2, 2)],
            vocab_size=min(getattr(model.config, 'vocab_size', 128), 128))
        loadgen.validate_trace(trace, eng_kw['max_length'])
        arms = {}
        for name, autoscaled in (('static', False), ('autoscaled', True)):
            arms[name] = autoscale_arm(
                model, trace, autoscaled=autoscaled,
                replicas=max_replicas, max_replicas=max_replicas,
                slo_ttft_s=slo_ttft_s, eng_kw=eng_kw,
                max_wall_s=6.0 * duration_s)
        st, au = arms['static'], arms['autoscaled']
        arms['trace'] = loadgen.trace_stats(trace)
        arms['replica_seconds_saved_pct'] = round(
            100.0 * (1.0 - au['replica_seconds']
                     / st['replica_seconds']), 2) \
            if st['replica_seconds'] else 0.0
        out[pattern] = arms
    return out


def autoscale_smoke(duration_s=5.0, rate=60.0, seed=77):
    """Tier-1 smoke (`bench.py autoscale --smoke`): a 5-second
    deterministic Poisson trace on CPU through the autoscaled arm
    only. The guard asserts the SLO-attainment JSON is produced
    (offered/attainment/replica-seconds all present), zero requests
    dropped, and the goodput ledger — with the scale_up/scale_down
    categories live — closes within 1%."""
    res = autoscale_ab(duration_s=duration_s, rate=rate, seed=seed,
                       patterns=('poisson',), max_replicas=2)
    arm = res['poisson']['autoscaled']
    return {
        'pattern': 'poisson', 'duration_s': duration_s, 'seed': seed,
        'offered': arm['offered'],
        'completed': arm['completed'],
        'dropped': arm['dropped'],
        'slo_attainment': arm['slo_attainment'],
        'ttft_p99_s': arm['ttft_p99_s'],
        'replica_seconds': arm['replica_seconds'],
        'attainment_per_replica_hour': arm['attainment_per_replica_hour'],
        'ledger_closure_err_pct': arm['ledger']['closure_err_pct'],
        'machinery_pct': arm['ledger']['machinery_pct'],
        'decisions': arm.get('autoscaler', {}).get('decisions', {}),
    }


def _phase_autoscale():
    """Autoscaling phase: the three-pattern static-vs-autoscaled A/B
    (tier-1 guards ride the smoke variant + the diurnal acceptance
    test in tests/test_autoscaler.py)."""
    try:
        return {'autoscale': autoscale_ab()}
    except Exception as e:
        print(f'# autoscale bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        return {'autoscale': {'error': type(e).__name__}}


def _bench_eager_dispatch():
    """Eager dispatch fast path A/B: the same DyGraph MLP train loop with
    the dispatch cache on vs off (per-call re-tracing), reporting ops/sec
    and trace counts for each arm."""
    try:
        cached = eager_mlp_loop(steps=30, use_cache=True)
        uncached = eager_mlp_loop(steps=30, use_cache=False)
        speedup = (cached['steps_per_sec'] / uncached['steps_per_sec']
                   if uncached['steps_per_sec'] else 0.0)
        return {'eager_dispatch': {
            'cached': cached, 'uncached': uncached,
            'speedup': round(speedup, 2),
            'parity': abs(cached['loss'] - uncached['loss']) < 1e-4,
        }}
    except Exception as e:   # never let the micro-bench kill the headline
        print(f'# eager dispatch bench failed: {type(e).__name__}: {e}',
              file=sys.stderr)
        return {'eager_dispatch': {'error': type(e).__name__}}


def _free_device_memory():
    """Drop dead device buffers between ladder rungs: the autograd tape
    creates reference cycles, so the previous rung's params/moments wait
    on the cyclic GC — collect them NOW or the next rung sees an HBM
    that is still full (r4: all 7B rungs OOMed behind the 1.3B run's
    garbage)."""
    import gc
    import jax
    gc.collect()
    jax.clear_caches()
    gc.collect()
    # bench phases are independent: anything still resident between
    # phases is garbage — delete it outright (tape cycles can survive
    # two gc passes; r5: the 7B overfit's moments kept 7 GB pinned and
    # OOMed the flash micro-bench's input allocation)
    for a in jax.live_arrays():
        try:
            a.delete()
        except Exception:  # paddle-lint: disable=swallowed-exception -- freeing live arrays between phases; a deleted buffer raising is fine
            pass
    gc.collect()


def _run_ladder(configs):
    """Run the first config of a ladder that succeeds; (name, result)
    or (None, None) if every rung fails."""
    for name, cfg, batch, seq, steps, warmup, dtype, *rest in configs:
        try:
            print(f'# rung {name} b{batch} s{seq} {dtype} '
                  f'offload={rest[0] if rest else None}', file=sys.stderr)
            res = _run_config(name, cfg, batch, seq, steps, warmup,
                              dtype, *rest)
            print(f'# rung {name} OK: {res["step_time_s"]:.3f}s/step',
                  file=sys.stderr)
            return name, res
        except Exception:
            # OOM, compiler blow-up, or a rung-specific failure (e.g. the
            # host-offload path on a backend where it is untested): every
            # rung is independent, so log the FULL traceback and fall
            # through to the next smaller config rather than killing the
            # whole phase
            import traceback
            print(f'# rung {name} failed:\n'
                  f'{traceback.format_exc()}', file=sys.stderr)
            _free_device_memory()
            continue
    return None, None


def _phase_headline():
    import jax
    on_tpu = jax.default_backend() not in ('cpu',)
    metric_name, result = _run_ladder(_configs(on_tpu))
    if result is None:
        raise RuntimeError('all bench configs failed')
    # only a different MODEL counts as a fallback (batch shrink within the
    # 1.3B config still benches the 1.3B headline)
    fell_back = on_tpu and metric_name != 'gpt3_1p3b'
    out = {
        'metric': f'{metric_name}_pretrain_tokens_per_sec_per_chip',
        'value': round(result['tokens_per_sec'], 1),
        'unit': 'tokens/s',
        'vs_baseline': round(result['mfu'] / 0.40, 4),
        'mfu': round(result['mfu'], 4),
        'step_time_s': round(result['step_time_s'], 4),
        'loss': round(result['loss'], 4),
        'device': str(jax.devices()[0].device_kind),
        'fell_back_from_1p3b': fell_back,
        'config': {'params_m': result['params_m'],
                   'batch': result['batch'], 'seq': result['seq'],
                   'dtype': result['dtype']},
    }
    if result.get('peak_hbm_gb'):
        out['peak_hbm_gb'] = result['peak_hbm_gb']
    return out


def _report_7b(res):
    return {
        'tokens_per_sec': round(res['tokens_per_sec'], 1),
        'mfu': round(res['mfu'], 4),
        'step_time_s': round(res['step_time_s'], 4),
        'loss': round(res['loss'], 4),
        'params_m': res['params_m'],
        'batch': res['batch'], 'seq': res['seq'],
        'peak_hbm_gb': res.get('peak_hbm_gb'),
        'layers': res['layers'], 'layers_full_7b': 32,
        'depth_reduced_to_fit_hbm': res['layers'] < 32,
        'optimizer_state_host_offload': res['offload_optimizer'],
    }


def _phase_7b():
    fast, deep = _7b_configs()
    out = {}
    _, res7 = _run_ladder(fast)
    if res7 is None:
        out['llama2_7b_shape'] = {'error': 'all 7B-shape rungs failed'}
    else:
        out['llama2_7b_shape'] = _report_7b(res7)
    _free_device_memory()
    _, res16 = _run_ladder(deep)
    if res16 is None:
        out['llama2_7b_deep_offload'] = {'error': '16L offload rung failed'}
    else:
        out['llama2_7b_deep_offload'] = _report_7b(res16)
    return out


def _phase_probe():
    if os.environ.get('BENCH_TEST_PROBE_HANG'):
        # regression-test hook: a wedged TPU tunnel (r5: the real probe
        # hung exactly like this and took the whole perf signal dark)
        time.sleep(3600)
    import jax
    d = jax.devices()[0]
    return {'device': jax.default_backend(),
            'device_kind': getattr(d, 'device_kind', '')}


PHASES = {
    'probe': _phase_probe,
    'headline': _phase_headline,
    '7b': _phase_7b,
    'overfit': lambda: {'llama2_7b_overfit': _run_7b_overfit()},
    'flash': _bench_flash_kernels,
    'fused_ce': _bench_fused_ce,
    'decode': _phase_decode,
    'eager': _bench_eager_dispatch,
    'obs': _phase_obs,
    'resilience': _phase_resilience,
    'serving': _phase_serving,
    'adapters': _phase_adapters,
    'router': _phase_router,
    'coldstart': _phase_coldstart,
    'goodput': _phase_goodput,
    'donation': _phase_donation,
    'autoscale': _phase_autoscale,
    'fleet_obs': _phase_fleet_obs,
    'fleet_proc': _phase_fleet_proc,
}


def _run_phase_subprocess(phase, timeout_s, env_extra=None):
    """Each phase gets a FRESH process: a failed/OOMed rung cannot
    fragment or leak HBM into the next phase (r5: after a too-deep 7B
    attempt OOMed, even previously-fitting rungs OOMed in-process)."""
    import os
    import subprocess
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, __file__, '--phase', phase],
            capture_output=True, text=True, timeout=timeout_s, env=env)
        sys.stderr.write(proc.stderr)
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() \
            else ''
        if proc.returncode != 0 or not line:
            return {f'{phase}_error': f'exit {proc.returncode}'}
        return json.loads(line)
    except subprocess.TimeoutExpired as e:
        # keep the child's partial stderr: the per-rung tracebacks are
        # exactly what diagnoses a hang
        sys.stderr.write((e.stderr.decode() if isinstance(e.stderr, bytes)
                          else e.stderr) or '')
        return {f'{phase}_error': 'timeout'}
    except Exception as e:
        return {f'{phase}_error': type(e).__name__}


def _cpu_phase_plan():
    """(phase, subprocess timeout) pairs for the CPU tier;
    BENCH_CPU_PHASES (comma list) restricts the set — the probe-fallback
    regression test runs a single fast phase."""
    plan = [('headline', 1500), ('eager', 600), ('obs', 600),
            ('resilience', 600), ('serving', 1200), ('adapters', 900),
            ('router', 900), ('coldstart', 900), ('goodput', 600),
            ('donation', 600), ('autoscale', 600), ('fleet_obs', 600)]
    only = os.environ.get('BENCH_CPU_PHASES')
    if only:
        wanted = {p.strip() for p in only.split(',') if p.strip()}
        plan = [(p, t) for p, t in plan if p in wanted]
    return plan


def main():
    # phases that configure a program store must not pay (or flake on)
    # an implicit donation probe: the donation PHASE owns that question
    # and sets its flags explicitly in-process. An operator exporting
    # FLAGS_donation still wins.
    os.environ.setdefault('FLAGS_donation', 'off')
    if len(sys.argv) >= 2 and sys.argv[1] == 'autoscale':
        # `bench.py autoscale [--smoke]`: the tier-1 CI entry point —
        # --smoke is the 5-second deterministic Poisson trace whose
        # SLO-attainment JSON + ledger closure the guard asserts
        if '--smoke' in sys.argv[2:]:
            print(json.dumps({'autoscale_smoke': autoscale_smoke()}))
        else:
            print(json.dumps(_phase_autoscale()))
        return 0
    if len(sys.argv) >= 2 and sys.argv[1] == 'adapters':
        # `bench.py adapters [--smoke]`: --smoke is the deterministic
        # mixed-adapter loadgen trace the tier-1 guard asserts on
        if '--smoke' in sys.argv[2:]:
            print(json.dumps({'adapters_smoke': adapters_smoke()}))
        else:
            print(json.dumps(_phase_adapters()))
        return 0
    if len(sys.argv) >= 2 and sys.argv[1] == 'reqledger_overhead_ab':
        # `bench.py reqledger_overhead_ab`: the request-ledger on/off
        # A/B on a routed serving trace (tier-1 guards <3%)
        print(json.dumps(
            {'reqledger_overhead': reqledger_overhead_ab()}))
        return 0
    if len(sys.argv) >= 3 and sys.argv[1] == '--coldstart-child':
        if os.environ.get('BENCH_FORCE_CPU'):
            import jax
            jax.config.update('jax_platforms', 'cpu')
        print(json.dumps(coldstart_child(json.loads(sys.argv[2]))))
        return 0
    if len(sys.argv) >= 3 and sys.argv[1] == '--phase':
        if os.environ.get('BENCH_FORCE_CPU'):
            # test hook for the phase flow: the axon preload ignores
            # JAX_PLATFORMS, so CPU must be forced in-process
            import jax
            jax.config.update('jax_platforms', 'cpu')
        print(json.dumps(PHASES[sys.argv[2]]()))
        return 0
    # The orchestrating parent must NOT import jax: on the single-chip
    # tunnel, a parent holding the TPU client blocks its own phase
    # subprocesses from attaching (r5: the 7b phase hung for 15 min
    # behind the parent's device handle).
    probe = _run_phase_subprocess(
        'probe', int(os.environ.get('BENCH_PROBE_TIMEOUT', '300')))
    if 'device' not in probe:
        # Backend attach failed/hung (e.g. TPU tunnel down). The perf
        # signal must not go dark (BENCH_r05 died here with rc=1 and
        # zero metrics): degrade to the CPU tier in forced-CPU
        # subprocesses — the parent still never imports jax — and exit
        # 0 with the fallback recorded in the JSON.
        print(f'# device probe failed ({probe}); degrading to CPU '
              f'phases', file=sys.stderr)
        out = {'device_probe': 'failed_cpu_fallback'}
        out.update(probe)
        for phase, t in _cpu_phase_plan():
            out.update(_run_phase_subprocess(
                phase, t, {'BENCH_FORCE_CPU': '1'}))
        print(json.dumps(out))
        return 0
    if str(probe.get('device', '')).lower() == 'cpu':
        out = {}
        for i, (phase, t) in enumerate(_cpu_phase_plan()):
            res = _run_phase_subprocess(phase, t)
            if phase == 'headline' and 'metric' not in res:
                raise RuntimeError(f'headline phase failed: {res}')
            out.update(res)
        print(json.dumps(out))  # CPU smoke: headline + eager/obs benches
        return 0
    # Measure the pallas CE kernel FIRST, then let the model phases use
    # whichever CE implementation actually won on this chip — the kernel
    # choice is data, not faith, and the decision lands in the JSON.
    ce = _run_phase_subprocess('fused_ce', 600)
    ce_wins = ce.get('fused_ce_speedup_pct', 0) > 0
    model_env = None if ce_wins else {'PADDLE_TPU_DISABLE_PALLAS_CE': '1'}
    out = _run_phase_subprocess('headline', 1500, model_env)
    if 'metric' not in out:
        raise RuntimeError(f'headline phase failed: {out}')
    out.update(ce)
    out['pallas_ce_used_in_models'] = ce_wins
    out.update(_run_phase_subprocess('7b', 1500, model_env))
    out.update(_run_phase_subprocess('overfit', 1200, model_env))
    out.update(_run_phase_subprocess('flash', 600))
    out.update(_run_phase_subprocess('decode', 900, model_env))
    out.update(_run_phase_subprocess('eager', 600))
    out.update(_run_phase_subprocess('obs', 600))
    out.update(_run_phase_subprocess('resilience', 600))
    out.update(_run_phase_subprocess('serving', 900))
    out.update(_run_phase_subprocess('router', 900))
    out.update(_run_phase_subprocess('coldstart', 900))
    out.update(_run_phase_subprocess('donation', 600))
    out.update(_run_phase_subprocess('autoscale', 600))
    out.update(_run_phase_subprocess('fleet_obs', 600))
    print(json.dumps(out))
    return 0


if __name__ == '__main__':
    sys.exit(main())
