"""paddle.optimizer — functional pytree core with an eager facade.

Upstream: python/paddle/optimizer/*.py. Each optimizer defines a pure
per-leaf update rule; the same rule serves
  - the eager path (`step()` reads `.grad` off Parameters and rebinds), and
  - the jitted path (`init_state` / `apply_gradients` over raw pytrees,
    used by paddle_tpu.jit.TrainStep with donated buffers).
Multi-precision: bf16/fp16 params keep an fp32 master copy in the slot
state; updates run in fp32 and cast back (TPU-native replacement for the
reference's multi_precision / master-weight machinery).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import lr as lr  # noqa: F401  (paddle.optimizer.lr.*)
from .lr import LRScheduler
from ..nn.clip import ClipGradBase
from ..tensor import Parameter, Tensor

_tree = jax.tree_util


def _is_low_precision(dtype):
    return jnp.dtype(dtype) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))



def _flatten_for_update(params, grads, slots):
    """Shared path-flattening for optimizer updates (fused and offload
    paths must derive leaf names identically): returns
    (treedef, names, flat_params, flat_grads, flat_slots)."""
    paths_p, treedef = _tree.tree_flatten_with_path(params)
    names = ['.'.join(str(getattr(e, 'key', e)) for e in path)
             for path, _ in paths_p]
    flat_p = [p for _, p in paths_p]
    flat_g = treedef.flatten_up_to(grads)
    flat_s = treedef.flatten_up_to(slots)
    return treedef, names, flat_p, flat_g, flat_s

class Optimizer:
    """Base optimizer. Subclasses implement `_init_slots` and `_rule`."""

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._decay_mode = 'l2'
        if weight_decay is None:
            self._coeff = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._coeff = float(weight_decay)
        else:  # L1Decay/L2Decay regularizer object
            self._coeff = float(getattr(weight_decay, '_coeff',
                                        getattr(weight_decay, 'coeff', 0.0)))
            if type(weight_decay).__name__ == 'L1Decay':
                self._decay_mode = 'l1'
        self._step_count = 0
        self._slots: Dict[int, dict] = {}  # id(param) -> slot dict

    # -- the pure core ------------------------------------------------------
    def _init_slots(self, p_value) -> dict:
        return {}

    def _rule(self, g, p, slots, lr, step):
        """Pure per-leaf update: (grad, fp32-param, slots, lr, step) ->
        (new fp32 param, new slots). g is fp32."""
        raise NotImplementedError

    def _decoupled_decay(self) -> bool:
        return False  # AdamW overrides

    def _leaf_init(self, p_value):
        slots = self._init_slots(p_value)
        if self._multi_precision and _is_low_precision(p_value.dtype):
            slots['master'] = p_value.astype(jnp.float32)
        return slots

    def _coeff_for(self, name):
        """Per-parameter decay coefficient (AdamW/Lamb exclusions)."""
        return self._coeff

    def _leaf_apply(self, g, p_value, slots, lr_value, step, name=None):
        low = 'master' in slots
        p32 = slots['master'] if low else p_value.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        coeff = self._coeff_for(name)
        if coeff and not self._decoupled_decay():
            reg = jnp.sign(p32) if self._decay_mode == 'l1' else p32
            g32 = g32 + coeff * reg
        new_p32, new_slots = self._rule(g32, p32, dict(slots), lr_value, step)
        if coeff and self._decoupled_decay():
            new_p32 = new_p32 - lr_value * coeff * p32
        if low:
            new_slots['master'] = new_p32
            return new_p32.astype(p_value.dtype), new_slots
        return new_p32.astype(p_value.dtype), new_slots

    # -- functional pytree API (jit path) -----------------------------------
    def init_state(self, params):
        """params: pytree of raw jax arrays -> opt state pytree."""
        slots = _tree.tree_map(self._leaf_init, params)
        return {'step': jnp.zeros((), jnp.int32), 'slots': slots}

    def apply_gradients(self, grads, params, state, lr_value):
        """Pure: (grads, params, state, lr) -> (new_params, new_state).
        Safe to call under jit; lr_value may be a traced scalar."""
        if self._grad_clip is not None:
            grads = self._grad_clip.apply_pytree(grads)
        step = state['step'] + 1
        treedef, names, flat_p, flat_g, flat_s = _flatten_for_update(
            params, grads, state['slots'])
        new_p, new_s = [], []
        for g, p, s, nm in zip(flat_g, flat_p, flat_s, names):
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            np_, ns_ = self._leaf_apply(g, p, s, lr_value, step, name=nm)
            new_p.append(np_)
            new_s.append(ns_)
        return (_tree.tree_unflatten(treedef, new_p),
                {'step': step, 'slots': _tree.tree_unflatten(treedef, new_s)})

    # -- eager facade -------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError('set_lr cannot override an LRScheduler')
        self._learning_rate = float(value)

    @property
    def _params(self) -> List[Parameter]:
        if self._parameter_list is None:
            raise ValueError('optimizer constructed without parameters')
        return self._parameter_list

    def step(self):
        params_grads = [(p, p.grad) for p in self._params
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr_v = self.get_lr()
        self._step_count += 1
        for p, g in params_grads:
            slots = self._slots.get(id(p))
            if slots is None:
                slots = self._leaf_init(p.value)
            # per-param lr multiplier (ParamAttr learning_rate)
            mult = 1.0
            if isinstance(p, Parameter):
                mult = p.optimize_attr.get('learning_rate', 1.0)
            new_val, new_slots = self._leaf_apply(
                g.value, p.value, slots, lr_v * mult, self._step_count,
                name=getattr(p, 'name', None))
            p._data = new_val
            p._node = None
            self._slots[id(p)] = new_slots

    def clear_grad(self, set_to_zero=True):
        for p in self._params:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # -- state dict ---------------------------------------------------------
    def state_dict(self):
        out = {'step': self._step_count, 'slots': []}
        for p in self._params:
            s = self._slots.get(id(p), None)
            out['slots'].append(
                None if s is None else
                {k: np.asarray(v) for k, v in s.items()})
        if isinstance(self._learning_rate, LRScheduler):
            out['LR_Scheduler'] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, sd):
        self._step_count = int(sd.get('step', 0))
        slots = sd.get('slots', [])
        for p, s in zip(self._params, slots):
            if s is not None:
                self._slots[id(p)] = {k: jnp.asarray(v) for k, v in s.items()}
        if 'LR_Scheduler' in sd and isinstance(self._learning_rate,
                                               LRScheduler):
            self._learning_rate.set_state_dict(sd['LR_Scheduler'])


class SGD(Optimizer):
    def _rule(self, g, p, slots, lr, step):
        return p - lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {'velocity': jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, g, p, slots, lr, step):
        v = self._momentum * slots['velocity'] + g
        if self._nesterov:
            p = p - lr * (g + self._momentum * v)
        else:
            p = p - lr * v
        slots['velocity'] = v
        return p, slots


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, p):
        return {'moment': jnp.full(p.shape, self._init_acc, jnp.float32)}

    def _rule(self, g, p, slots, lr, step):
        m = slots['moment'] + jnp.square(g)
        slots['moment'] = m
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), slots


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        s = {'mean_square': jnp.zeros(p.shape, jnp.float32),
             'momentum': jnp.zeros(p.shape, jnp.float32)}
        if self._centered:
            s['mean_grad'] = jnp.zeros(p.shape, jnp.float32)
        return s

    def _rule(self, g, p, slots, lr, step):
        ms = self._rho * slots['mean_square'] + (1 - self._rho) * jnp.square(g)
        slots['mean_square'] = ms
        denom = ms
        if self._centered:
            mg = self._rho * slots['mean_grad'] + (1 - self._rho) * g
            slots['mean_grad'] = mg
            denom = ms - jnp.square(mg)
        upd = g / jnp.sqrt(denom + self._epsilon)
        if self._momentum:
            mom = self._momentum * slots['momentum'] + lr * upd
            slots['momentum'] = mom
            return p - mom, slots
        return p - lr * upd, slots


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 amsgrad=False, moment_dtype=None, offload=None, name=None):
        """moment_dtype: storage dtype for m/v (default fp32). 'bfloat16'
        halves optimizer HBM — how billion-param models fit one chip; the
        moment *update* still computes in fp32 either way.

        offload='host' keeps m/v (and masters) in pinned host memory and
        streams per-leaf updates through HBM (upstream: fleet sharding
        `offload`; see optimizer/offload.py). Honored by jit.TrainStep."""
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._amsgrad = amsgrad
        self._moment_dtype = jnp.dtype(moment_dtype) if moment_dtype \
            else jnp.float32
        if offload not in (None, 'host'):
            raise ValueError(f"offload must be None or 'host', got "
                             f"{offload!r}")
        self._offload = offload

    def _init_slots(self, p):
        s = {'moment1': jnp.zeros(p.shape, self._moment_dtype),
             'moment2': jnp.zeros(p.shape, self._moment_dtype)}
        if self._amsgrad:
            s['moment2_max'] = jnp.zeros(p.shape, self._moment_dtype)
        return s

    def _rule(self, g, p, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots['moment1'].astype(jnp.float32) + (1 - b1) * g
        v = b2 * slots['moment2'].astype(jnp.float32) \
            + (1 - b2) * jnp.square(g)
        slots['moment1'] = m.astype(self._moment_dtype)
        slots['moment2'] = v.astype(self._moment_dtype)
        t = step.astype(jnp.float32) if hasattr(step, 'astype') \
            else jnp.asarray(step, jnp.float32)
        lr_t = lr * jnp.sqrt(1 - jnp.power(b2, t)) / (1 - jnp.power(b1, t))
        if self._amsgrad:
            vm = jnp.maximum(slots['moment2_max'].astype(jnp.float32), v)
            slots['moment2_max'] = vm.astype(self._moment_dtype)
            v = vm
        return p - lr_t * m / (jnp.sqrt(v) + self._epsilon), slots


class AdamW(Adam):
    """Adam with decoupled weight decay (upstream: optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 moment_dtype=None, offload=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         amsgrad, moment_dtype, offload)
        self._apply_decay_fn = apply_decay_param_fun

    def _decoupled_decay(self):
        return True

    def _coeff_for(self, name):
        # exclusion is per-leaf, so grad clipping stays one global pass
        if self._apply_decay_fn is not None and name is not None \
                and not self._apply_decay_fn(name):
            return 0.0
        return self._coeff


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        self._lamb_now = lamb_weight_decay

    def _init_slots(self, p):
        return {'moment1': jnp.zeros(p.shape, jnp.float32),
                'moment2': jnp.zeros(p.shape, jnp.float32)}

    def _coeff_for(self, name):
        # called once per leaf right before _rule (trace-time python), so
        # stashing the active decay here routes the exclusion into _rule
        self._lamb_now = 0.0 if (
            self._exclude_fn is not None and name is not None
            and self._exclude_fn(name)) else self._lamb_decay
        return 0.0

    def _rule(self, g, p, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots['moment1'] + (1 - b1) * g
        v = b2 * slots['moment2'] + (1 - b2) * jnp.square(g)
        slots['moment1'], slots['moment2'] = m, v
        t = jnp.asarray(step, jnp.float32)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon) + self._lamb_now * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, slots


class Adadelta(Optimizer):
    """Upstream: optimizer/adadelta.py — accumulates squared grads and
    squared updates; the effective step needs no external lr scale
    (lr multiplies anyway, matching paddle)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._rho, self._epsilon = rho, epsilon

    def _init_slots(self, p):
        return {'avg_squared_grad': jnp.zeros(p.shape, jnp.float32),
                'avg_squared_update': jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, g, p, slots, lr, step):
        rho, eps = self._rho, self._epsilon
        sg = rho * slots['avg_squared_grad'] + (1 - rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots['avg_squared_update'] + eps) \
            / jnp.sqrt(sg + eps)
        su = rho * slots['avg_squared_update'] + (1 - rho) * jnp.square(upd)
        slots['avg_squared_grad'] = sg
        slots['avg_squared_update'] = su
        return p - lr * upd, slots


class Adamax(Optimizer):
    """Upstream: optimizer/adamax.py — Adam with an infinity-norm second
    moment."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {'moment': jnp.zeros(p.shape, jnp.float32),
                'inf_norm': jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, g, p, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots['moment'] + (1 - b1) * g
        u = jnp.maximum(b2 * slots['inf_norm'], jnp.abs(g))
        slots['moment'] = m
        slots['inf_norm'] = u
        t = jnp.asarray(step, jnp.float32)
        return p - (lr / (1 - jnp.power(b1, t))) * m \
            / (u + self._epsilon), slots


class NAdam(Adam):
    """Adam with Nesterov momentum and the Dozat momentum-decay schedule
    mu_t = beta1*(1 - 0.5*0.96^(t*psi)) (matches torch.optim.NAdam; the
    running mu product lives in a scalar slot per leaf)."""

    def __init__(self, *args, momentum_decay=0.004, **kwargs):
        super().__init__(*args, **kwargs)
        self._momentum_decay = momentum_decay

    def _init_slots(self, p):
        s = super()._init_slots(p)
        s['mu_product'] = jnp.ones((), jnp.float32)
        return s

    def _rule(self, g, p, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        psi = self._momentum_decay
        m = b1 * slots['moment1'].astype(jnp.float32) + (1 - b1) * g
        v = b2 * slots['moment2'].astype(jnp.float32) \
            + (1 - b2) * jnp.square(g)
        slots['moment1'] = m.astype(self._moment_dtype)
        slots['moment2'] = v.astype(self._moment_dtype)
        t = jnp.asarray(step, jnp.float32)
        mu_t = b1 * (1 - 0.5 * jnp.power(0.96, t * psi))
        mu_t1 = b1 * (1 - 0.5 * jnp.power(0.96, (t + 1) * psi))
        mu_prod = slots['mu_product'] * mu_t
        slots['mu_product'] = mu_prod
        m_hat = mu_t1 * m / (1 - mu_prod * mu_t1) \
            + (1 - mu_t) * g / (1 - mu_prod)
        v_hat = v / (1 - jnp.power(b2, t))
        return p - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon), slots


class RAdam(Adam):
    """Rectified Adam (upstream: incubate/radam): falls back to
    unadapted SGD-with-momentum while the variance rectifier is
    untrustworthy (rho_t <= 4)."""

    def _rule(self, g, p, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots['moment1'].astype(jnp.float32) + (1 - b1) * g
        v = b2 * slots['moment2'].astype(jnp.float32) \
            + (1 - b2) * jnp.square(g)
        slots['moment1'] = m.astype(self._moment_dtype)
        slots['moment2'] = v.astype(self._moment_dtype)
        t = jnp.asarray(step, jnp.float32)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * jnp.power(b2, t) / (1 - jnp.power(b2, t))
        m_hat = m / (1 - jnp.power(b1, t))
        r = jnp.sqrt(jnp.maximum(
            (rho_t - 4) * (rho_t - 2) * rho_inf
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-9),
            0.0))
        # threshold 5 and eps-on-sqrt(v) match the torch/paddle
        # implementations (the paper's nominal cutoff is 4)
        adaptive = lr * r * m_hat * jnp.sqrt(1 - jnp.power(b2, t)) \
            / (jnp.sqrt(v) + self._epsilon)
        plain = lr * m_hat
        return p - jnp.where(rho_t > 5.0, adaptive, plain), slots


class Rprop(Optimizer):
    """Resilient backprop (upstream: optimizer/rprop.py) — per-weight
    step sizes grown/shrunk by gradient sign agreement; gradients'
    magnitudes are ignored."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._eta_minus, self._eta_plus = etas
        self._lr_min, self._lr_max = learning_rate_range
        try:
            self._lr0 = float(learning_rate)
        except (TypeError, ValueError):
            self._lr0 = 1e-2  # scheduler-driven: seed step sizes modestly

    def _init_slots(self, p):
        return {'prev_grad': jnp.zeros(p.shape, jnp.float32),
                'step_size': jnp.full(p.shape, self._lr0, jnp.float32)}

    def _rule(self, g, p, slots, lr, step):
        sign = jnp.sign(g * slots['prev_grad'])
        factor = jnp.where(sign > 0, self._eta_plus,
                           jnp.where(sign < 0, self._eta_minus, 1.0))
        size = jnp.clip(slots['step_size'] * factor, self._lr_min,
                        self._lr_max)
        # on sign flip, skip the update and zero the remembered grad
        g_eff = jnp.where(sign < 0, 0.0, g)
        slots['prev_grad'] = g_eff
        slots['step_size'] = size
        return p - size * jnp.sign(g_eff), slots


class ASGD(Optimizer):
    """Averaged SGD (upstream: optimizer/asgd.py): steps with the mean
    of the last `batch_num` gradients. The ring buffer of gradients is
    optimizer state, exactly like upstream (paddle allocates a
    [batch_num, *shape] accumulator per parameter — mind the HBM cost
    for large batch_num)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._batch_num = max(int(batch_num), 1)

    def _init_slots(self, p):
        if self._batch_num == 1:
            return {}
        return {'grad_ring': jnp.zeros((self._batch_num,) + tuple(p.shape),
                                       jnp.float32),
                'grad_sum': jnp.zeros(p.shape, jnp.float32)}

    def _rule(self, g, p, slots, lr, step):
        if self._batch_num == 1:
            return p - lr * g, slots
        n = self._batch_num
        t = step  # 1-based
        idx = (t - 1) % n
        old = slots['grad_ring'][idx]
        ssum = slots['grad_sum'] - old + g
        slots['grad_ring'] = slots['grad_ring'].at[idx].set(g)
        slots['grad_sum'] = ssum
        denom = jnp.minimum(t, n).astype(jnp.float32)
        return p - lr * ssum / denom, slots


# regularizer shims (upstream: python/paddle/regularizer.py)
class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
