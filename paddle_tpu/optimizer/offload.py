"""Host-offloaded optimizer state (upstream:
python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_optimizer_stage2.py `offload=True`, which pins FP32 master
weights + moments in CPU memory and updates parameters there).

TPU-native design: optimizer slots (Adam moments, fp32 masters) live in
the chip's `pinned_host` memory space instead of HBM. Each step streams
ONE parameter leaf's slots into HBM, runs a donated per-shape update
kernel, and streams the new slots back; jax's async dispatch overlaps
leaf i+1's PCIe transfer with leaf i's update compute. HBM then never
holds more than params + grads + one leaf's slots — for the Llama-2 7B
geometry that is the difference between 8 and 16+ layers training on a
single 16 GB chip (see bench.py `_7b_configs`). XLA's in-jit host
offload (`device_put` under jit) is not used because the remote-compile
tunnel rejects it; the eager streaming path compiles one tiny kernel
per (shape, dtype, decay-coeff) and is schedule-equivalent.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import SingleDeviceSharding

from .. import observability as _obs

_tree = jax.tree_util


def _slot_bytes(slots: Dict[str, Any]) -> int:
    return sum(int(v.size) * v.dtype.itemsize for v in slots.values())


def _note_transfer(direction: str, nbytes: int):
    """H2D/D2H ledger for the streamed optimizer slots — the number that
    tells you whether offload's PCIe traffic is hiding under compute or
    dominating the step. No-op while observability is disabled."""
    if not _obs.enabled() or not nbytes:
        return
    _obs.get_registry().counter(
        f'paddle_offload_{direction}_bytes_total',
        f'optimizer-slot {direction.upper()} transfer bytes').inc(nbytes)


def _host_sharding(device=None):
    device = device or jax.devices()[0]
    # TPU devices address host RAM as 'pinned_host'; the CPU backend
    # exposes it as 'unpinned_host' — take whichever this device has
    try:
        kinds = {m.kind for m in device.addressable_memories()}
    except Exception:  # paddle-lint: disable=swallowed-exception -- memory-kind probe; unpinned_host fallback is the documented CPU behavior
        kinds = ()
    kind = 'pinned_host' if 'pinned_host' in kinds else 'unpinned_host'
    return SingleDeviceSharding(device, memory_kind=kind)


def _device_sharding(device=None):
    device = device or jax.devices()[0]
    # 'device' (HBM) on accelerators; the CPU backend has no separate
    # device memory — use its default kind so offload degrades to a
    # correct (if pointless) host<->host stream there
    try:
        kind = device.default_memory().kind
    except Exception:  # paddle-lint: disable=swallowed-exception -- default_memory probe; device kind fallback documented for CPU
        kind = 'device'
    return SingleDeviceSharding(device, memory_kind=kind)


class OffloadEngine:
    """Streams an Optimizer's per-leaf updates through HBM while the
    slot state persists in pinned host memory."""

    def __init__(self, optimizer, device=None):
        self.opt = optimizer
        self.device = device or jax.devices()[0]
        self._host = _host_sharding(self.device)
        self._dev = _device_sharding(self.device)
        self._kernels: Dict[Any, Any] = {}

    # -- state --------------------------------------------------------------
    def init_state(self, params):
        def leaf(p):
            slots = self.opt._leaf_init(p)  # device zeros, one leaf at a
            return {k: jax.device_put(v, self._host)  # time -> no HBM spike
                    for k, v in slots.items()}
        slots = _tree.tree_map(leaf, params)
        return {'step': jnp.zeros((), jnp.int32), 'slots': slots}

    # -- kernels ------------------------------------------------------------
    def _kernel(self, g, p, slots, nm):
        # nm is part of the key: the compiled closure bakes the leaf
        # name in, and optimizers may branch on it beyond _coeff_for
        key = (nm, p.shape, str(p.dtype), str(g.dtype),
               tuple(sorted(slots.keys())))
        if key not in self._kernels:
            opt = self.opt

            def fn(gv, pv, sv, lr, step):
                return opt._leaf_apply(gv, pv, sv, lr, step, name=nm)
            # donate g, p, slots: the update is in-place in HBM
            self._kernels[key] = jax.jit(fn, donate_argnums=(0, 1, 2))  # paddle-lint: disable=donation-path -- per-leaf direct kernels, never store-served: the PR-8 corruption is export-path only
        return self._kernels[key]

    # -- apply --------------------------------------------------------------
    def apply(self, grads, params, state, lr_value):
        """(grads, params, host-state, lr) -> (new_params, new_state).
        Eager python loop; every kernel launch and transfer is async, so
        the H2D fetch of leaf i+1 rides under leaf i's compute."""
        if self.opt._grad_clip is not None:
            grads = self.opt._grad_clip.apply_pytree(grads)
        step = state['step'] + 1
        from . import _flatten_for_update
        treedef, names, flat_p, flat_g, flat_s = _flatten_for_update(
            params, grads, state['slots'])
        n = len(flat_p)
        lr = jnp.asarray(lr_value, jnp.float32)

        staged: list = [None] * n

        def fetch(i):
            if flat_g[i] is not None:
                staged[i] = {k: jax.device_put(v, self._dev)
                             for k, v in flat_s[i].items()}
                _note_transfer('h2d', _slot_bytes(flat_s[i]))
        if n:
            fetch(0)
        new_p, new_s = [], []
        for i in range(n):
            if i + 1 < n:
                fetch(i + 1)  # prefetch: H2D overlaps this leaf's update
            g, p, s, nm = flat_g[i], flat_p[i], flat_s[i], names[i]
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            np_, ns_ = self._kernel(g, p, staged[i], nm)(
                g, p, staged[i], lr, step)
            staged[i] = None
            new_p.append(np_)
            new_s.append({k: jax.device_put(v, self._host)
                          for k, v in ns_.items()})
            _note_transfer('d2h', _slot_bytes(new_s[-1]))
        return (_tree.tree_unflatten(treedef, new_p),
                {'step': step,
                 'slots': _tree.tree_unflatten(treedef, new_s)})
