"""LR schedulers (upstream: python/paddle/optimizer/lr.py).

Stateful step()-driven schedulers with state_dict round-trip. The jitted
train step reads `scheduler()` (current value) host-side each step and
feeds it as a traced scalar — no recompilation per LR change.
"""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def get_lr(self):
        raise NotImplementedError

    def step(self, epoch=None):
        self.last_epoch = self.last_epoch + 1 if epoch is None else epoch
        self.last_lr = float(self.get_lr())

    def __call__(self):
        return self.last_lr

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, (int, float, bool, str, list))}

    def set_state_dict(self, sd):
        self.__dict__.update(sd)

    def get_last_lr(self):
        return self.last_lr


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(1, self.last_epoch)
        return (self.base_lr * self.d_model ** -0.5
                * min(step ** -0.5, step * self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(max(step, 1) / self.decay_steps)
            decay_steps = self.decay_steps * max(div, 1)
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def state_dict(self):
        d = super().state_dict()
        d.pop('lr_lambda', None)
        return d


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (self.eta_min + (self.base_lr - self.eta_min)
                * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2)


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_after = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * (
                self.last_epoch / self.warmup_steps) + self.start_lr
        if isinstance(self.lr_after, LRScheduler):
            return self.lr_after()
        return self.lr_after

    def step(self, epoch=None):
        if self.last_epoch >= self.warmup_steps \
                and isinstance(self.lr_after, LRScheduler):
            self.lr_after.step(epoch)
        super().step(epoch)

    def state_dict(self):
        d = super().state_dict()
        if isinstance(self.lr_after, LRScheduler):
            d['lr_after'] = self.lr_after.state_dict()
        return d

    def set_state_dict(self, sd):
        sub = sd.pop('lr_after', None)
        self.__dict__.update(sd)
        if sub is not None and isinstance(self.lr_after, LRScheduler):
            self.lr_after.set_state_dict(sub)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy='cos', last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, a, b, pct):
        if self.anneal == 'cos':
            return b + (a - b) * (1 + math.cos(math.pi * pct)) / 2
        return a + (b - a) * pct

    def get_lr(self):
        step = min(self.last_epoch, self.total_steps)
        up = self.phase_pct * self.total_steps
        if step <= up:
            return self._interp(self.initial_lr, self.max_lr,
                                step / max(up, 1))
        pct = (step - up) / max(self.total_steps - up, 1)
        return self._interp(self.max_lr, self.end_lr, pct)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up=2000, step_size_down=None, mode='triangular',
                 exp_gamma=1.0, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        cycle_len = self.up + self.down
        pos = self.last_epoch % cycle_len
        frac = pos / self.up if pos < self.up else \
            1 - (pos - self.up) / self.down
        amp = self.max_lr - self.base_lr
        if self.mode == 'triangular2':
            amp = amp / (2 ** (self.last_epoch // cycle_len))
        elif self.mode == 'exp_range':
            amp = amp * self.exp_gamma ** self.last_epoch
        return self.base_lr + amp * frac


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode='min', factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode='rel', cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0

    def get_lr(self):
        return self.last_lr

    def _better(self, a, b):
        if b is None:
            return True
        if self.threshold_mode == 'rel':
            eps = 1.0 - self.threshold if self.mode == 'min' \
                else 1.0 + self.threshold
            return a < b * eps if self.mode == 'min' else a > b * eps
        return a < b - self.threshold if self.mode == 'min' \
            else a > b + self.threshold

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        m = float(metrics.numpy()) if hasattr(metrics, 'numpy') else float(metrics)
        self.last_epoch += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self._better(m, self.best):
            self.best = m
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.num_bad > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0


class CosineAnnealingWarmRestarts(LRScheduler):
    """SGDR (upstream: lr.py CosineAnnealingWarmRestarts): cosine decay
    restarting every T_i epochs, periods growing by T_mult."""

    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0,
                 last_epoch=-1, verbose=False):
        if T_0 <= 0 or T_mult < 1:
            raise ValueError('T_0 must be > 0 and T_mult >= 1')
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = max(self.last_epoch, 0)
        T_i, t_cur = self.T_0, t
        while t_cur >= T_i:
            t_cur -= T_i
            T_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) \
            * (1 + math.cos(math.pi * t_cur / T_i)) / 2


class MultiplicativeDecay(LRScheduler):
    """lr *= lr_lambda(epoch) each step (upstream
    paddle.optimizer.lr.MultiplicativeDecay). The factor applies
    cumulatively from epoch 1; the running product is tracked
    incrementally (O(1) per sequential step) and only rebuilt on epoch
    jumps (set_state_dict / explicit step(epoch))."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1,
                 verbose=False):
        self.lr_lambda = lr_lambda
        self._prod = 1.0
        self._prod_epoch = 0
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch == self._prod_epoch + 1:
            self._prod *= self.lr_lambda(self.last_epoch)
        elif self.last_epoch != self._prod_epoch:
            self._prod = 1.0
            for e in range(1, self.last_epoch + 1):
                self._prod *= self.lr_lambda(e)
        self._prod_epoch = self.last_epoch
        return self.base_lr * self._prod


class LinearLR(LRScheduler):
    """Linear ramp of the LR factor from start_factor to end_factor over
    total_steps (upstream paddle.optimizer.lr.LinearLR)."""

    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        if total_steps <= 0:
            raise ValueError('total_steps must be positive')
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = min(self.last_epoch, self.total_steps)
        frac = t / self.total_steps
        factor = self.start_factor + \
            (self.end_factor - self.start_factor) * frac
        return self.base_lr * factor
