"""paddle_tpu.programs — the unified persistent program store.

One `ProgramStore` owns AOT `lower().compile()` for every jitted
compilation tier (jit.TrainStep / to_static, the serving engine's
decode + prefill programs; the eager dispatch cache keeps its own
in-process tier and reports through the same catalog), keyed like the
dispatch cache plus a backend fingerprint, with an optional crash-safe
on-disk tier so a preempted trainer or a cold serving replica restarts
without paying XLA compiles. See store.py for the full contract.

Enable persistence with `programs.configure('/path/to/store')`, the
`FLAGS_program_store_dir` flag/env var, or the examples'
`--program-store` argument; `get_store().preload()` bulk-loads the
matching entries at startup (Model.fit and ReplicaSet do this
automatically when the store is persistent).
"""
from . import donation
from .store import (ProgramDeserializeError, ProgramStore, StoredJit,
                    backend_fingerprint, code_token, configure,
                    describe_statics, get_store, store_key)

__all__ = [
    'ProgramDeserializeError', 'ProgramStore', 'StoredJit',
    'backend_fingerprint', 'code_token', 'configure', 'describe_statics',
    'donation', 'get_store', 'store_key',
]
