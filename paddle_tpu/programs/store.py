"""Unified persistent program store: one owner for every compiled XLA
program in the process, with a crash-safe on-disk tier.

Before this module, three independent caches each managed compiled
executables — the eager dispatch cache (`_dispatch.py`), the
`jit`/`to_static` python-side caches, and the serving engine's
decode/prefill set — none of which survived a restart, so every
preemption resume and every cold serving replica paid minutes of XLA
recompiles before doing useful work. The `ProgramStore` is the single
compilation owner for the jitted tiers: `wrap_jit` AOT-compiles ONCE
per (name, fn source, statics, treedef, avals, sharding) key through
`lower().compile()`, folds the `ProgramCatalog` cost attribution in as
its bookkeeping (one `ProgramRecord` per named program — never tracked
twice), shares executables across wrappers with the same key (N serving
replicas of one model compile the decode block once), and — when a
store directory is configured — persists each executable so the next
process *loads* instead of compiling.

Persistence is two complementary layers under one store directory:

  'stablehlo'   `jax.export` bytes (the serialization `jit.save` already
                uses) — removes Python tracing from the restart path.
                The cold path compiles THROUGH the exported program
                (`jax.jit(exported.call)`, donation re-applied), so the
                cold and warm processes compile the identical module.
  <dir>/xla     jax's persistent compilation cache, pointed inside the
                store directory — serves the compiled executable BYTES
                on the warm path, so re-compiling the deserialized
                module is a cache read, not an XLA compile. The
                warm-restart tier-1 guard asserts every
                `paddle_jit_compiles_total` tick in the warm window is
                matched by a `paddle_jit_cache_hits_total` tick (zero
                real compiles).

(`jax.experimental.serialize_executable` — pickling the PjRt executable
itself — was evaluated first and rejected: deserialized donated
executables intermittently corrupt the heap on this jaxlib. The
export+cache pair reaches the same zero-compile warm restart through
two independently hardened upstream paths.)

Donation: whether a store-served program re-applies its recorded
`donate_argnums` is decided by the donation gauntlet (donation.py) —
a subprocess-isolated probe of the installed runtime run at store
init, manifest-recorded per backend fingerprint. On a 'safe' verdict
donated programs alias their buffers again (no transient 2x train
state); the first K invocations of each donated executable run under
a corruption sentinel, and a trip quarantines donation for this
fingerprint and recompiles undonated — mid-call, without surfacing
the garbage value. The DIRECT path (in-process `lower().compile()` of
the caller's own jit, no serialization) donates unconditionally: PR 8
established that only the export/deserialize path corrupts.

Crash safety (the robustness contract, fault-injection-tested in
tests/test_programs.py): entries are written payload-first with atomic
renames and committed by their manifest, every manifest carries a
sha256 of the payload plus a backend fingerprint (paddle_tpu/jax/jaxlib
versions, backend, device kind, device/process counts), and the load
path verifies ALL of it — a truncated file, a flipped byte, a stale
jaxlib, a half-written entry from a killed writer, or a racing second
writer can only ever produce a `program_cache_reject` event and a fresh
compile, never an exception out of the store. A poisoned cache degrades
to cold-start behavior; it cannot take down a trainer or replica.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import jax

from .. import flags as _flags
from .. import observability as _obs
from ..analysis.runtime import concurrency as _concurrency
from ..observability import cost as _cost
from . import donation as _donation

_MANIFEST_VERSION = 1

_flags.register_flag('FLAGS_program_store', True)
_flags.register_flag('FLAGS_program_store_dir', '')


class ProgramDeserializeError(RuntimeError):
    """A serialized program artifact could not be deserialized.

    Typed so callers (jit.load, the store's own disk tier) can fall back
    to a fresh compile instead of crashing on a raw internal exception.
    Carries the artifact path and the underlying reason."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(f'cannot deserialize program artifact {path}: '
                         f'{reason}')


# ---------------------------------------------------------------------------
# fingerprint + keying
# ---------------------------------------------------------------------------

def backend_fingerprint() -> Dict[str, Any]:
    """The compatibility envelope of a compiled executable: an entry
    written under a different fingerprint is rejected at load (a PjRt
    executable is only valid for the exact runtime that produced it;
    StableHLO survives more skew, but version-gating both keeps the
    invalidation rule simple and safe)."""
    try:
        import jaxlib
        jaxlib_version = jaxlib.__version__
    except Exception:
        jaxlib_version = 'unknown'
    try:
        from .. import version as _version
        own = _version.full_version
    except Exception:
        own = 'unknown'
    try:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else 'none'
        count = len(devs)
    except Exception:
        kind, count = 'unknown', 0
    try:
        procs = jax.process_count()
    except Exception:
        procs = 1
    return {
        'paddle_tpu': own,
        'jax': jax.__version__,
        'jaxlib': jaxlib_version,
        'backend': jax.default_backend(),
        'device_kind': kind,
        'device_count': count,
        'process_count': procs,
    }


def code_token(fn, _depth: int = 0) -> str:
    """Best-effort stable identity for a function/class body ACROSS
    processes (the in-process `id()` the dispatch cache uses is
    meaningless after a restart): sha256 of the source text plus the
    tokens of closure cells (a generic wrapper closing over the real
    loss fn keys on THAT fn's body, not the wrapper's), falling back to
    the bytecode, falling back to the qualified name. Catches a changed
    function/closure body; deeper changes (a helper the body calls) are
    covered by the fingerprint + the documented wipe rule."""
    target = getattr(fn, '__wrapped__', fn)
    try:
        import inspect
        blob = inspect.getsource(target)
    except Exception:  # paddle-lint: disable=swallowed-exception -- source unavailable (REPL/frozen); bytecode/qualname fallbacks below
        code = getattr(target, '__code__', None)
        if code is not None:
            blob = code.co_code.hex() + repr(code.co_consts)
        else:
            blob = getattr(target, '__qualname__',
                           type(target).__name__)
    if _depth < 3:
        func = getattr(target, '__func__', target)
        for cell in (getattr(func, '__closure__', None) or ()):
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if callable(v):
                # body token + scalar-attr token: a loss Layer keys on
                # its class AND its baked hyperparams (label smoothing)
                blob += code_token(v, _depth + 1) + describe_statics(v)
            else:
                blob += describe_statics(v)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def describe_statics(obj, _depth: int = 0) -> str:
    """Stable textual token for compile-time constants baked into a
    program (optimizer hyperparams, model config, engine geometry) —
    values that change the compiled computation WITHOUT changing any
    input aval. Best-effort: unknown objects degrade to their class
    name, never raise."""
    if _depth > 4:
        return '...'
    try:
        if obj is None or isinstance(obj, (bool, int, float, str)):
            return repr(obj)
        if isinstance(obj, (list, tuple)):
            inner = ','.join(describe_statics(v, _depth + 1) for v in obj)
            return f'[{inner}]'
        if isinstance(obj, dict):
            inner = ','.join(
                f'{k!r}:{describe_statics(obj[k], _depth + 1)}'
                for k in sorted(obj, key=repr))
            return f'{{{inner}}}'
        if hasattr(obj, '__dict__'):
            scalars = {k: v for k, v in vars(obj).items()
                       if isinstance(v, (bool, int, float, str, type(None)))
                       and not k.startswith('_')}
            return (f'{type(obj).__qualname__}'
                    f'({describe_statics(scalars, _depth + 1)})')
        return type(obj).__qualname__
    except Exception:  # paddle-lint: disable=swallowed-exception -- statics token must never raise; class name is the degraded token
        return type(obj).__name__


def _leaf_sig(leaf):
    dt = getattr(leaf, 'dtype', None)
    if dt is not None:
        shard = ''
        try:
            s = getattr(leaf, 'sharding', None)
            if s is not None and type(s).__name__ not in (
                    'SingleDeviceSharding',):
                shard = str(s)
        except Exception:  # paddle-lint: disable=swallowed-exception -- sharding probe; empty token means single-device layout
            pass
        return (tuple(getattr(leaf, 'shape', ())), str(dt),
                bool(getattr(leaf, 'weak_type', False)), shard)
    if isinstance(leaf, (bool, int, float, str, type(None))):
        return ('py', repr(leaf))
    return ('py', type(leaf).__name__)


def _mesh_token() -> str:
    """Active fleet mesh topology (axis names/sizes), part of the key so
    re-meshed programs never collide with their pre-resize ancestors."""
    try:
        from ..distributed import fleet
        mesh = fleet.get_mesh()
        if mesh is None:
            return ''
        return repr(tuple(zip(mesh.axis_names, mesh.devices.shape)))
    except Exception:  # paddle-lint: disable=swallowed-exception -- mesh token probe; empty token means no mesh
        return ''


def store_key(name: str, fn_token: str, statics_token: str, args) -> str:
    """The persistent cache key: sha256 over (name, fn identity, input
    treedef, tensor avals, static leaves, sharding, mesh) — the dispatch
    cache's key shape, made process-independent. The backend fingerprint
    is deliberately NOT part of the key: a skewed entry must be FOUND
    and rejected (with an event) rather than silently missed."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = tuple(_leaf_sig(leaf) for leaf in leaves)
    blob = repr((_MANIFEST_VERSION, name, fn_token, statics_token,
                 str(treedef), sig, _mesh_token()))
    return hashlib.sha256(blob.encode('utf-8')).hexdigest()[:32]


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------

def _export_program(jitted, args):
    """Trace `jitted` into a portable `jax.export.Exported` at the
    abstract shapes of `args` (the artifact the persistent tier
    stores). Typed PRNG-key leaves are rejected up front (the export
    flatbuffer cannot encode `key<fry>` avals — framework RNG uses raw
    keys for exactly this reason); callers degrade to the plain
    unpersisted compile."""
    from jax import export as _jex
    for leaf in jax.tree_util.tree_leaves(args):
        dt = getattr(leaf, 'dtype', None)
        if dt is not None and jax.dtypes.issubdtype(
                dt, jax.dtypes.prng_key):
            raise TypeError(
                'typed PRNG-key argument cannot be exported; pass raw '
                'uint32 key data (jax.random.PRNGKey / key_data)')
    plats = {'tpu', 'cpu', jax.default_backend()}
    abstract = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype)
        if hasattr(v, 'shape') else v, args)
    return _jex.export(jitted, platforms=tuple(sorted(plats)))(*abstract)


def _compile_exported(exported, donate_argnums=(), donated=False):
    """AOT-compile an exported program from its own recorded in_avals.

    No Python tracing of the original function; the backend compile of
    this module is served by jax's persistent compilation cache on warm
    restarts (same module bytes -> same cache key), so it costs a disk
    read, not an XLA compile.

    Donation: re-applying `donate_argnums` on the wrapper jit here is
    the exact operation that intermittently corrupts the heap on jaxlib
    0.4.36 (PR 8's fault-injection gauntlet: segfaults/garbage losses
    ~50% of runs; stable 12/12 without) — so it happens ONLY when the
    donation gauntlet classified the installed runtime 'safe'
    (`donated=True`, probe-verified or operator-forced, and sentinel-
    guarded by the caller for its first K invocations). Otherwise the
    program compiles undonated and `donate_argnums` just rides the
    manifest for a runtime that passes the probe."""
    specs = [jax.ShapeDtypeStruct(a.shape, a.dtype)
             for a in exported.in_avals]
    args, kwargs = jax.tree_util.tree_unflatten(exported.in_tree, specs)
    donate = tuple(donate_argnums) if donated else ()
    jitted = jax.jit(exported.call, donate_argnums=donate) if donate \
        else jax.jit(exported.call)
    return jitted.lower(*args, **kwargs).compile()


def _load_stablehlo(payload: bytes, path: str, donate_argnums=(),
                    donated=False):
    """Deserialize exported StableHLO and AOT-compile it — the warm
    half of the restart path."""
    from jax import export as _jex
    try:
        exported = _jex.deserialize(bytearray(payload))
    except Exception as exc:
        raise ProgramDeserializeError(
            path, f'{type(exc).__name__}: {exc}') from exc
    try:
        return _compile_exported(exported, donate_argnums, donated)
    except Exception as exc:
        raise ProgramDeserializeError(
            path, f'aot compile of deserialized program failed: '
                  f'{type(exc).__name__}: {exc}') from exc


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class _StoreEntry:
    __slots__ = ('key', 'name', 'kind', 'callable', 'source', 'format',
                 'fingerprint', 'donated', 'donate')

    def __init__(self, key, name, kind, call, source, fmt, fingerprint,
                 donated=False, donate=()):
        self.key = key
        self.name = name
        self.kind = kind
        self.callable = call
        self.source = source          # 'compile' | 'disk'
        self.format = fmt             # 'stablehlo' | '' (unpersisted)
        self.fingerprint = fingerprint
        # donate: the RECORDED donate_argnums (what the program wants);
        # donated: whether this executable was actually compiled with
        # them re-applied (export path + gauntlet-enabled at the time).
        # A posture change invalidates entries where the two disagree.
        self.donated = bool(donated)
        self.donate = tuple(donate)


class ProgramStore:
    """Process-wide owner of AOT-compiled executables, with an optional
    persistent tier. All state-changing paths are exception-safe: disk
    problems degrade to a fresh compile, never propagate."""

    def __init__(self, catalog: Optional[_cost.ProgramCatalog] = None,
                 directory: Optional[str] = None):
        # `is None`, not truthiness: these framework objects are falsy
        # when empty (the PR 10 EventLog rerouting bug class)
        self.catalog = catalog if catalog is not None else _cost.get_catalog()
        self._lock = _concurrency.RLock('ProgramStore._lock')
        self._mem: Dict[str, _StoreEntry] = {}
        self._dir = directory
        self._fingerprint = backend_fingerprint()
        self._hits_memory = 0
        self._hits_disk = 0
        self._misses = 0
        self._rejects = 0
        self._persisted = 0
        self._persist_skips = 0
        self._invalidated = 0
        self._preload: Optional[Dict[str, Any]] = None
        self._coldstart_s: Optional[float] = None
        # donation gauntlet state: posture dict from
        # donation.resolve_posture, a generation counter bumped on
        # quarantine (wrappers holding donated executables re-resolve),
        # and per-key sentinel budgets for the guarded first-K window
        self._donation: Dict[str, Any] = {'enabled': False,
                                          'posture': 'off',
                                          'verdict': None, 'reason': '',
                                          'source': 'init', 'token': ''}
        self._donation_gen = 0
        self._sentinel: Dict[str, int] = {}
        self._resolve_donation()

    # -- configuration -------------------------------------------------------
    @property
    def directory(self) -> Optional[str]:
        if self._dir is not None:
            return self._dir or None
        d = str(_flags.flag('FLAGS_program_store_dir') or '')
        return d or None

    @property
    def persistent(self) -> bool:
        return self.directory is not None

    def configure(self, directory: Optional[str]):
        """Point the store at a directory ('' / None disables the
        persistent tier; the in-memory tier is unaffected). Enabling
        also points jax's persistent compilation cache at
        `<directory>/xla` — the second half of the warm-restart path:
        our manifests carry the traced program, the XLA cache carries
        its compiled bytes."""
        self._dir = directory if directory else ''
        try:
            if directory:
                os.makedirs(directory, exist_ok=True)
                jax.config.update('jax_compilation_cache_dir',
                                  os.path.join(directory, 'xla'))
                # cache every program, however small/fast: the
                # zero-compile warm guard covers incidental converts too
                jax.config.update(
                    'jax_persistent_cache_min_compile_time_secs', 0.0)
                jax.config.update(
                    'jax_persistent_cache_min_entry_size_bytes', 0)
            else:
                jax.config.update('jax_compilation_cache_dir', None)
            # jax memoizes "is the cache used" at the FIRST compile of
            # the process — a store configured after any compile would
            # silently never cache. Reset so the next compile re-reads
            # the (re)configured directory.
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:  # paddle-lint: disable=swallowed-exception -- older jax without cc reset knobs still gets the stablehlo tier
            pass   # an older jax without these knobs still gets the
            # stablehlo tier (warm restarts then skip tracing only)
        self._resolve_donation()
        return self

    def refresh_fingerprint(self):
        """Recompute the backend fingerprint (the elastic layer calls
        this after a re-mesh: device count changed, so entries written
        under the old topology must stop matching) and drop in-memory
        entries that no longer match."""
        with self._lock:
            self._fingerprint = backend_fingerprint()
            stale = [k for k, e in self._mem.items()
                     if e.fingerprint != self._fingerprint]
            for k in stale:
                del self._mem[k]
            self._invalidated += len(stale)
        if stale:
            _obs.emit('program_store_invalidate', entries=len(stale),
                      reason='fingerprint_change')
        # a new fingerprint is a new runtime: its donation verdict may
        # differ (and a quarantine recorded for the OLD runtime no
        # longer applies)
        self._resolve_donation()
        return len(stale)

    # -- donation gauntlet ---------------------------------------------------
    def _resolve_donation(self) -> Dict[str, Any]:
        """(Re)run the gauntlet's decision procedure for the current
        directory + fingerprint (probing in a subprocess when 'auto'
        finds no recorded verdict — see donation.resolve_posture)."""
        posture = _donation.resolve_posture(self.directory,
                                            self._fingerprint)
        with self._lock:
            flipped = bool(posture.get('enabled')) \
                != bool(self._donation.get('enabled'))
            self._donation = posture
            if flipped:
                # entries compiled under the OTHER posture stop being
                # served: an undonated executable under 'on' silently
                # loses the aliasing, a donated one under 'off' is the
                # exact hazard the gauntlet exists to prevent
                stale = [k for k, e in self._mem.items()
                         if e.donate
                         and e.donated != bool(posture.get('enabled'))]
                for k in stale:
                    del self._mem[k]
                    self._sentinel.pop(k, None)
                self._donation_gen += 1
        return posture

    @property
    def donation_enabled(self) -> bool:
        """True when store-served programs re-apply their recorded
        donate_argnums (probe-verified safe, or operator-forced)."""
        return bool(self._donation.get('enabled'))

    @property
    def donation_gen(self) -> int:
        """Bumped on quarantine; wrappers caching donated executables
        compare it to know their callable was invalidated."""
        return self._donation_gen

    def donation_state(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._donation)
            out['donated_entries'] = sum(1 for e in self._mem.values()
                                         if e.donated)
            out['sentinel_pending'] = sum(self._sentinel.values())
        return out

    def _arm_sentinel(self, key: str):
        n = _donation.sentinel_budget()
        if n > 0:
            with self._lock:
                self._sentinel[key] = n

    def sentinel_remaining(self, key: str) -> int:
        with self._lock:
            return self._sentinel.get(key, 0)

    def sentinel_call(self, key: str, name: str, call, args):
        """One guarded invocation inside the post-enablement window:
        the donated executable consumes snapshot COPIES of the args (so
        the originals survive for an undonated re-run), and the outputs
        pass a finiteness sentinel before anything sees them. Returns
        ``(out, ok)`` — on ``ok=False`` donation has been QUARANTINED
        and the caller must recompile undonated and re-run; the corrupt
        value is never returned."""
        snap = _donation.snapshot_args(args)
        detail = ''
        try:
            out = call(*snap)
            ok = _donation.outputs_ok(out)
            if not ok:
                detail = 'non-finite output'
        except Exception as exc:
            # the donated executable blowing up inside the guard window
            # is a trip, not a crash: the snapshots absorbed the damage
            out, ok = None, False
            detail = f'{type(exc).__name__}: {exc}'
        if _obs.enabled():
            _obs.get_registry().counter(
                'paddle_donation_sentinel_checks_total',
                'sentinel-guarded invocations of donated programs').inc()
        if ok:
            with self._lock:
                left = self._sentinel.get(key, 0) - 1
                if left <= 0:
                    self._sentinel.pop(key, None)
                else:
                    self._sentinel[key] = left
            return out, True
        self.quarantine_donation(f'sentinel tripped on {name}: {detail}')
        return None, False

    def quarantine_donation(self, reason: str) -> int:
        """Donation corrupted on this runtime: flip the posture off,
        drop every donated executable from the memory tier (the next
        acquire recompiles undonated from the SAME payload), bump the
        generation so wrappers re-resolve, and record the quarantine —
        verdict manifest + `donation_quarantined` event (a flight-
        recorder trigger). Idempotent once quarantined."""
        with self._lock:
            if self._donation.get('posture') == 'quarantined':
                return 0
            self._donation = {
                'enabled': False, 'posture': 'quarantined',
                'verdict': 'quarantined', 'reason': str(reason),
                'source': 'sentinel',
                'token': self._donation.get('token', ''),
            }
            self._donation_gen += 1
            stale = [k for k, e in self._mem.items() if e.donated]
            for k in stale:
                del self._mem[k]
            self._sentinel.clear()
        # outside the store lock: quarantine() emits the event that
        # triggers a flight bundle, whose listeners read other locks
        _donation.quarantine(self.directory, self._fingerprint, reason)
        return len(stale)

    # -- metrics/events helpers ---------------------------------------------
    def _counter(self, name, help_, **labels):
        if not _obs.enabled():
            return None
        reg = _obs.get_registry()
        if labels:
            return reg.counter(name, help_,
                               tuple(sorted(labels))).labels(**labels)
        return reg.counter(name, help_)

    def _note_hit(self, name: str, tier: str, fmt: str = ''):
        with self._lock:
            if tier == 'memory':
                self._hits_memory += 1
            else:
                self._hits_disk += 1
        c = self._counter('paddle_program_cache_hits_total',
                          'program-store hits by tier', tier=tier)
        if c is not None:
            c.inc()
        _obs.emit('program_cache_hit', program=name, tier=tier,
                  **({'format': fmt} if fmt else {}))

    def _note_miss(self, name: str):
        with self._lock:
            self._misses += 1
        c = self._counter('paddle_program_cache_misses_total',
                          'program-store misses (fresh compiles)')
        if c is not None:
            c.inc()
        _obs.emit('program_cache_miss', program=name)

    def _note_reject(self, name: str, path: str, reason: str,
                     detail: str = ''):
        with self._lock:
            self._rejects += 1
        c = self._counter('paddle_program_cache_rejects_total',
                          'persisted entries rejected at load',
                          reason=reason)
        if c is not None:
            c.inc()
        _obs.emit('program_cache_reject', program=name, path=path,
                  reason=reason, **({'detail': detail} if detail else {}))

    # -- disk tier -----------------------------------------------------------
    def _paths(self, key: str):
        d = self.directory
        return (os.path.join(d, f'{key}.bin'),
                os.path.join(d, f'{key}.json'))

    def _save_disk(self, key: str, name: str, kind: str, payload: bytes,
                   donate_argnums=()) -> Optional[str]:
        """Persist one exported program: payload first, manifest second,
        both through atomic renames (a crash between the two leaves a
        manifest-less payload, which the load path treats as absent; a
        racing writer's os.replace wins wholesale — either way every
        committed entry is internally consistent)."""
        d = self.directory
        if d is None:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            fmt = 'stablehlo'
            bin_path, man_path = self._paths(key)
            nonce = f'.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp'
            tmp_bin = bin_path + nonce
            with open(tmp_bin, 'wb') as f:
                f.write(payload)
            os.replace(tmp_bin, bin_path)
            manifest = {
                'version': _MANIFEST_VERSION,
                'key': key,
                'name': name,
                'kind': kind,
                'format': fmt,
                'sha256': hashlib.sha256(payload).hexdigest(),
                'size': len(payload),
                'donate_argnums': list(donate_argnums),
                'fingerprint': self._fingerprint,
                'created': time.time(),
            }
            tmp_man = man_path + nonce
            with open(tmp_man, 'w') as f:
                json.dump(manifest, f, indent=1)
            os.replace(tmp_man, man_path)
            with self._lock:
                self._persisted += 1
            _obs.emit('program_store_persist', program=name, format=fmt,
                      bytes=len(payload))
            return fmt
        except Exception as exc:
            # persistence is an optimization: failing to write must
            # never fail the call that just compiled successfully
            with self._lock:
                self._persist_skips += 1
            _obs.emit('program_store_persist_skipped', program=name,
                      error=type(exc).__name__)
            return None

    def _load_disk(self, key: str):
        """Integrity-verified load of one persisted entry. Returns a
        `_StoreEntry` or None; NEVER raises. Every rejection emits
        `program_cache_reject` with its reason."""
        d = self.directory
        if d is None:
            return None
        bin_path, man_path = self._paths(key)
        if not os.path.exists(man_path):
            return None   # absent (or uncommitted half-write): plain miss
        try:
            with open(man_path) as f:
                manifest = json.load(f)
        except Exception as exc:
            self._note_reject(key, man_path, 'manifest_unreadable',
                              type(exc).__name__)
            return None
        name = str(manifest.get('name', key))
        if manifest.get('version') != _MANIFEST_VERSION:
            self._note_reject(name, man_path, 'manifest_version')
            return None
        if manifest.get('fingerprint') != self._fingerprint:
            self._note_reject(name, man_path, 'fingerprint')
            return None
        try:
            with open(bin_path, 'rb') as f:
                payload = f.read()
        except OSError:
            self._note_reject(name, bin_path, 'payload_missing')
            return None
        if hashlib.sha256(payload).hexdigest() != manifest.get('sha256'):
            self._note_reject(name, bin_path, 'checksum')
            return None
        fmt = manifest.get('format', '')
        donate = tuple(manifest.get('donate_argnums') or ())
        donated = bool(donate) and self.donation_enabled
        try:
            if fmt == 'stablehlo':
                call = _load_stablehlo(payload, bin_path, donate,
                                       donated=donated)
            else:
                self._note_reject(name, bin_path, 'format', fmt)
                return None
        except ProgramDeserializeError as exc:
            self._note_reject(name, bin_path, 'deserialize', exc.reason)
            return None
        except Exception as exc:   # belt and braces: load NEVER raises
            self._note_reject(name, bin_path, 'deserialize',
                              type(exc).__name__)
            return None
        if donated:
            self._arm_sentinel(key)
        return _StoreEntry(key, name, str(manifest.get('kind', 'jit')),
                           call, 'disk', fmt, self._fingerprint,
                           donated=donated, donate=donate)

    # -- the acquisition path ------------------------------------------------
    def acquire(self, key: str, name: str, kind: str,
                record: _cost.ProgramRecord,
                compile_fn: Callable[[], Any],
                jitted=None, args=None, persist: bool = True,
                donate_argnums=()):
        """Resolve one program key to an executable: memory tier, then
        the integrity-verified disk tier, then a fresh AOT compile.

        With a persistent store, the fresh compile goes THROUGH the
        export artifact (trace -> serialize -> compile the exported
        module) so the cold process compiles the exact module a warm
        process will deserialize — the XLA persistent cache then serves
        the warm compile from disk. Export failures fall back to the
        plain direct compile (memory tier only, note='aot_noexport').
        Returns the resolved `_StoreEntry` (callable + donation flag),
        or None when no AOT path works at all — callers fall back to
        their plain jitted call."""
        with self._lock:
            ent = self._mem.get(key)
        if ent is not None:
            self._note_hit(name, 'memory', ent.format)
            if ent.source == 'disk':
                record.note = record.note or f'loaded:{ent.format}'
            return ent
        ent = self._load_disk(key)
        if ent is not None:
            t0 = time.perf_counter()
            _cost._read_analysis(ent.callable, record)
            record.note = f'loaded:{ent.format}'
            with self._lock:
                self._mem[key] = ent
            with self.catalog._lock:
                record.compile_seconds += time.perf_counter() - t0
            self._note_hit(name, 'disk', ent.format)
            return ent
        # cold: compile fresh
        persisting = (persist and self.persistent
                      and bool(_flags.flag('FLAGS_program_store'))
                      and jitted is not None and args is not None)
        t0 = time.perf_counter()
        compiled = payload = None
        fmt = ''
        donated = False
        if persisting:
            try:
                exported = _export_program(jitted, args)
                payload = exported.serialize()
                donated = bool(donate_argnums) and self.donation_enabled
                compiled = _compile_exported(exported, donate_argnums,
                                             donated=donated)
                fmt = 'stablehlo'
            except Exception as exc:
                donated = False
                _obs.emit('program_store_persist_skipped', program=name,
                          error=type(exc).__name__)
        if compiled is None:
            try:
                compiled = compile_fn()
            except Exception:  # paddle-lint: disable=swallowed-exception -- no AOT path for this callable; caller serves the plain jitted call which surfaces any real error
                return None   # no AOT path; caller serves the plain call
            if persisting:
                record.note = 'aot_noexport'
        dt = time.perf_counter() - t0
        with self.catalog._lock:
            record.compile_count += 1
            record.compile_seconds += dt
        _cost._read_analysis(compiled, record)
        self._note_miss(name)
        ent = _StoreEntry(key, name, kind, compiled, 'compile', fmt,
                          self._fingerprint, donated=donated,
                          donate=donate_argnums)
        if donated:
            self._arm_sentinel(key)
        with self._lock:
            self._mem[key] = ent
        if payload is not None:
            self._save_disk(key, name, kind, payload,
                            donate_argnums=donate_argnums)
        return ent

    # -- warm restart --------------------------------------------------------
    def preload(self, match: Optional[str] = None) -> Dict[str, Any]:
        """Bulk-load every committed, fingerprint-matching entry into
        the in-memory tier (the warm-restart path: a resumed trainer or
        a cold replica materializes its executables BEFORE serving).
        Holds the ref-counted `warming` degraded state on /healthz for
        the duration. Idempotent: already-resident keys are skipped.
        `match` restricts to names containing the substring."""
        d = self.directory
        stats = {'loaded': 0, 'skipped': 0, 'rejected': 0, 'seconds': 0.0}
        if d is None or not os.path.isdir(d):
            return stats
        t0 = time.perf_counter()
        rejects_before = self._rejects
        _obs.note_degraded('warming', {'dir': d})
        try:
            for fname in sorted(os.listdir(d)):
                if not fname.endswith('.json') or '.tmp' in fname:
                    continue
                key = fname[:-len('.json')]
                with self._lock:
                    if key in self._mem:
                        stats['skipped'] += 1
                        continue
                if match is not None:
                    try:
                        with open(os.path.join(d, fname)) as f:
                            if match not in str(json.load(f).get('name')):
                                stats['skipped'] += 1
                                continue
                    except Exception:  # paddle-lint: disable=swallowed-exception -- unreadable manifest: _load_disk rejects it with a counted program_cache_reject
                        pass   # unreadable manifest: let _load_disk reject
                ent = self._load_disk(key)
                if ent is None:
                    continue
                record = self.catalog.record(ent.name, kind=ent.kind)
                _cost._read_analysis(ent.callable, record)
                record.note = f'loaded:{ent.format}'
                with self._lock:
                    self._mem[key] = ent
                self._note_hit(ent.name, 'disk', ent.format)
                stats['loaded'] += 1
        finally:
            _obs.clear_degraded('warming')
        stats['seconds'] = round(time.perf_counter() - t0, 4)
        stats['rejected'] = self._rejects - rejects_before
        try:
            from ..observability import server as _srv
            self._coldstart_s = round(
                time.monotonic() - _srv._START, 4)
        except Exception:  # paddle-lint: disable=swallowed-exception -- server module optional; coldstart gauge just stays unset
            self._coldstart_s = None
        with self._lock:
            self._preload = dict(stats)
        if _obs.enabled():
            reg = _obs.get_registry()
            reg.gauge('paddle_program_preload_seconds',
                      'wall seconds of the last program-store preload'
                      ).set(stats['seconds'])
            reg.gauge('paddle_program_preload_loaded',
                      'programs loaded by the last preload'
                      ).set(stats['loaded'])
            if self._coldstart_s is not None:
                reg.gauge('paddle_coldstart_seconds',
                          'process start -> program store warm'
                          ).set(self._coldstart_s)
        _obs.emit('program_store_preload', **stats)
        return stats

    # -- wrapping ------------------------------------------------------------
    def wrap_jit(self, fn, name: Optional[str] = None,
                 name_fn: Optional[Callable] = None, kind: str = 'jit',
                 statics: Any = None, persist: bool = True,
                 donate_argnums=()) -> 'StoredJit':
        """Enroll a jax.jit'd callable: AOT compile through the store
        (memory -> disk -> compile), cost attribution folded into the
        catalog. `statics` names the compile-time constants baked into
        the program that its input avals cannot see (optimizer
        hyperparams, model config, engine geometry) — part of the
        persistent key. `donate_argnums` mirrors the wrapped jit's
        donation so it survives the export round trip (recorded in the
        manifest for the warm process)."""
        return StoredJit(self, fn, name=name, name_fn=name_fn, kind=kind,
                         statics=statics, persist=persist,
                         donate_argnums=donate_argnums)

    # -- bookkeeping / reporting --------------------------------------------
    def program_names(self) -> List[str]:
        with self._lock:
            return sorted({e.name for e in self._mem.values()})

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{'key': e.key, 'name': e.name, 'kind': e.kind,
                     'source': e.source, 'format': e.format,
                     'donated': e.donated}
                    for e in self._mem.values()]

    def disk_entries(self) -> int:
        d = self.directory
        if d is None or not os.path.isdir(d):
            return 0
        try:
            return sum(1 for f in os.listdir(d)
                       if f.endswith('.json') and '.tmp' not in f)
        except OSError:
            return 0

    def wipe(self) -> int:
        """Safely clear the persistent tier (the documented answer to a
        suspect cache): removes committed entries AND stray tmp files;
        in-memory executables stay valid."""
        d = self.directory
        if d is None or not os.path.isdir(d):
            return 0
        n = 0
        for fname in os.listdir(d):
            if fname.endswith(('.bin', '.json')) or '.tmp' in fname:
                try:
                    os.unlink(os.path.join(d, fname))
                    n += 1
                except OSError:
                    pass
        _obs.emit('program_store_wipe', files=n, dir=d)
        return n

    def clear_memory(self):
        with self._lock:
            self._mem.clear()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                'persistent': self.persistent,
                'dir': self.directory,
                'memory_entries': len(self._mem),
                'programs': len({e.name for e in self._mem.values()}),
                'loaded_from_disk': sum(1 for e in self._mem.values()
                                        if e.source == 'disk'),
                'hits_memory': self._hits_memory,
                'hits_disk': self._hits_disk,
                'misses': self._misses,
                'rejects': self._rejects,
                'persisted': self._persisted,
                'persist_skips': self._persist_skips,
                'invalidated': self._invalidated,
                'preload': dict(self._preload) if self._preload else None,
                'coldstart_seconds': self._coldstart_s,
            }
        out['disk_entries'] = self.disk_entries()
        out['donation'] = self.donation_state()
        return out

    def verify_catalog_consistency(self) -> Dict[str, Any]:
        """The double-attribution guard: every store-owned program is
        tracked by exactly one catalog record, and no jitted-tier
        catalog record exists outside the store. (Dispatch-tier records
        mirror the eager cache and are excluded — the eager tier keeps
        its own in-process cache and reports through the same catalog.)
        Returns the comparison; tier-1 asserts the sets match."""
        store_names = set(self.program_names())
        catalog_names = {r.name for r in self.catalog.records()
                         if r.kind != 'dispatch'
                         and (r.compile_count > 0
                              or r.note.startswith('loaded:'))}
        return {
            'store': sorted(store_names),
            'catalog': sorted(catalog_names),
            'only_in_store': sorted(store_names - catalog_names),
            'only_in_catalog': sorted(catalog_names - store_names),
            'consistent': store_names == catalog_names,
        }

    def reset_stats(self):
        with self._lock:
            self._hits_memory = self._hits_disk = 0
            self._misses = self._rejects = 0
            self._persisted = self._persist_skips = 0
            self._invalidated = 0
            self._preload = None


class StoredJit:
    """A jax.jit'd callable enrolled in the program store (the successor
    of observability.cost.CatalogedJit — same calling contract, same
    cost attribution, plus the shared memory tier and persistence).

    First call per input signature resolves through the store: an
    executable already resident (compiled by another wrapper with the
    same key — e.g. a sibling serving replica) or persisted on disk is
    reused; otherwise the one AOT `lower().compile()` the plain call
    would have cost runs here, and its analysis lands in the program
    record. Any AOT failure falls back to the plain jitted call for
    that signature ('aot_unavailable')."""

    def __init__(self, store: ProgramStore, fn, name: Optional[str] = None,
                 name_fn: Optional[Callable] = None, kind: str = 'jit',
                 statics: Any = None, persist: bool = True,
                 donate_argnums=()):
        if name is None and name_fn is None:
            raise ValueError('StoredJit needs name= or name_fn=')
        self._store = store
        self._name = name
        self._name_fn = name_fn
        self._kind = kind
        self._persist = persist
        self._donate = tuple(donate_argnums)
        # the store is the donation owner: callers pass the RAW function
        # plus its donate_argnums and the wrapper jits it here — the
        # DIRECT path donates as declared (in-process compile, the
        # PR-8-safe case), while the export path re-applies donation
        # only on a gauntlet-safe verdict. Already-jitted callables are
        # still accepted (their donation is whatever they baked in),
        # and OPAQUE callables (class instances without .lower) are
        # deliberately NOT auto-jitted — they keep the plain-call
        # 'aot_unavailable' fallback, since tracing an arbitrary
        # callable can change its semantics.
        import types
        if hasattr(fn, 'lower'):
            self._fn = fn
        elif isinstance(fn, (types.FunctionType, types.MethodType)):
            self._fn = jax.jit(fn, donate_argnums=self._donate) \
                if self._donate else jax.jit(fn)
        else:
            self._fn = fn
        self._fn_token = code_token(fn)
        self._statics_token = describe_statics(statics)
        # sig -> (record, callable, store_key, donated, donation_gen)
        self._entries: Dict[Any, Any] = {}

    def _signature(self, args):
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = []
        for leaf in leaves:
            dt = getattr(leaf, 'dtype', None)
            if dt is not None:
                sig.append((tuple(getattr(leaf, 'shape', ())), str(dt),
                            bool(getattr(leaf, 'weak_type', False))))
            else:
                sig.append(('py', type(leaf)))
        key = (treedef, tuple(sig))
        hash(key)
        return key

    def _build(self, key, args):
        if self._name is not None:
            name = self._name
        else:
            try:
                name = self._name_fn(args)
            except Exception:  # paddle-lint: disable=swallowed-exception -- naming must never fail a call; kind:unnamed IS the visible trace
                name = f'{self._kind}:unnamed'   # naming must never fail
        record = self._store.catalog.record(name, kind=self._kind)
        call = self._fn
        skey = None
        donated = False
        if key is not None:
            try:
                skey = store_key(name, self._fn_token,
                                 self._statics_token, args)
            except Exception:
                # unkeyable statics: this program silently loses
                # persistence — make "silently" false
                _obs.count_suppressed('program_store.key')
                skey = None
            got = None
            if skey is not None and bool(_flags.flag('FLAGS_program_store')):
                ent = self._store.acquire(
                    skey, name, self._kind, record,
                    compile_fn=lambda: self._fn.lower(*args).compile(),
                    jitted=self._fn, args=args, persist=self._persist,
                    donate_argnums=self._donate)
                if ent is not None:
                    got = ent.callable
                    donated = ent.donated
            else:
                # store bypassed: keep the plain AOT-compile behavior
                t0 = time.perf_counter()
                try:
                    got = self._fn.lower(*args).compile()
                    dt = time.perf_counter() - t0
                    with self._store.catalog._lock:
                        record.compile_count += 1
                        record.compile_seconds += dt
                    _cost._read_analysis(got, record)
                except Exception:  # paddle-lint: disable=swallowed-exception -- AOT re-analysis failed post-acquire; record.note=aot_unavailable carries the posture
                    got = None
            if got is not None:
                call = got
            else:
                record.note = 'aot_unavailable'
            entry = (record, call, skey, donated,
                     self._store.donation_gen)
            self._entries[key] = entry
            return entry
        return (record, call, skey, donated, self._store.donation_gen)

    def __call__(self, *args):
        try:
            key = self._signature(args)
        except Exception:
            # an unkeyable signature re-resolves the program EVERY call
            # — survivable, but it must be visible when it happens per
            # step instead of once
            _obs.count_suppressed('program_store.signature')
            key = None
        entry = self._entries.get(key) if key is not None else None
        t0 = time.perf_counter()
        if entry is None:
            entry = self._build(key, args)
        record, call, skey, donated, gen = entry
        if self._donate and gen != self._store.donation_gen:
            # the donation posture moved since this executable was
            # resolved (quarantine, or a flag/verdict flip at
            # re-configure): drop it and re-resolve under the current
            # posture
            self._entries.pop(key, None)
            record, call, skey, donated, gen = self._build(key, args)
        if donated and skey is not None \
                and self._store.sentinel_remaining(skey) > 0:
            out, ok = self._store.sentinel_call(skey, record.name, call,
                                                args)
            if not ok:
                # sentinel tripped → donation quarantined; recompile
                # undonated and serve the SAME call from the original
                # (never-donated) args — garbage never surfaces
                self._entries.pop(key, None)
                record, call, skey, donated, gen = self._build(key, args)
                out = call(*args)
        else:
            out = call(*args)
        dt = time.perf_counter() - t0
        with self._store.catalog._lock:
            record.invocations += 1
            record.host_seconds += dt
        return out

    # the wrapped object still answers AOT introspection (TrainStep's
    # memory_analysis does `self._jitted.lower(...)`); the lowering
    # cache makes that free after the wrapper's own compile
    def __getattr__(self, name):
        return getattr(self._fn, name)


_store: Optional[ProgramStore] = None
_store_lock = _concurrency.Lock('store._store_lock')


def get_store() -> ProgramStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = ProgramStore()
            d = _store.directory
            if d:   # flag/env-configured: engage the full persistent
                _store.configure(d)   # tier incl. the XLA cache dir
        return _store


def configure(directory: Optional[str]) -> ProgramStore:
    """Point the process-wide store at `directory` (None/'' = memory
    only). The env/flag `FLAGS_program_store_dir` is the declarative
    form; this is the programmatic one (examples' --program-store)."""
    return get_store().configure(directory)
