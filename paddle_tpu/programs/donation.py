"""Donation gauntlet: probe-and-enable buffer donation for store-served
programs.

PR 8 discovered that store-served executables (jax.export StableHLO
payloads re-compiled through ``jax.jit(exported.call)``) intermittently
HEAP-CORRUPT when donation is re-applied on jaxlib 0.4.36 — segfaults
and garbage losses on roughly half of 14-run gauntlets. The store has
run every persisted program UNDONATED since: memory-safe, but every
serving pool op paid a full pool-buffer round trip and donated train
state transiently 2x-buffered (the ROADMAP "Kill the copy" tax).

This module replaces the hardcoded posture with a *probe*: at
ProgramStore init (when a persistent directory is configured) a
subprocess-isolated gauntlet compiles a small donated store-served
executable — export → serialize → deserialize → ``jax.jit(call,
donate_argnums)`` → AOT compile, the exact code path the store uses —
and re-runs it against an undonated reference of the same exported
module. Bitwise-equal, finite outputs across every run classify the
installed runtime ``safe``; a mismatch, a non-finite value, a non-zero
exit (the probe segfaulting must never take the trainer with it — hence
the subprocess), or a timeout classify it ``corrupting``. The verdict is
manifest-recorded per backend fingerprint in the store directory, so a
jaxlib upgrade flips donation back on with zero code change, and a
process-level cache keeps re-inits from re-probing.

On a ``safe`` verdict the store re-applies each program's recorded
``donate_argnums`` and guards the first K post-enablement invocations
with corruption sentinels (finiteness spot-checks on the outputs, run
against snapshot copies of the donated inputs so a trip can re-run
undonated). A tripped sentinel QUARANTINES donation for this
fingerprint — verdict file flipped, donated executables dropped and
recompiled undonated, ``donation_quarantined`` emitted (a
flight-recorder trigger) — and the triggering call re-runs undonated,
so a garbage value is never surfaced.

Deployment note (single-client accelerators): on a TPU the probe child
cannot attach while the parent holds the device — the probe then times
out and the verdict conservatively lands ``corrupting``. Record the
verdict BEFORE launching instead: ``python -m paddle_tpu.programs.donation
<store_dir>`` runs the gauntlet standalone and commits the verdict the
next ProgramStore init will read. ``FLAGS_donation=on|off`` overrides
the probe entirely (``on`` still honors a recorded quarantine).

Test hooks: ``PADDLE_DONATION_PROBE_MODE`` = ``ok`` (skip the donated
trials, report safe) | ``garbage`` (corrupt one probe output — the
simulated corrupting runtime) | ``segv`` (the probe child kills itself
with SIGSEGV). Production leaves it unset.
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional

from .. import flags as _flags
from .. import observability as _obs
from ..analysis.runtime import concurrency as _concurrency

_flags.register_flag('FLAGS_donation', 'auto')          # auto | on | off
_flags.register_flag('FLAGS_donation_probe_runs', 8)
_flags.register_flag('FLAGS_donation_probe_timeout', 180.0)
_flags.register_flag('FLAGS_donation_sentinel', 8)      # guarded calls

_VERDICT_VERSION = 1

#: fingerprint-token -> verdict dict; one probe per process per runtime
#: (test helpers reset this via `clear_cache()`)
_PROC_VERDICTS: Dict[str, Dict[str, Any]] = {}
_probe_lock = _concurrency.Lock('donation._probe_lock')


def clear_cache():
    """Drop the process-level verdict cache (tests re-probing)."""
    _PROC_VERDICTS.clear()


def fingerprint_token(fingerprint: Dict[str, Any]) -> str:
    """Stable short token for one backend fingerprint — the key the
    verdict manifest is recorded under."""
    blob = json.dumps(fingerprint, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# the subprocess probe
# ---------------------------------------------------------------------------

# The probe child reproduces the store-served path byte for byte:
# export a donated train-step-shaped program, serialize, deserialize,
# re-apply donation on the wrapper jit, AOT-compile, and drive a chain
# of donated steps per trial — comparing bitwise against the SAME
# exported module compiled undonated. Only jax is imported (the probe
# targets the compiler/runtime boundary, not this framework).
_PROBE_SRC = r'''
import json, os, signal, sys
mode = os.environ.get('PADDLE_DONATION_PROBE_MODE', '')
runs = int(os.environ.get('PADDLE_DONATION_PROBE_RUNS', '8'))
chain = int(os.environ.get('PADDLE_DONATION_PROBE_CHAIN', '6'))
if mode == 'ok':
    print(json.dumps({'ok': True, 'runs': 0, 'detail': 'forced ok'}))
    sys.exit(0)
import numpy as np
import jax
import jax.numpy as jnp
from jax import export as _jex


def step(state, x):
    w, m = state['w'], state['m']
    g = jnp.tanh(x @ w)
    gw = x.T @ g / x.shape[0]
    m2 = 0.9 * m + 0.1 * gw
    w2 = w - 0.05 * m2
    return {'w': w2, 'm': m2}


def init():
    k = jax.random.PRNGKey(0)
    w = jax.random.normal(k, (48, 48), jnp.float32)
    return {'w': w, 'm': jnp.zeros_like(w)}


x = jnp.asarray(np.random.RandomState(1).standard_normal(
    (8, 48)).astype('float32'))
abstract = jax.tree_util.tree_map(
    lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), (init(), x))
plats = tuple(sorted({'cpu', jax.default_backend()}))
exported = _jex.export(jax.jit(step, donate_argnums=(0,)),
                       platforms=plats)(*abstract)
payload = exported.serialize()
de = _jex.deserialize(bytearray(payload))
ref_fn = jax.jit(de.call).lower(*abstract).compile()
don_fn = jax.jit(de.call, donate_argnums=(0,)).lower(*abstract).compile()

state = init()
for _ in range(chain):
    state = ref_fn(state, x)
ref = {k: np.asarray(v) for k, v in state.items()}

if mode == 'segv':
    os.kill(os.getpid(), signal.SIGSEGV)

ok, detail = True, ''
for trial in range(runs):
    state = init()
    for _ in range(chain):
        state = don_fn(state, x)
    got = {k: np.asarray(v) for k, v in state.items()}
    if mode == 'garbage' and trial == runs // 2:
        got['w'] = got['w'].copy()
        got['w'].flat[0] = np.nan
    for k in ref:
        if not np.isfinite(got[k]).all():
            ok, detail = False, f'non-finite output {k!r} on trial {trial}'
            break
        if got[k].tobytes() != ref[k].tobytes():
            ok, detail = False, (
                f'donated output {k!r} diverged from the undonated '
                f'reference on trial {trial}')
            break
    if not ok:
        break
print(json.dumps({'ok': ok, 'runs': runs, 'detail': detail}))
'''


def run_probe(runs: Optional[int] = None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
    """Run the subprocess gauntlet once; returns a verdict dict
    (``verdict`` is 'safe' or 'corrupting' — never raises). The child
    crashing (segfault included) or hanging is itself the corrupting
    classification: a probe that cannot complete cleanly is not a
    runtime to donate on."""
    runs = int(runs if runs is not None
               else _flags.flag('FLAGS_donation_probe_runs'))
    timeout = float(timeout if timeout is not None
                    else _flags.flag('FLAGS_donation_probe_timeout'))
    env = dict(os.environ)
    env['PADDLE_DONATION_PROBE_RUNS'] = str(runs)
    t0 = time.perf_counter()
    verdict: Dict[str, Any] = {
        'version': _VERDICT_VERSION, 'runs': runs,
        'mode': env.get('PADDLE_DONATION_PROBE_MODE', ''),
        'probed_at': time.time(),
    }
    try:
        proc = subprocess.run([sys.executable, '-c', _PROBE_SRC],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        verdict.update(verdict='corrupting',
                       reason=f'probe timed out after {timeout}s '
                              f'(single-client device? see the module '
                              f'docstring runbook)')
        verdict['seconds'] = round(time.perf_counter() - t0, 3)
        return verdict
    except Exception as exc:
        verdict.update(verdict='corrupting',
                       reason=f'probe could not launch: '
                              f'{type(exc).__name__}: {exc}')
        return verdict
    verdict['seconds'] = round(time.perf_counter() - t0, 3)
    if proc.returncode != 0:
        sig = -proc.returncode if proc.returncode < 0 else None
        verdict.update(
            verdict='corrupting',
            reason=(f'probe died with signal {sig}' if sig
                    else f'probe exited {proc.returncode}'),
            stderr_tail=proc.stderr[-500:])
        return verdict
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() \
        else ''
    try:
        result = json.loads(line)
    except Exception:  # paddle-lint: disable=swallowed-exception -- unparseable probe output IS the corrupting classification recorded in the returned verdict
        verdict.update(verdict='corrupting',
                       reason='probe produced no parseable verdict')
        return verdict
    if result.get('ok'):
        verdict.update(verdict='safe', reason=result.get('detail', ''))
    else:
        verdict.update(verdict='corrupting',
                       reason=result.get('detail', 'output mismatch'))
    return verdict


# ---------------------------------------------------------------------------
# verdict persistence (manifest-recorded, per backend fingerprint)
# ---------------------------------------------------------------------------

def _verdict_path(directory: str, token: str) -> str:
    return os.path.join(directory, f'donation.{token}.json')


def load_verdict(directory: Optional[str],
                 token: str) -> Optional[Dict[str, Any]]:
    """Read the recorded verdict for this fingerprint, or None. An
    unreadable/garbage manifest is treated as absent (re-probe), never
    an exception — the store's poisoned-cache contract."""
    if not directory:
        return None
    path = _verdict_path(directory, token)
    try:
        with open(path) as f:
            data = json.load(f)
    except Exception:  # paddle-lint: disable=swallowed-exception -- unreadable verdict manifest reads as absent: the caller re-probes, the store's poisoned-cache contract
        return None
    if data.get('version') != _VERDICT_VERSION \
            or data.get('verdict') not in ('safe', 'corrupting',
                                           'quarantined'):
        return None
    return data


def record_verdict(directory: Optional[str], token: str,
                   verdict: Dict[str, Any]):
    """Atomically commit the verdict manifest (tmp + rename, like every
    other store artifact). Failures are survivable: the posture still
    holds in the process cache; only re-init re-probes."""
    if not directory:
        return
    try:
        os.makedirs(directory, exist_ok=True)
        path = _verdict_path(directory, token)
        tmp = f'{path}.{os.getpid()}.tmp'
        with open(tmp, 'w') as f:
            json.dump(verdict, f, indent=1, default=str)
        os.replace(tmp, path)
    except Exception:
        _obs.count_suppressed('donation.record_verdict')


def _posture_gauge(value: float):
    if _obs.enabled():
        _obs.get_registry().gauge(
            'paddle_donation_posture',
            'store-served donation posture: 1 enabled, 0 disabled, '
            '-1 quarantined').set(value)


def resolve_posture(directory: Optional[str],
                    fingerprint: Dict[str, Any]) -> Dict[str, Any]:
    """The gauntlet's decision procedure, run at ProgramStore init /
    configure / fingerprint refresh. Returns
    ``{enabled, posture, verdict, reason, source, token}``:

    - ``FLAGS_donation='off'``: donation stays off, no probe (the PR-8
      posture, and what tier-1 pins for determinism).
    - ``'on'``: enabled without probing (operator override) — unless a
      QUARANTINE was recorded for this fingerprint, which always wins.
    - ``'auto'``: recorded verdict (store manifest, then process cache)
      decides; with a persistent directory and no verdict, the
      subprocess probe runs NOW and its verdict is recorded. Without a
      directory nothing is store-served, so no probe runs and donation
      stays off.
    """
    token = fingerprint_token(fingerprint)
    mode = str(_flags.flag('FLAGS_donation') or 'auto').lower()
    out: Dict[str, Any] = {'enabled': False, 'posture': 'off',
                           'verdict': None, 'reason': '', 'source': 'flag',
                           'token': token}
    recorded = load_verdict(directory, token) or _PROC_VERDICTS.get(token)
    if recorded is not None and recorded.get('verdict') == 'quarantined':
        # a quarantine outlives flag overrides: a sentinel caught real
        # corruption on THIS runtime; only wiping the verdict file (or a
        # fingerprint change) re-arms donation
        out.update(posture='quarantined', verdict='quarantined',
                   reason=recorded.get('reason', ''), source='recorded')
        _posture_gauge(-1.0)
        return out
    if mode == 'off':
        out['reason'] = 'FLAGS_donation=off'
        _posture_gauge(0.0)
        return out
    if mode == 'on':
        out.update(enabled=True, posture='on', verdict='forced',
                   reason='FLAGS_donation=on')
        _obs.emit('donation_enabled', token=token, forced=True,
                  sentinel=sentinel_budget())
        _posture_gauge(1.0)
        return out
    # auto: probe-verified only
    if recorded is None:
        if not directory:
            out['reason'] = 'no persistent store (nothing store-served)'
            _posture_gauge(0.0)
            return out
        with _probe_lock:
            recorded = load_verdict(directory, token) \
                or _PROC_VERDICTS.get(token)
            if recorded is None:
                with _obs.span('donation.probe'):
                    recorded = run_probe()
                recorded['fingerprint'] = dict(fingerprint)
                _PROC_VERDICTS[token] = recorded
                record_verdict(directory, token, recorded)
                if _obs.enabled():
                    _obs.get_registry().counter(
                        'paddle_donation_probes_total',
                        'donation gauntlet probes by verdict',
                        ('verdict',)).labels(
                            verdict=recorded['verdict']).inc()
                if recorded['verdict'] == 'safe':
                    _obs.emit('donation_probe_ok',
                              runs=recorded.get('runs', 0),
                              seconds=recorded.get('seconds', 0.0))
                else:
                    _obs.emit('donation_probe_failed',
                              reason=recorded.get('reason', ''),
                              seconds=recorded.get('seconds', 0.0))
    else:
        _PROC_VERDICTS.setdefault(token, recorded)
    out.update(verdict=recorded['verdict'],
               reason=recorded.get('reason', ''), source='recorded')
    if recorded['verdict'] == 'safe':
        out.update(enabled=True, posture='on')
        _obs.emit('donation_enabled', token=token,
                  sentinel=sentinel_budget())
        _posture_gauge(1.0)
    else:
        _posture_gauge(0.0)
    return out


def quarantine(directory: Optional[str], fingerprint: Dict[str, Any],
               reason: str) -> Dict[str, Any]:
    """Record that donation CORRUPTED on this runtime (a tripped
    sentinel): the verdict manifest flips to 'quarantined' — which every
    later resolve, flag overrides included, honors — and the event that
    triggers a flight bundle fires. Returns the recorded verdict."""
    token = fingerprint_token(fingerprint)
    verdict = {'version': _VERDICT_VERSION, 'verdict': 'quarantined',
               'reason': str(reason), 'quarantined_at': time.time(),
               'fingerprint': dict(fingerprint)}
    _PROC_VERDICTS[token] = verdict
    record_verdict(directory, token, verdict)
    _obs.emit('donation_quarantined', reason=str(reason), token=token)
    if _obs.enabled():
        _obs.get_registry().counter(
            'paddle_donation_quarantines_total',
            'donation quarantines (sentinel trips)').inc()
    _posture_gauge(-1.0)
    return verdict


# ---------------------------------------------------------------------------
# corruption sentinels
# ---------------------------------------------------------------------------

def sentinel_budget() -> int:
    """Post-enablement invocations of each donated store-served program
    guarded by an output sentinel."""
    try:
        return max(0, int(_flags.flag('FLAGS_donation_sentinel')))
    except Exception:  # paddle-lint: disable=swallowed-exception -- an unparseable flag degrades to the default budget; guarding MORE calls is the safe direction
        return 8


def snapshot_args(args):
    """Device-copy every jax array leaf so the donated call consumes the
    COPIES — the originals stay valid for the undonated re-run a tripped
    sentinel needs. Only used inside the K-call sentinel window, where
    the copy is exactly what the undonated posture paid every call."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda v: jnp.array(v) if isinstance(v, jax.Array) else v, args)


def outputs_ok(out) -> bool:
    """Cheap corruption sentinel over one call's outputs: every
    floating-point leaf must be finite (the device computes the
    reduction; only one scalar per leaf crosses to host). Heap
    corruption manifesting as garbage floats trips this; bitwise
    output divergence is what the PROBE chain catches up front."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    try:
        for leaf in jax.tree_util.tree_leaves(out):
            dt = getattr(leaf, 'dtype', None)
            if dt is None or not jnp.issubdtype(dt, jnp.floating):
                continue
            if not bool(np.asarray(jnp.isfinite(leaf).all())):
                return False
    except Exception:
        # a sentinel that cannot even read the outputs is a trip: the
        # call must fall back to the undonated recompile
        _obs.count_suppressed('donation.sentinel_read')
        return False
    return True


def main(argv=None):
    """``python -m paddle_tpu.programs.donation <store_dir>`` — run the
    gauntlet standalone and record the verdict manifest the next
    ProgramStore init will read (the single-client-device runbook)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ('-h', '--help'):
        print(__doc__.split('\n\n')[0])
        print('\nusage: python -m paddle_tpu.programs.donation '
              '<store_dir> [runs]')
        return 0
    directory = argv[0]
    runs = int(argv[1]) if len(argv) > 1 else None
    from .store import backend_fingerprint
    fp = backend_fingerprint()
    token = fingerprint_token(fp)
    verdict = run_probe(runs=runs)
    verdict['fingerprint'] = fp
    record_verdict(directory, token, verdict)
    print(json.dumps({'token': token, **verdict}, indent=1, default=str))
    return 0 if verdict['verdict'] == 'safe' else 1


if __name__ == '__main__':   # pragma: no cover - exercised via -m
    sys.exit(main())
