"""paddle.signal (upstream: python/paddle/signal.py) — stft/istft built
on frame extraction + the fft module (XLA-lowered, differentiable)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops._helpers import defop

__all__ = ['stft', 'istft', 'frame', 'overlap_add']


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames along `axis` (last-dim layout: paddle
    returns [..., frame_length, num_frames])."""
    def f(v):
        if axis not in (-1, v.ndim - 1):
            raise NotImplementedError('frame supports the last axis only')
        n = v.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[None, :] + jnp.arange(frame_length)[:, None]
        return v[..., idx]  # [..., frame_length, num]
    return defop(f, name='frame')(x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: sum overlapping [..., frame_length, num_frames]
    back to a signal."""
    def f(v):
        fl, num = v.shape[-2], v.shape[-1]
        out_len = fl + hop_length * (num - 1)
        starts = jnp.arange(num) * hop_length
        idx = (starts[None, :] + jnp.arange(fl)[:, None]).reshape(-1)
        flat = v.reshape(v.shape[:-2] + (-1,))
        out = jnp.zeros(v.shape[:-2] + (out_len,), v.dtype)
        return out.at[..., idx].add(flat)
    return defop(f, name='overlap_add')(x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode='reflect', normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform ([B, T] -> [B, n_fft//2+1, frames]
    complex, matching paddle.signal.stft semantics)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def f(v, *w):
        win = w[0] if w else jnp.ones(wl, v.dtype)
        if wl < n_fft:  # center-pad window to n_fft
            lp = (n_fft - wl) // 2
            win = jnp.pad(win, (lp, n_fft - wl - lp))
        sig = v
        if center:
            sig = jnp.pad(sig, [(0, 0)] * (sig.ndim - 1)
                          + [(n_fft // 2, n_fft // 2)], mode=pad_mode)
        n = sig.shape[-1]
        num = 1 + (n - n_fft) // hop
        starts = jnp.arange(num) * hop
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = sig[..., idx] * win  # [..., num, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]
    args = (x,) if window is None else (x, window)
    return defop(f, name='stft')(*args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization (matches
    paddle.signal.istft for COLA-satisfying windows)."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def f(v, *w):
        win = w[0] if w else jnp.ones(wl, jnp.float32)
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            win = jnp.pad(win, (lp, n_fft - wl - lp))
        spec = jnp.swapaxes(v, -1, -2)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
            else jnp.fft.ifft(spec, axis=-1).real
        frames = frames * win
        num = frames.shape[-2]
        out_len = n_fft + hop * (num - 1)
        starts = jnp.arange(num) * hop
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        sig = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
        sig = sig.at[..., idx].add(
            frames.reshape(frames.shape[:-2] + (-1,)))
        env = jnp.zeros(out_len, frames.dtype).at[idx].add(
            jnp.tile(win * win, num))
        sig = sig / jnp.maximum(env, 1e-10)
        if center:
            sig = sig[..., n_fft // 2:]
            if length is None:
                sig = sig[..., :sig.shape[-1] - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig
    args = (x,) if window is None else (x, window)
    return defop(f, name='istft')(*args)
