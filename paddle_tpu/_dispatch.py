"""Eager dispatch fast path: cached jitted primals + reusable VJPs.

The DyGraph eager layer routes every op through ``tensor.apply_op``. The
classic eager tax is that each call re-traces the pure jax function —
twice when grad is enabled (``jax.vjp`` traces the forward AND builds the
pullback) — in Python, on every invocation. This module amortizes that
cost the way upstream Paddle's final-state DyGraph + phi op-dispatch
cache do: key the call, trace once, replay a compiled executable.

Key: ``(op name, fn identity, input treedef, tensor positions,
tensor-leaf avals, hashable static leaves)``. "fn identity" is the
function object itself for stable module-level ops, or (code object,
closure values, defaults) for per-call lambdas whose captured values are
hashable — so e.g. ``lambda x: x.astype(dt)`` keys on ``dt``, not on the
throwaway function object. Calls that cannot be keyed (unhashable
statics such as fresh PRNG key arrays, numpy buffers, or slice-bearing
treedefs on py<3.12) or that fail to trace (data-dependent output
shapes, Tensor-returning bodies) fall back to the uncached slow path and
are counted.

Cached per key:
  - primal: ``jax.jit(canonical)`` for the no-grad path;
  - fwd: ``jax.jit(lambda *vals: jax.vjp(canonical, *vals))`` for the
    grad path. The pullback returned OUT of jit is a
    ``jax.tree_util.Partial`` carrying concrete residual arrays — a
    reusable primal+VJP pair: the forward runs as one XLA executable and
    the tape Node gets a residual-bound vjp closure with zero Python
    re-tracing.

Telemetry: hit / miss / retrace / fallback counters, exposed through
``paddle_tpu.debug.dispatch_stats()`` / ``dispatch_summary()`` and
folded into ``paddle_tpu.profiler.Profiler`` summaries. A *retrace* is a
miss whose (op, fn, treedef) signature had already been traced in the
same flavor — i.e. a shape/dtype/static change forced re-tracing of an
op the cache had compiled before; steady-state training should show
zero of them after warmup.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from . import flags as _flags

_tree = jax.tree_util

_flags.register_flag('FLAGS_eager_dispatch_cache', True)
# LRU capacity of the dispatch cache: long-running serving/training
# processes with churning shapes must not grow executable memory
# without bound. Read at use time so set_flags() applies live.
_flags.register_flag('FLAGS_eager_dispatch_cache_size', 512)

_MAX_BLACKLIST = 4096


def _max_entries() -> int:
    try:
        return max(int(_flags.flag('FLAGS_eager_dispatch_cache_size')), 1)
    except (TypeError, ValueError):
        return 512

_enabled = [bool(_flags.flag('FLAGS_eager_dispatch_cache'))]
_cache: "collections.OrderedDict[Any, _Entry]" = collections.OrderedDict()
_blacklist: set = set()
_seen_flavors: set = set()


class _Counters:
    __slots__ = ('hits', 'misses', 'retraces', 'fallbacks', 'errors',
                 'evictions', 'per_op')

    def __init__(self):
        self.reset()

    def reset(self):
        self.hits = 0
        self.misses = 0
        self.retraces = 0
        self.fallbacks = 0
        self.errors = 0
        self.evictions = 0
        # name -> [hits, misses, fallbacks]
        self.per_op: Dict[str, list] = collections.defaultdict(
            lambda: [0, 0, 0])


_counters = _Counters()


def enabled() -> bool:
    return _enabled[0]


def enable(on: bool = True):
    _enabled[0] = bool(on)
    _flags.set_flags({'FLAGS_eager_dispatch_cache': bool(on)})


def stats() -> dict:
    c = _counters
    calls = c.hits + c.misses + c.fallbacks
    return {
        'enabled': _enabled[0],
        'hits': c.hits, 'misses': c.misses, 'retraces': c.retraces,
        'fallbacks': c.fallbacks, 'errors': c.errors,
        'evictions': c.evictions, 'calls': calls,
        'hit_rate': (c.hits / calls) if calls else 0.0,
        'cache_size': len(_cache), 'blacklist_size': len(_blacklist),
        'per_op': {k: {'hits': v[0], 'misses': v[1], 'fallbacks': v[2]}
                   for k, v in c.per_op.items()},
    }


def reset_stats():
    _counters.reset()


def clear():
    """Drop every cached executable and trace record (stats survive;
    use reset_stats() for those)."""
    _cache.clear()
    _blacklist.clear()
    _seen_flavors.clear()


# ---------------------------------------------------------------------------
# keying
# ---------------------------------------------------------------------------

def _static_key(v):
    """Hashable identity for one baked-in static value, or None.
    The type rides along so 1 / 1.0 / True cannot collide into one key
    (they hash and compare equal but trace to different programs)."""
    try:
        hash(v)
    except TypeError:
        return None
    return (v.__class__, v)


def _aval_key(v):
    try:
        return ('aval', v.shape, v.dtype, bool(getattr(v, 'weak_type',
                                                       False)))
    except AttributeError:
        return ('aval', np.shape(v), np.result_type(v), True)


def _fn_key(fn):
    """Stable identity for the op body. Module-level fns key as
    (code,); per-call closures key on (code, captured values); anything
    with an unhashable capture (PRNG key arrays, numpy buffers) is
    uncacheable."""
    self_obj = getattr(fn, '__self__', None)
    func = getattr(fn, '__func__', fn)
    code = getattr(func, '__code__', None)
    if code is None:
        # builtin / partial / callable object: only safe keyed by identity
        return _static_key(fn)
    parts = [code]
    if self_obj is not None:
        sk = _static_key(self_obj)
        if sk is None:
            return None
        parts.append(sk)
    closure = getattr(func, '__closure__', None)
    if closure:
        for cell in closure:
            try:
                v = cell.cell_contents
            except ValueError:   # empty cell
                return None
            sk = _static_key(v)
            if sk is None:
                return None
            parts.append(sk)
    for d in (getattr(func, '__defaults__', None) or ()):
        sk = _static_key(d)
        if sk is None:
            return None
        parts.append(sk)
    kwd = getattr(func, '__kwdefaults__', None)
    if kwd:
        for k in sorted(kwd):
            sk = _static_key(kwd[k])
            if sk is None:
                return None
            parts.append((k, sk))
    return tuple(parts)


def _build_key(name, fn, treedef, leaves, t_idx, vals):
    """(key, sig) or (None, None) when the call cannot be keyed."""
    fk = _fn_key(fn)
    if fk is None:
        return None, None
    try:
        hash(treedef)   # aux data may hold slices (py<3.12) / arrays
    except TypeError:
        return None, None
    parts = []
    ti = 0
    n_t = len(t_idx)
    for i, leaf in enumerate(leaves):
        if ti < n_t and i == t_idx[ti]:
            parts.append(_aval_key(vals[ti]))
            ti += 1
        else:
            sk = _static_key(leaf)
            if sk is None:
                return None, None
            parts.append(sk)
    sig = (name, fk, treedef)
    return (sig, tuple(t_idx), tuple(parts)), sig


# ---------------------------------------------------------------------------
# cache entries
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ('canonical', 'primal_jit', 'fwd_jit')

    def __init__(self, canonical):
        self.canonical = canonical
        self.primal_jit = None
        self.fwd_jit = None

    def primal(self, *tvals):
        """Replayable primal for tape Nodes (autograd._build_pure):
        shared across every call that hit this entry, jitted lazily so
        eager replay hits the executable cache and traced replay
        (jacobian/higher-order grad) reuses one cached jaxpr."""
        j = self.primal_jit
        if j is None:
            j = self.primal_jit = jax.jit(self.canonical)
        return j(*tvals)


def _make_canonical(fn, treedef, template, t_idx):
    """The cache-shared pure function: rebuilds fn's (args, kwargs) from
    the recorded static leaves with the dynamic tensor values dropped
    into their recorded slots."""
    def canonical(*tvals):
        ls = list(template)
        for i, v in zip(t_idx, tvals):
            ls[i] = v
        a, k = _tree.tree_unflatten(treedef, ls)
        return fn(*a, **k)
    return canonical


def _note_fallback(name):
    _counters.fallbacks += 1
    _counters.per_op[name][2] += 1


def _note_program_compile(name, seconds):
    """Cold-path-only hook into the observability ProgramCatalog: one
    cache entry was traced+compiled (the building call's wall time).
    Invocation counts mirror at scrape time; the hit path pays nothing."""
    try:
        from .observability.cost import note_dispatch_compile
        note_dispatch_compile(name, seconds)
    except Exception:  # paddle-lint: disable=swallowed-exception -- observability optional on the hot path; nothing to count into if import failed
        pass   # observability is optional here


def _guarded_vjp(raw_vjp, entry, key, vals):
    """custom_vjp bodies whose bwd closes over trace-local values cannot
    survive the jitted-forward / out-of-trace-pullback split (the
    residual-passing idiom can; see nn.functional._fused_softmax_ce_xla).
    If such a pullback leaks a tracer, permanently route the key to the
    slow path and answer this backward from an eager re-vjp."""
    def vjp(cotangents):
        try:
            return raw_vjp(cotangents)
        except jax.errors.UnexpectedTracerError:
            _counters.errors += 1
            if len(_blacklist) >= _MAX_BLACKLIST:
                _blacklist.clear()
            try:
                _blacklist.add(key)
                _cache.pop(key, None)
            except Exception:  # paddle-lint: disable=swallowed-exception -- unhashable key cannot enter the blacklist; the very next line is the counted fallback
                pass
            return jax.vjp(entry.canonical, *vals)[1](cotangents)
    return vjp


def run(fn, name, treedef, leaves, t_idx, vals, record
        ) -> Optional[Tuple[Any, Any, Any]]:
    """Dispatch one op through the cache.

    Returns (out_pytree, vjp_fn_or_None, replay_primal_fn), or None when
    the call must take the uncached slow path. `vals` are the raw jax
    values (post-AMP-cast) for the Tensor leaves at `t_idx`.
    """
    # Inside a jit/vmap capture the values are tracers: the enclosing
    # transform compiles the whole program once, so a per-op cache buys
    # nothing there — and nested-pjit lowering of cached executables is
    # not portable across jax versions. Eager values only.
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        _note_fallback(name)
        return None
    # key building AND lookup are guarded: PyTreeDef hashes ignore aux
    # data, so dict/set probes can fall into aux __eq__ — and aux may
    # hold objects with array-valued equality (e.g. _IndexBox Tensors),
    # whose truthiness raises. Any such hazard routes to the slow path.
    try:
        key, sig = _build_key(name, fn, treedef, leaves, t_idx, vals)
        if key is not None and key in _blacklist:
            key = None
    except Exception:  # paddle-lint: disable=swallowed-exception -- unkeyable call: key=None routes to _note_fallback right below
        key = None
    if key is None:
        _note_fallback(name)
        return None

    try:
        entry = _cache.get(key)
        if entry is not None:
            _cache.move_to_end(key)   # true LRU: a hit is a touch
    except Exception:
        _note_fallback(name)
        return None
    fresh_entry = entry is None
    if fresh_entry:
        template = list(leaves)
        for i in t_idx:
            template[i] = None
        entry = _Entry(_make_canonical(fn, treedef, tuple(template),
                                       tuple(t_idx)))

    flavor = 'fwd' if record else 'primal'
    jitted = entry.fwd_jit if record else entry.primal_jit
    building = jitted is None
    if building:
        _counters.misses += 1
        _counters.per_op[name][1] += 1
        try:   # sig holds the treedef: probing can hit aux __eq__ hazards
            seen_key = (sig, flavor)
            if seen_key in _seen_flavors:
                _counters.retraces += 1
            else:
                _seen_flavors.add(seen_key)
        except Exception:  # paddle-lint: disable=swallowed-exception -- retrace telemetry bookkeeping only; dispatch result unaffected
            pass
        if record:
            def _fwd(*tvals, _c=entry.canonical):
                return jax.vjp(_c, *tvals)
            jitted = jax.jit(_fwd)
        else:
            jitted = jax.jit(entry.canonical)
    else:
        _counters.hits += 1
        _counters.per_op[name][0] += 1

    t_build = time.perf_counter() if building else 0.0
    try:
        if record:
            out, raw_vjp = jitted(*vals)
            vjp_fn = _guarded_vjp(raw_vjp, entry, key, tuple(vals))
        else:
            out, vjp_fn = jitted(*vals), None
    except Exception:
        if not building:
            raise   # a previously-compiled executable failed: genuine error
        # first trace/compile of this key failed (data-dependent shapes,
        # Tensor-returning body, ...): permanently route this key to the
        # slow path — which re-raises any genuine user error itself
        _counters.misses -= 1
        _counters.per_op[name][1] -= 1
        _counters.errors += 1
        _note_fallback(name)
        if len(_blacklist) >= _MAX_BLACKLIST:
            _blacklist.clear()
        try:
            _blacklist.add(key)
        except Exception:  # paddle-lint: disable=swallowed-exception -- unhashable key cannot enter the blacklist; caller already counted the fallback
            pass
        return None

    if building:
        _note_program_compile(name, time.perf_counter() - t_build)
        if record:
            entry.fwd_jit = jitted
        else:
            entry.primal_jit = jitted
        if fresh_entry:
            try:
                _cache[key] = entry
                cap = _max_entries()
                while len(_cache) > cap:
                    _cache.popitem(last=False)
                    _counters.evictions += 1
            except Exception:  # paddle-lint: disable=swallowed-exception -- unstorable key: the computed result is still valid, next call re-traces
                pass   # unstorable key: the result is still valid
    return out, vjp_fn, entry.primal
