"""DyGraph autograd: a per-op tape over pure jax functions.

TPU-native replacement for the reference's eager autograd engine
(upstream: paddle/fluid/eager/ + C++ grad-node graph). Instead of hand-written
grad kernels, every op records a `jax.vjp` at forward time; backward() walks
the tape in reverse, feeding cotangents through the stored vjp closures.
The jitted training path (paddle_tpu.jit) bypasses the tape entirely and
differentiates the whole step functionally with jax.grad.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True
        self.functional = False  # inside functional capture: never record


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled and not _state.functional


@contextlib.contextmanager
def no_grad():
    old = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = old


@contextlib.contextmanager
def enable_grad():
    old = _state.enabled
    _state.enabled = True
    try:
        yield
    finally:
        _state.enabled = old


@contextlib.contextmanager
def functional_scope():
    """Inside jit capture: ops must stay pure, tape off."""
    old = _state.functional
    _state.functional = True
    try:
        yield
    finally:
        _state.functional = old


set_grad_enabled = enable_grad  # reference-compat alias


def _float0_zero(leaf):
    return np.zeros(np.shape(leaf), dtype=jax.dtypes.float0)


_node_counter = [0]


class Node:
    """One recorded op: inputs (Tensor refs), vjp closure, output metadata."""

    __slots__ = ('inputs', 'vjp_fn', 'out_avals', 'out_treedef', 'name',
                 '_order')

    def __init__(self, inputs, vjp_fn, out_avals, out_treedef, name=''):
        self.inputs = inputs          # list[Tensor] participating inputs
        self.vjp_fn = vjp_fn          # cotangents(pytree) -> tuple of input cotangents
        self.out_avals = out_avals    # list of (shape, dtype) per output leaf
        self.out_treedef = out_treedef
        self.name = name
        _node_counter[0] += 1
        self._order = _node_counter[0]

    def release(self):
        self.vjp_fn = None
        self.inputs = ()


def backward(outputs, grad_tensors=None, retain_graph=False):
    """Reverse-accumulate gradients from `outputs` into leaf .grad slots.

    Mirrors Tensor.backward()/paddle.autograd.backward semantics: scalar
    outputs seed with ones; non-scalars require explicit grad_tensors.
    """
    from .tensor import Tensor  # cycle-free at call time

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if grad_tensors is None:
        grad_tensors = [None] * len(outputs)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Cotangents for graph-internal tensors are keyed by
    # (id(producing_node), output_leaf_index) — nodes are held strongly for
    # the whole walk, so no id-reuse hazard. Leaves accumulate straight into
    # .grad via _accumulate_grad.
    cot: dict = {}

    def add_cot(tensor, value):
        key = (id(tensor._node), tensor._leaf_index)
        if key in cot:
            cot[key] = cot[key] + value
        else:
            cot[key] = value

    roots = []
    for out, g in zip(outputs, grad_tensors):
        if out.stop_gradient:
            continue
        if g is None:
            if out.size != 1:
                raise RuntimeError(
                    'grad can be implicitly created only for scalar outputs; '
                    'pass grad_tensors for non-scalar outputs')
            g_val = jnp.ones(out.shape, out.dtype)
        else:
            g_val = g.value if isinstance(g, Tensor) else jnp.asarray(g)
        if out._node is None:
            out._accumulate_grad(g_val)
        else:
            add_cot(out, g_val)
            roots.append(out)

    # Topological walk: collect reachable nodes by DFS over producer links,
    # then process in reverse creation order.
    seen_nodes = []
    seen_ids = set()
    stack = [t._node for t in roots if t._node is not None]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen_ids:
            continue
        seen_ids.add(id(node))
        seen_nodes.append(node)
        for t in node.inputs:
            if t._node is not None and id(t._node) not in seen_ids:
                stack.append(t._node)
    seen_nodes.sort(key=lambda n: n._order)

    for node in reversed(seen_nodes):
        # Assemble output cotangents (zeros / float0 where untouched).
        leaves = []
        any_set = False
        for i, (shape, dt) in enumerate(node.out_avals):
            g = cot.pop((id(node), i), None)
            if g is not None:
                any_set = True
                leaves.append(g)
            elif jnp.issubdtype(dt, jnp.inexact):
                leaves.append(jnp.zeros(shape, dt))
            else:
                leaves.append(np.zeros(shape, dtype=jax.dtypes.float0))
        if not any_set:
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                'trying to backward through the graph a second time '
                '(set retain_graph=True on the first backward)')
        out_cot = jax.tree_util.tree_unflatten(node.out_treedef, leaves)
        in_cots = node.vjp_fn(out_cot)
        for t, g in zip(node.inputs, in_cots):
            if t.stop_gradient:
                continue
            if g is not None and np.dtype(getattr(g, 'dtype', np.float32)) != jax.dtypes.float0:
                if t._node is None:
                    t._accumulate_grad(g)
                else:
                    add_cot(t, g)
        if not retain_graph:
            node.release()


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=True):
    """paddle.grad: return grads of `outputs` w.r.t. `inputs` (no .grad mutation)."""
    from .tensor import Tensor

    single = isinstance(inputs, Tensor)
    inputs_l = [inputs] if single else list(inputs)
    saved = [(t.grad, t.stop_gradient) for t in inputs_l]
    for t in inputs_l:
        t.grad = None
        t.stop_gradient = False
    try:
        backward(outputs, grad_outputs, retain_graph=retain_graph or create_graph)
        grads = []
        for t in inputs_l:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError('an input was unused in the graph')
                grads.append(None)
            else:
                grads.append(t.grad)
    finally:
        for t, (g, sg) in zip(inputs_l, saved):
            t.grad, t.stop_gradient = g, sg
    return grads[0] if single else grads
