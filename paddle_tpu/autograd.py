"""DyGraph autograd: a per-op tape over pure jax functions.

TPU-native replacement for the reference's eager autograd engine
(upstream: paddle/fluid/eager/ + C++ grad-node graph). Instead of hand-written
grad kernels, every op records a `jax.vjp` at forward time; backward() walks
the tape in reverse, feeding cotangents through the stored vjp closures.

Design notes:
- Node inputs are `InputRef` snapshots (target tensor + its node/leaf-index/
  stop_gradient *at record time*), so "in-place" rebinds of the live Tensor
  cannot sever or corrupt the recorded graph.
- `grad(..., create_graph=True)` supports true higher-order differentiation:
  the recorded primal closures are replayed into one pure function of the
  requested inputs, and its vjp is evaluated *through the tape* (apply_op),
  so the returned grads are themselves differentiable — recursively.
- The jitted training path (paddle_tpu.jit) bypasses the tape entirely and
  differentiates whole steps functionally with jax.grad.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True
        self.functional = False  # inside functional capture: never record


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled and not _state.functional


@contextlib.contextmanager
def no_grad():
    old = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = old


@contextlib.contextmanager
def enable_grad():
    old = _state.enabled
    _state.enabled = True
    try:
        yield
    finally:
        _state.enabled = old


@contextlib.contextmanager
def functional_scope():
    """Inside jit capture: ops must stay pure, tape off."""
    old = _state.functional
    _state.functional = True
    try:
        yield
    finally:
        _state.functional = old


set_grad_enabled = enable_grad  # reference-compat alias


def _is_float0(g) -> bool:
    return np.dtype(getattr(g, 'dtype', np.float32)) == jax.dtypes.float0


_node_counter = [0]


class InputRef:
    """Snapshot of one Tensor input at record time.

    Backward keys cotangents off the *recorded* producing node, and leaf
    accumulation routes to the original tensor object — so later in-place
    rebinds of the live Tensor leave the recorded graph intact
    (fix for the round-1 tape-severing bug).
    """

    __slots__ = ('target', 'node', 'leaf_index', 'stop_gradient', 'data')

    def __init__(self, t):
        self.target = t
        self.node = t._node
        self.leaf_index = t._leaf_index
        self.stop_gradient = t.stop_gradient
        self.data = t._data


class Node:
    """One recorded op: input refs, vjp closure, replayable primal, metadata.

    On the cached dispatch path (paddle_tpu._dispatch) `vjp_fn` is the
    residual-bound pullback returned out of the entry's jitted forward,
    and `primal_fn` is the entry's shared jitted primal — so both
    backward and tape replay (_build_pure) reuse compiled programs
    instead of re-tracing the op body."""

    __slots__ = ('inputs', 'vjp_fn', 'primal_fn', 'out_avals', 'out_treedef',
                 'name', '_order')

    def __init__(self, inputs, vjp_fn, primal_fn, out_avals, out_treedef,
                 name=''):
        self.inputs = inputs          # list[InputRef]
        self.vjp_fn = vjp_fn          # cotangents(pytree) -> tuple of input cotangents
        self.primal_fn = primal_fn    # pure fn(*input_vals) -> output pytree
        self.out_avals = out_avals    # list of (shape, dtype) per output leaf
        self.out_treedef = out_treedef
        self.name = name
        _node_counter[0] += 1
        self._order = _node_counter[0]

    def release(self):
        self.vjp_fn = None
        self.primal_fn = None
        self.inputs = ()


def _collect_nodes(root_nodes):
    """All recorded ancestors of `root_nodes`, sorted by creation order."""
    seen_nodes, seen_ids = [], set()
    stack = [n for n in root_nodes if n is not None]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen_ids:
            continue
        seen_ids.add(id(node))
        seen_nodes.append(node)
        for ref in node.inputs:
            if ref.node is not None and id(ref.node) not in seen_ids:
                stack.append(ref.node)
    seen_nodes.sort(key=lambda n: n._order)
    return seen_nodes


def backward(outputs, grad_tensors=None, retain_graph=False, capture=None,
             frozen_ids=()):
    """Reverse-accumulate gradients from `outputs`.

    With capture=None (public Tensor.backward path): grads accumulate into
    leaf `.grad` slots. With capture={id(tensor): None, ...} (paddle.grad
    path): no `.grad` mutation; cotangent sums for the requested tensors are
    collected into the dict instead.
    """
    from .tensor import Tensor  # cycle-free at call time

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if grad_tensors is None:
        grad_tensors = [None] * len(outputs)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    def cap_add(tid, value):
        prev = capture.get(tid)
        capture[tid] = value if prev is None else prev + value

    # Cotangents for graph-internal tensors are keyed by
    # (id(producing_node), output_leaf_index) — nodes are held strongly for
    # the whole walk, so no id-reuse hazard.
    cot: dict = {}
    # Leaf partials are summed here and flushed once at the end of the walk
    # so grad hooks see the full gradient, not each partial.
    leaf_sums: dict = {}

    def leaf_add(t, g_val):
        if id(t) in leaf_sums:
            leaf_sums[id(t)][1] = leaf_sums[id(t)][1] + g_val
        else:
            leaf_sums[id(t)] = [t, g_val]

    root_nodes = []
    for out, g in zip(outputs, grad_tensors):
        if out.stop_gradient:
            continue
        if g is None:
            if out.size != 1:
                raise RuntimeError(
                    'grad can be implicitly created only for scalar outputs; '
                    'pass grad_tensors for non-scalar outputs')
            g_val = jnp.ones(out.shape, out.dtype)
        else:
            g_val = g.value if isinstance(g, Tensor) else jnp.asarray(g)
        if capture is not None and id(out) in capture:
            cap_add(id(out), g_val)
        if out._node is None:
            if capture is None:
                leaf_add(out, g_val)
        else:
            key = (id(out._node), out._leaf_index)
            cot[key] = cot[key] + g_val if key in cot else g_val
            root_nodes.append(out._node)

    for node in reversed(_collect_nodes(root_nodes)):
        # Assemble output cotangents (zeros / float0 where untouched).
        leaves = []
        any_set = False
        for i, (shape, dt) in enumerate(node.out_avals):
            g = cot.pop((id(node), i), None)
            if g is not None:
                any_set = True
                leaves.append(g)
            elif jnp.issubdtype(dt, jnp.inexact):
                leaves.append(jnp.zeros(shape, dt))
            else:
                leaves.append(np.zeros(shape, dtype=jax.dtypes.float0))
        if not any_set:
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                'trying to backward through the graph a second time '
                '(set retain_graph=True on the first backward)')
        out_cot = jax.tree_util.tree_unflatten(node.out_treedef, leaves)
        in_cots = node.vjp_fn(out_cot)
        for ref, g in zip(node.inputs, in_cots):
            if ref.stop_gradient or g is None or _is_float0(g):
                continue
            if id(ref.target) in frozen_ids:  # no_grad_vars: cut here
                continue
            if capture is not None and id(ref.target) in capture:
                cap_add(id(ref.target), g)
            if ref.node is not None:
                key = (id(ref.node), ref.leaf_index)
                cot[key] = cot[key] + g if key in cot else g
            elif capture is None:
                leaf_add(ref.target, g)
        if not retain_graph:
            node.release()

    for t, g_val in leaf_sums.values():
        t._accumulate_grad(g_val)


def _build_pure(outputs, inputs, frozen_ids=()):
    """Replay the recorded subgraph into a pure fn(*input_vals) -> out_vals.

    Replays every recorded ancestor of `outputs`; wherever an InputRef's
    target is one of `inputs`, the caller-supplied value is substituted —
    cutting the graph there so the result is a function of exactly those
    inputs (everything else enters as a recorded-constant snapshot).
    `frozen_ids` (no_grad_vars) are forced to their recorded snapshots.
    """
    input_pos = {id(t): i for i, t in enumerate(inputs)}
    nodes = _collect_nodes(
        [t._node for t in outputs if t._node is not None and id(t) not in input_pos])
    for n in nodes:
        if n.primal_fn is None:
            raise RuntimeError(
                'create_graph=True requires the recorded graph to be alive; '
                'it was already freed by a prior backward '
                '(use retain_graph=True there)')

    def f(*xvals):
        env = {}

        def lookup(tid, node, leaf_index, const):
            if tid in input_pos:
                return xvals[input_pos[tid]]
            if tid in frozen_ids:
                return const
            if node is not None and (id(node), leaf_index) in env:
                return env[(id(node), leaf_index)]
            return const

        for node in nodes:
            invals = [lookup(id(r.target), r.node, r.leaf_index, r.data)
                      for r in node.inputs]
            out = node.primal_fn(*invals)
            out_leaves, _ = jax.tree_util.tree_flatten(out)
            for i, leaf in enumerate(out_leaves):
                env[(id(node), i)] = leaf
        return tuple(
            lookup(id(t), t._node, t._leaf_index, t._data) for t in outputs)

    reachable = set(input_pos) & (
        {id(r.target) for n in nodes for r in n.inputs} | {id(t) for t in outputs})
    return f, reachable


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad: grads of `outputs` w.r.t. `inputs` (no .grad mutation).

    create_graph=True returns grads recorded on the tape (differentiable
    again — arbitrary order), via pure-replay + jax.vjp through apply_op.
    """
    from .tensor import Tensor, apply_op

    single_out = isinstance(outputs, Tensor)
    outputs_l = [outputs] if single_out else list(outputs)
    single = isinstance(inputs, Tensor)
    inputs_l = [inputs] if single else list(inputs)
    frozen_ids = frozenset(
        id(t) for t in (no_grad_vars or ()))

    def seed_for(out, g):
        if g is not None:
            return g
        if out.size != 1:
            raise RuntimeError(
                'grad can be implicitly created only for scalar outputs; '
                'pass grad_outputs for non-scalar outputs')
        return Tensor(jnp.ones(out.shape, out.dtype))

    if grad_outputs is None:
        grad_outputs = [None] * len(outputs_l)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    if not create_graph:
        capture = {id(t): None for t in inputs_l}
        backward(outputs_l, grad_outputs, retain_graph=retain_graph,
                 capture=capture, frozen_ids=frozen_ids)
        grads = []
        for t in inputs_l:
            v = capture[id(t)]
            if v is None:
                if not allow_unused:
                    raise RuntimeError(
                        'one of the inputs was not used in the graph; '
                        'set allow_unused=True to return None for it')
                grads.append(None)
            else:
                grads.append(Tensor(v))
        return grads[0] if single else grads

    # -- higher-order path --------------------------------------------------
    # Dedupe inputs: jax.vjp splits the cotangent across duplicate arg slots,
    # but paddle semantics give each duplicate the full gradient.
    uniq, uniq_pos = [], {}
    for t in inputs_l:
        if id(t) not in uniq_pos:
            uniq_pos[id(t)] = len(uniq)
            uniq.append(t)

    f, reachable = _build_pure(outputs_l, uniq, frozen_ids=frozen_ids)
    unused_ids = {id(t) for t in uniq if id(t) not in reachable}
    if unused_ids and not allow_unused:
        raise RuntimeError(
            'one of the inputs was not used in the graph; '
            'set allow_unused=True to return None for it')

    cots = [seed_for(o, g) for o, g in zip(outputs_l, grad_outputs)]
    n_in = len(uniq)

    def hg(*vals):
        xs, cs = vals[:n_in], vals[n_in:]
        _, vjp_f = jax.vjp(f, *xs)
        return vjp_f(tuple(cs))

    # _cacheable=False: hg closes over the per-call replay fn `f`, so a
    # dispatch-cache key could never repeat — it would only churn entries.
    # The replayed Nodes' primal_fns ARE the cached per-op primals, so the
    # trace inside jax.vjp still reuses their jaxprs.
    res = apply_op(hg, *uniq, *cots, _name='grad', _cacheable=False)
    res = list(res) if isinstance(res, (tuple, list)) else [res]
    grads = [None if id(t) in unused_ids else res[uniq_pos[id(t)]]
             for t in inputs_l]
    return grads[0] if single else grads


# ---------------------------------------------------------------------------
# PyLayer: user-defined forward/backward (upstream:
# python/paddle/autograd/py_layer.py)
# ---------------------------------------------------------------------------

class PyLayerContext:
    """Passed as `ctx` to PyLayer.forward/backward."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return list(self._saved)


class PyLayer:
    """Custom op with a hand-written gradient:

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x
            @staticmethod
            def backward(ctx, grad):
                x, = ctx.saved_tensor()
                return 3 * x * x * grad

        y = Cube.apply(x)

    `forward` must be deterministic in its inputs: under jit / higher-order
    grad the framework replays it (like jax.checkpoint) to rebuild `ctx`,
    so a forward that draws fresh RNG or reads mutable globals would hand
    `backward` a different ctx than the original call produced.

    TPU-native mechanics: `forward` runs eagerly with the tape OFF (its
    internal ops are not differentiated — `backward` IS the gradient),
    then one custom Node is recorded whose vjp calls `backward` and
    whose replayable primal re-runs `forward` (so paddle.grad
    create_graph still works through PyLayers)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .tensor import Tensor

        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
        tensors = [leaves[i] for i in t_idx]

        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        out_is_seq = isinstance(out, (tuple, list))
        outs = list(out) if out_is_seq else [out]
        for o in outs:
            if not isinstance(o, Tensor):
                raise TypeError('PyLayer.forward must return Tensor(s)')

        record = is_grad_enabled() and any(
            not t.stop_gradient for t in tensors)
        if record:
            def vjp_fn(cotangents):
                cots = cotangents if isinstance(cotangents, (tuple, list)) \
                    else (cotangents,)
                with no_grad():
                    gin = cls.backward(
                        ctx, *[Tensor(jnp.asarray(c)) for c in cots])
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                if len(gin) != len(tensors):
                    raise RuntimeError(
                        f'{cls.__name__}.backward returned {len(gin)} '
                        f'grads for {len(tensors)} Tensor inputs')
                return tuple(
                    None if g is None
                    else (g._data if isinstance(g, Tensor) else jnp.asarray(g))
                    for g in gin)

            def _run_fwd(vals):
                ls = list(leaves)
                for i, v in zip(t_idx, vals):
                    ls[i] = Tensor(v)
                a, k = jax.tree_util.tree_unflatten(treedef, ls)
                c = PyLayerContext()
                with no_grad():
                    o = cls.forward(c, *a, **k)
                os_ = list(o) if isinstance(o, (tuple, list)) else [o]
                vals_out = [t._data for t in os_]
                out_v = tuple(vals_out) if len(vals_out) > 1 \
                    else vals_out[0]
                return out_v, c

            # The replayable primal must carry the USER's backward, not
            # jax's derivative of the re-run forward (a straight-through
            # PyLayer would otherwise silently lose its custom gradient
            # under paddle.grad(create_graph=True)). custom_vjp residuals
            # are the ctx's saved tensor values.
            @jax.custom_vjp
            def primal(*vals):
                return _run_fwd(vals)[0]

            def primal_fwd(*vals):
                out_v, _ = _run_fwd(vals)
                # residuals are the INPUT vals: backward re-runs forward to
                # rebuild the full ctx (saved tensors AND any python attrs
                # the user set on it — a ctx built from saved values alone
                # would lose those)
                return out_v, vals

            def primal_bwd(saved_vals, cot):
                # Re-running forward here requires it to be deterministic
                # w.r.t. its inputs: a forward that draws fresh RNG keys or
                # reads mutable external state rebuilds a DIFFERENT ctx than
                # the original backward saw. (Same contract as
                # jax.checkpoint / upstream recompute.)
                _, c = _run_fwd(saved_vals)
                cots = cot if isinstance(cot, (tuple, list)) else (cot,)
                with no_grad():
                    gin = cls.backward(
                        c, *[Tensor(jnp.asarray(v)) for v in cots])
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                if len(gin) != len(saved_vals):
                    raise RuntimeError(
                        f'{cls.__name__}.backward returned {len(gin)} '
                        f'grads for {len(saved_vals)} Tensor inputs')
                # None-grad zeros come from saved_vals (the possibly
                # vmapped/batched operands), not the captured eager leaves,
                # so cotangent shapes track the traced call.
                return tuple(
                    jnp.zeros_like(sv) if g is None else
                    (g._data if isinstance(g, Tensor) else jnp.asarray(g))
                    for g, sv in zip(gin, saved_vals))

            primal.defvjp(primal_fwd, primal_bwd)

            out_vals = [o._data for o in outs]
            _, out_td = jax.tree_util.tree_flatten(
                tuple(out_vals) if len(out_vals) > 1 else out_vals[0])
            node = Node(
                [InputRef(t) for t in tensors], vjp_fn, primal,
                [(tuple(v.shape), jnp.dtype(v.dtype)) for v in out_vals],
                out_td, name=cls.__name__)
            outs = [Tensor(v, stop_gradient=False, _node=node,
                           _leaf_index=i)
                    for i, v in enumerate(out_vals)]
        if out_is_seq:
            return type(out)(outs)
        return outs[0]


# ---------------------------------------------------------------------------
# jacobian / hessian (upstream: python/paddle/autograd/autodiff.py)
# ---------------------------------------------------------------------------

def _jac_single(y, x, batch_axis):
    """Dense Jacobian of one output Tensor w.r.t. one input Tensor."""
    from .tensor import Tensor

    f, reachable = _build_pure([y], [x])
    if id(x) not in reachable:
        raise RuntimeError('xs is not reachable from ys on the tape')
    jac = jax.jacrev(lambda v: f(v)[0])(x._data)  # y.shape + x.shape
    if batch_axis is None:
        return Tensor(jac.reshape(int(np_prod(y.shape)),
                                  int(np_prod(x.shape))))
    if batch_axis != 0:
        raise NotImplementedError('batch_axis must be None or 0')
    by, bx = y.shape[0], x.shape[0]
    my = int(np_prod(y.shape)) // by
    nx = int(np_prod(x.shape)) // bx
    # [By, My, Bx, Nx] -> per-sample diagonal [B, My, Nx]
    j4 = jac.reshape(by, my, bx, nx)
    diag = jnp.diagonal(j4, axis1=0, axis2=2)  # [My, Nx, B]
    return Tensor(jnp.moveaxis(diag, -1, 0))


def np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def jacobian(ys, xs, batch_axis=None):
    """paddle.autograd.jacobian — dense Jacobian of `ys` w.r.t. `xs`,
    evaluated by functionalizing the recorded tape and applying
    `jax.jacrev` (upstream computes this with repeated backward passes;
    one traced jacrev is the TPU-native equivalent).

    batch_axis=None -> [ys.numel, xs.numel]; batch_axis=0 -> per-sample
    diagonal [B, ys.numel/B, xs.numel/B]. Lists map to (tuples of)
    results like upstream.
    """
    from .tensor import Tensor

    ys_l = [ys] if isinstance(ys, Tensor) else list(ys)
    xs_l = [xs] if isinstance(xs, Tensor) else list(xs)
    rows = [tuple(_jac_single(y, x, batch_axis) for x in xs_l) for y in ys_l]
    rows = [r[0] if isinstance(xs, Tensor) else r for r in rows]
    return rows[0] if isinstance(ys, Tensor) else tuple(rows)


def hessian(ys, xs, batch_axis=None):
    """paddle.autograd.hessian — Hessian of a scalar `ys` w.r.t. `xs`
    via `jax.hessian` over the functionalized tape."""
    from .tensor import Tensor

    if not isinstance(ys, Tensor) or ys.size != 1:
        raise ValueError('hessian requires a scalar ys Tensor')
    if batch_axis is not None:
        raise NotImplementedError('hessian supports batch_axis=None only')
    xs_l = [xs] if isinstance(xs, Tensor) else list(xs)
    f, reachable = _build_pure([ys], xs_l)
    for x in xs_l:
        if id(x) not in reachable:
            raise RuntimeError('xs is not reachable from ys on the tape')
    scalar = lambda *vals: f(*vals)[0].reshape(())
    hess = jax.hessian(scalar, argnums=tuple(range(len(xs_l))))(
        *[x._data for x in xs_l])
    out = tuple(
        tuple(Tensor(hess[i][j].reshape(np_prod(xi.shape),
                                        np_prod(xj.shape)))
              for j, xj in enumerate(xs_l))
        for i, xi in enumerate(xs_l))
    if isinstance(xs, Tensor):
        return out[0][0]
    return out
