"""Global framework state: places/devices, default dtype, RNG.

TPU-native analogue of the reference's place/device machinery
(upstream: paddle/phi/backends/, python/paddle/device/). Devices are jax
devices; the "place" API is a thin veneer so reference-style code runs
unchanged. RNG is stateless threefry underneath (reproducible, trace-safe)
with a stateful facade for eager mode.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import jax.numpy as jnp

from . import dtype as _dtype_mod

# --------------------------------------------------------------------------
# Places
# --------------------------------------------------------------------------


class Place:
    device_type = 'unknown'

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f'Place({self.device_type}:{self.device_id})'

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if _kind(d) == self.device_type]
        if not devs:  # fall back to whatever the default backend is
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    device_type = 'cpu'

    def jax_device(self):
        return jax.devices('cpu')[self.device_id % len(jax.devices('cpu'))]


class TPUPlace(Place):
    device_type = 'tpu'


# Alias for reference-style code; there is no CUDA here, it maps to the
# accelerator place (upstream: paddle/phi/common/place.h CUDAPlace).
XLAPlace = TPUPlace
CUDAPlace = TPUPlace


def _kind(dev) -> str:
    p = getattr(dev, 'platform', 'cpu')
    return 'tpu' if p in ('tpu', 'axon') else p


class _State(threading.local):
    def __init__(self):
        self.default_dtype = _dtype_mod.float32
        self.place = None  # lazily resolved


_state = _State()


def _default_place() -> Place:
    if _state.place is None:
        kinds = {_kind(d) for d in jax.devices()}
        _state.place = TPUPlace(0) if 'tpu' in kinds else CPUPlace(0)
    return _state.place


def set_device(device: str):
    """set_device('tpu') / 'tpu:0' / 'cpu' (upstream: paddle.device.set_device)."""
    name, _, idx = device.partition(':')
    idx = int(idx) if idx else 0
    name = {'gpu': 'tpu', 'xla': 'tpu', 'xpu': 'tpu'}.get(name, name)
    if name == 'tpu':
        _state.place = TPUPlace(idx)
    elif name == 'cpu':
        _state.place = CPUPlace(idx)
    else:
        raise ValueError(f'unknown device {device!r}')
    return _state.place


def get_device() -> str:
    p = _default_place()
    return f'{p.device_type}:{p.device_id}'


def get_place() -> Place:
    return _default_place()


@contextlib.contextmanager
def device_guard(device: str):
    old = _default_place()
    set_device(device)
    try:
        yield
    finally:
        _state.place = old


def synchronize():
    """Block until all dispatched device work is complete."""
    (jnp.zeros(()) + 0).block_until_ready()


def is_compiled_with_cuda() -> bool:  # reference-compat shim
    return False


def is_compiled_with_xla() -> bool:
    return True


# --------------------------------------------------------------------------
# Device memory introspection (upstream: python/paddle/device/cuda/
# max_memory_allocated / memory_allocated / memory_reserved family —
# here backed by PjRt per-device memory_stats()).
# --------------------------------------------------------------------------


def _memory_stats(device_id: Optional[int] = None) -> dict:
    devs = jax.devices()
    dev = devs[device_id or 0] if device_id is not None else devs[0]
    stats = dev.memory_stats()
    return dict(stats) if stats else {}


def memory_allocated(device_id: Optional[int] = None) -> int:
    """Bytes currently allocated on the device (0 when the backend does
    not expose stats, e.g. the CPU test mesh)."""
    return int(_memory_stats(device_id).get('bytes_in_use', 0))


def max_memory_allocated(device_id: Optional[int] = None) -> int:
    """High-water mark of device bytes allocated since process start."""
    s = _memory_stats(device_id)
    return int(s.get('peak_bytes_in_use', s.get('bytes_in_use', 0)))


def memory_reserved(device_id: Optional[int] = None) -> int:
    """Bytes reserved by the allocator pool (>= allocated)."""
    s = _memory_stats(device_id)
    return int(s.get('bytes_reserved',
                     s.get('bytes_reservable_limit', 0)) or
               s.get('bytes_in_use', 0))


def max_memory_reserved(device_id: Optional[int] = None) -> int:
    s = _memory_stats(device_id)
    return int(s.get('peak_bytes_reserved', 0) or max_memory_allocated(
        device_id))


def device_memory_limit(device_id: Optional[int] = None) -> int:
    """Total usable device memory (HBM) in bytes, when known."""
    return int(_memory_stats(device_id).get('bytes_limit', 0))


# --------------------------------------------------------------------------
# Default dtype
# --------------------------------------------------------------------------


def set_default_dtype(d):
    _state.default_dtype = _dtype_mod.convert_dtype(d)


def get_default_dtype():
    return _state.default_dtype


# --------------------------------------------------------------------------
# RNG: stateless threefry core, stateful eager facade, trace-safe capture
# --------------------------------------------------------------------------


class Generator:
    """Counter-based PRNG stream.

    Eager mode: every draw folds a fresh counter into the root key.
    Trace (jit) mode: `trace_scope(key)` installs a per-step key; draws fold
    a trace-local counter so each op site gets a distinct, deterministic
    subkey that varies with the per-step key input (no baked-in constants).
    """

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._counter = 0
        self._trace_key = None
        self._trace_counter = 0

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._counter = 0
        return self

    @property
    def root_key(self):
        # legacy raw uint32[2] key, NOT jax.random.key(): the bits and
        # every downstream jax.random.* op are identical, but the typed
        # key<fry> aval cannot ride through jax.export serialization —
        # and these keys are inputs to every persisted train/to_static
        # program in the program store
        return jax.random.PRNGKey(self._seed)

    def next_key(self):
        if self._trace_key is not None:
            k = jax.random.fold_in(self._trace_key, self._trace_counter)
            self._trace_counter += 1
            return k
        k = jax.random.fold_in(self.root_key, self._counter)
        self._counter += 1
        return k

    @contextlib.contextmanager
    def trace_scope(self, key):
        old_key, old_ctr = self._trace_key, self._trace_counter
        self._trace_key, self._trace_counter = key, 0
        try:
            yield
        finally:
            self._trace_key, self._trace_counter = old_key, old_ctr

    def state(self):
        return {'seed': self._seed, 'counter': self._counter}

    def set_state(self, st):
        self._seed = int(st['seed'])
        self._counter = int(st['counter'])


default_generator = Generator(0)


def seed(s: int):
    """Global seed (upstream: paddle.seed)."""
    default_generator.manual_seed(s)
    return default_generator


def next_rng_key():
    return default_generator.next_key()


def get_cuda_rng_state():
    """CUDA-API shim (upstream python/paddle/framework/random.py): the
    stateless threefry (seed, counter) pair is the only RNG state on
    TPU — returned as a one-element list to mirror the per-device list
    upstream returns."""
    return [default_generator.state()]


def set_cuda_rng_state(state):
    if state:
        default_generator.set_state(state[0])
