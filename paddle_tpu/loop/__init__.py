"""paddle_tpu.loop — post-training loops that close the
trainer→serving circle (ISSUE 12).

Training (`jit.TrainStep` / `resilience.elastic`) and serving
(`serving.Router` over a `ReplicaSet`) each stand alone; this package
drives them AS ONE SYSTEM: the serving fleet generates rollouts, a
reward function scores them, the trainer consumes the best of them, and
the freshly trained weights stream back into the very replicas that
generated the rollouts via the hot-swap subsystem
(`serving.hotswap`) — versioned, health-gated, zero-downtime,
zero-recompile. That is the RLHF-shaped composed scenario the whole
stack exists for (`examples/rlhf_loop.py` demos it end to end).

    from paddle_tpu.loop import RolloutLoop, response_lm_loss
"""
from __future__ import annotations

from .rollout import (Rollout, RolloutBatch, RolloutLoop,
                      response_lm_loss)

__all__ = ['Rollout', 'RolloutBatch', 'RolloutLoop', 'response_lm_loss']
