"""RLHF-shaped rollout loop: serve → score → train → publish → swap.

The loop implements best-of-n (rejection-sampling) fine-tuning — the
simplest member of the RLHF family that still has the full production
shape (WebGPT/RAFT-style): each iteration the SERVING fleet samples
`rollouts_per_iter` continuations for fresh prompts, a scalar
`reward_fn` scores each, the top `keep_best` become the training batch
(response tokens supervised, prompt tokens masked), the TRAINER takes
`train_passes` optimizer steps on them, the `WeightPublisher` snapshots
every `interval_steps`, and the `ReplicaUpdater` hot-swaps the new
version across the replicas — so the NEXT iteration's rollouts come
from the weights this iteration just learned. Two models, one storage
hop, no restart, no recompile:

    trainer (TrainStep)  --publish-->  WeightStore  --swap-->  Router
        ^                                                        |
        +------------- scored rollouts (reward_fn) <-------------+

Shapes are deliberately static: every training batch is exactly
`keep_best` rows of `seq_len` tokens (right-padded, masked), and every
rollout asks for the same `max_new_tokens` — so after the first
iteration NOTHING recompiles, which the example/test guard with the
same compile counters the serving stack uses.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import observability as _obs
from ..serving.api import SAMPLING, SamplingParams

# label value cross_entropy ignores: prompt + pad positions contribute
# zero loss, so only the RESPONSE tokens are supervised
IGNORE_INDEX = -100


def response_lm_loss(vocab_size: int):
    """Loss factory for `jit.TrainStep`: next-token cross-entropy over
    the response span only. Labels carry `IGNORE_INDEX` at prompt and
    pad positions (the loop builds them that way), which
    `F.cross_entropy(ignore_index=...)` masks out of the mean."""
    import paddle_tpu.nn.functional as F

    def loss_fn(logits, labels):
        return F.cross_entropy(
            logits[:, :-1].reshape([-1, int(vocab_size)]),
            labels[:, 1:].reshape([-1]), ignore_index=IGNORE_INDEX)
    return loss_fn


class Rollout:
    """One scored generation: `prompt` + `response` token lists and the
    scalar `reward` the reward function assigned."""

    __slots__ = ('prompt', 'response', 'reward', 'weight_version')

    def __init__(self, prompt: List[int], response: List[int],
                 reward: float, weight_version: Optional[int] = None):
        self.prompt = list(prompt)
        self.response = list(response)
        self.reward = float(reward)
        self.weight_version = weight_version

    def __repr__(self):
        return (f'Rollout(prompt={len(self.prompt)}t, '
                f'response={len(self.response)}t, '
                f'reward={self.reward:.4f}, v={self.weight_version})')


class RolloutBatch:
    """A reward-ranked set of rollouts plus the fixed-shape training
    arrays built from the best of them."""

    def __init__(self, rollouts: Sequence[Rollout], keep_best: int,
                 seq_len: int, pad_token_id: int = 0):
        self.rollouts = sorted(rollouts, key=lambda r: -r.reward)
        self.selected = self.rollouts[:int(keep_best)]
        self.seq_len = int(seq_len)
        self.pad_token_id = int(pad_token_id)
        self.inputs, self.labels = self._build()

    def _build(self):
        """[keep_best, seq_len] int32 arrays: inputs are prompt +
        response right-padded; labels mask prompt and pad positions
        with IGNORE_INDEX so only response tokens are supervised. The
        shape is identical every iteration — the train step never
        retraces."""
        n, t = len(self.selected), self.seq_len
        inputs = np.full((n, t), self.pad_token_id, np.int32)
        labels = np.full((n, t), IGNORE_INDEX, np.int32)
        for i, r in enumerate(self.selected):
            seq = (r.prompt + r.response)[:t]
            inputs[i, :len(seq)] = seq
            lo = min(len(r.prompt), t)
            hi = min(len(seq), t)
            labels[i, lo:hi] = seq[lo:hi]
        return inputs, labels

    @property
    def mean_reward(self) -> float:
        rs = [r.reward for r in self.rollouts]
        return float(np.mean(rs)) if rs else 0.0

    @property
    def best_reward(self) -> float:
        return self.rollouts[0].reward if self.rollouts else 0.0

    @property
    def selected_mean_reward(self) -> float:
        rs = [r.reward for r in self.selected]
        return float(np.mean(rs)) if rs else 0.0


class RolloutLoop:
    """The composed post-training driver (see module docstring).

    Args:
        train_step: a `jit.TrainStep`-shaped callable
            `(inputs, labels) -> loss` over the TRAINER's model (build
            it with `response_lm_loss(vocab)`); the publisher's source
            should snapshot the same model.
        router: the live serving `Router` the rollouts come from (its
            replicas are also the swap targets).
        publisher: `serving.WeightPublisher` over the trainer's model.
        updater: `serving.ReplicaUpdater` over `router` + the
            publisher's store.
        prompt_fn: `iteration_index -> list of prompt token lists`
            (`rollouts_per_iter` of them; fewer is allowed).
        reward_fn: `(prompt_tokens, response_tokens) -> float`.
        rollouts_per_iter / keep_best: generation fan-out and the
            best-of-n selection width (the training batch size —
            constant, so the step compiles once).
        max_new_tokens: response budget per rollout (constant).
        temperature / top_p / top_k: rollout sampling knobs; rollouts
            SAMPLE (seeded per request for reproducibility) because
            best-of-n needs diversity to select from.
        train_passes: optimizer steps per iteration on the selected
            batch.
        seq_len: training window (default: longest prompt the fn may
            yield + max_new_tokens, probed from iteration 0).
        pad_token_id: fill for the right-padding.
        swap_every_iters: poll the updater every this many iterations
            (1 = swap as soon as a version lands).
    """

    def __init__(self, *, train_step, router, publisher, updater,
                 prompt_fn: Callable[[int], Sequence[Sequence[int]]],
                 reward_fn: Callable[[List[int], List[int]], float],
                 rollouts_per_iter: int = 8, keep_best: int = 4,
                 max_new_tokens: int = 8, temperature: float = 1.0,
                 top_p: float = 1.0, top_k: int = 0,
                 train_passes: int = 1,
                 seq_len: Optional[int] = None, pad_token_id: int = 0,
                 swap_every_iters: int = 1):
        if keep_best < 1 or rollouts_per_iter < keep_best:
            raise ValueError('need rollouts_per_iter >= keep_best >= 1')
        self.train_step = train_step
        self.router = router
        self.publisher = publisher
        self.updater = updater
        self.prompt_fn = prompt_fn
        self.reward_fn = reward_fn
        self.rollouts_per_iter = int(rollouts_per_iter)
        self.keep_best = int(keep_best)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.train_passes = int(train_passes)
        self.seq_len = None if seq_len is None else int(seq_len)
        self.pad_token_id = int(pad_token_id)
        self.swap_every_iters = max(int(swap_every_iters), 1)
        self.global_step = 0
        self.iterations = 0
        self._req_seq = 0
        self.history: List[Dict[str, Any]] = []

    # -- phases -------------------------------------------------------------
    def generate(self, iteration: int) -> List[Rollout]:
        """Fan the iteration's prompts out over the serving fleet and
        score every finished continuation. Requests are seeded by a
        loop-owned sequence number, so a re-run reproduces the same
        rollouts from the same weights."""
        prompts = [list(map(int, p)) for p in self.prompt_fn(iteration)]
        handles = []
        for p in prompts:
            self._req_seq += 1
            handles.append(self.router.submit(p, SamplingParams(
                max_new_tokens=self.max_new_tokens, strategy=SAMPLING,
                temperature=self.temperature, top_p=self.top_p,
                top_k=self.top_k, eos_token_id=-1,
                seed=self._req_seq)))
        self.router.run()
        out = []
        for p, h in zip(prompts, handles):
            if h.status != 'FINISHED':
                continue    # a failed rollout is skipped, not fatal
            toks = list(h.tokens)
            out.append(Rollout(p, toks, self.reward_fn(p, toks),
                               h.weight_version))
        return out

    def _ensure_seq_len(self, rollouts: Sequence[Rollout]):
        if self.seq_len is None:
            longest = max((len(r.prompt) for r in rollouts), default=8)
            self.seq_len = longest + self.max_new_tokens

    def train(self, batch: RolloutBatch) -> float:
        """`train_passes` optimizer steps on the selected rollouts;
        returns the last loss. Publishes on the publisher's step
        interval as the step counter advances."""
        loss = None
        for _ in range(self.train_passes):
            loss = self.train_step(batch.inputs, batch.labels)
            self.global_step += 1
            self.publisher.maybe_publish(self.global_step)
        return float(np.asarray(getattr(loss, 'value', loss)))  # paddle-lint: disable=host-sync -- one scalar loss read per iteration, reported in history

    # -- the loop -----------------------------------------------------------
    def iteration(self) -> Dict[str, Any]:
        """One full serve→score→train→publish→swap turn; returns (and
        records) the iteration's stats."""
        i = self.iterations
        rollouts = self.generate(i)
        if not rollouts:
            raise RuntimeError(
                f'iteration {i}: every rollout failed — the serving '
                f'fleet is not producing continuations')
        self._ensure_seq_len(rollouts)
        batch = RolloutBatch(rollouts, self.keep_best, self.seq_len,
                             self.pad_token_id)
        loss = self.train(batch)
        swap = None
        if (i + 1) % self.swap_every_iters == 0:
            swap = self.updater.poll()
        self.iterations += 1
        stats = {
            'iteration': i,
            'rollouts': len(rollouts),
            'mean_reward': batch.mean_reward,
            'best_reward': batch.best_reward,
            'selected_mean_reward': batch.selected_mean_reward,
            'loss': loss,
            'global_step': self.global_step,
            'published_version': self.publisher.last_published_version,
            'swap': None if swap is None else
                    {'version': swap['version'],
                     'outcome': swap['outcome']},
            'fleet_version': self.updater.fleet_version,
        }
        self.history.append(stats)
        _obs.emit('rollout_iteration', **{
            k: v for k, v in stats.items()
            if isinstance(v, (int, float)) and v is not None})
        return stats

    def run(self, iterations: int) -> List[Dict[str, Any]]:
        for _ in range(int(iterations)):
            self.iteration()
        return self.history

    def stats(self) -> Dict[str, Any]:
        return {
            'iterations': self.iterations,
            'global_step': self.global_step,
            'fleet_version': self.updater.fleet_version,
            'store': self.publisher.store.stats(),
            'history': list(self.history),
        }
