"""paddle.fft (upstream: python/paddle/fft.py) — thin defop wrappers
over jnp.fft so transforms ride XLA's FFT lowering (and stay
differentiable through the tape)."""
from __future__ import annotations

import jax.numpy as jnp

from .ops._helpers import defop

__all__ = ['fft', 'ifft', 'rfft', 'irfft', 'hfft', 'ihfft',
           'fft2', 'ifft2', 'rfft2', 'irfft2',
           'fftn', 'ifftn', 'rfftn', 'irfftn',
           'fftshift', 'ifftshift', 'fftfreq', 'rfftfreq']


def _wrap1(jfn, name):
    def op(x, n=None, axis=-1, norm='backward', name_=None):
        return defop(lambda v: jfn(v, n=n, axis=axis, norm=norm),
                     name=name)(x)
    op.__name__ = name
    return op


def _wrap2(jfn, name):
    def op(x, s=None, axes=(-2, -1), norm='backward', name_=None):
        return defop(lambda v: jfn(v, s=s, axes=tuple(axes), norm=norm),
                     name=name)(x)
    op.__name__ = name
    return op


def _wrapn(jfn, name):
    def op(x, s=None, axes=None, norm='backward', name_=None):
        ax = tuple(axes) if axes is not None else None
        return defop(lambda v: jfn(v, s=s, axes=ax, norm=norm),
                     name=name)(x)
    op.__name__ = name
    return op


fft = _wrap1(jnp.fft.fft, 'fft')
ifft = _wrap1(jnp.fft.ifft, 'ifft')
rfft = _wrap1(jnp.fft.rfft, 'rfft')
irfft = _wrap1(jnp.fft.irfft, 'irfft')
hfft = _wrap1(jnp.fft.hfft, 'hfft')
ihfft = _wrap1(jnp.fft.ihfft, 'ihfft')
fft2 = _wrap2(jnp.fft.fft2, 'fft2')
ifft2 = _wrap2(jnp.fft.ifft2, 'ifft2')
rfft2 = _wrap2(jnp.fft.rfft2, 'rfft2')
irfft2 = _wrap2(jnp.fft.irfft2, 'irfft2')
fftn = _wrapn(jnp.fft.fftn, 'fftn')
ifftn = _wrapn(jnp.fft.ifftn, 'ifftn')
rfftn = _wrapn(jnp.fft.rfftn, 'rfftn')
irfftn = _wrapn(jnp.fft.irfftn, 'irfftn')


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return defop(lambda v: jnp.fft.fftshift(v, axes=ax),
                 name='fftshift')(x)


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return defop(lambda v: jnp.fft.ifftshift(v, axes=ax),
                 name='ifftshift')(x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor import Tensor
    out = jnp.fft.fftfreq(int(n), d=float(d))
    return Tensor(out.astype(jnp.dtype(dtype)) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .tensor import Tensor
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    return Tensor(out.astype(jnp.dtype(dtype)) if dtype else out)
