"""Replicated serving: health-checked router, failover, QoS admission.

One `InferenceEngine` is one failure domain: when it dies mid-decode,
every accepted request it held dies with it, and nothing tells clients
to back off when it saturates. Production TPU serving fleets
(MegaScale-style, Jiang et al. NSDI'24) treat replica failure and
overload as the STEADY STATE; this module composes the pieces the
repo already has — the continuous-batching engine (PR 4), the
transient-error classifier (PR 3), graceful drain + degraded-state
health (PR 5/6) — into that posture:

- `ReplicaSet` owns N engine replicas over the SAME weights
  (independent slot pools, independent compiled programs), each tagged
  with an observability scope ('replica:N') so degraded states are
  attributable per replica.
- `Router` places each accepted request on the healthy replica with
  the fewest outstanding decode tokens (least-loaded, not round-robin:
  a replica stuck behind a long-budget batch stops receiving work).
  Replicas are EXCLUDED while any degraded state (draining / resizing /
  hang_suspected) is active for their scope — the same machinery
  /healthz reports, not a parallel health system.
- Failover: a replica failure mid-step evicts its accepted-but-
  unfinished requests and resubmits them to survivors — IF the failure
  classifies as transient (`resilience.retry.is_transient`, which walks
  the `__cause__` chain, so the `ReplicaFailure`-wrapped PjRt error
  still reads as transient) and the per-request failover budget is not
  exhausted. Otherwise the request FAILS with the typed
  `ReplicaFailure` — accepted requests complete or fail loudly, never
  silently vanish. Greedy (and seeded-sampling) requests re-decode
  deterministically, so a failed-over request's tokens are bit-identical
  to an undisturbed run.
- A per-replica `CircuitBreaker` (closed -> open on consecutive
  failures -> half-open single probe -> closed) keeps the router from
  hammering a sick replica with resubmissions.
- Admission control (`tenancy.py`): per-tenant token-bucket rate +
  concurrency caps + priority classes, and explicit load shedding —
  past the queue-depth / estimated-TTFT budget, work below the
  protected priority is rejected FAST with a typed `AdmissionRejected`
  carrying a `retry_after_s` hint, before any prefill happens.

Everything reports: `paddle_router_*` metrics, `router_failover` /
`request_shed` / `breaker_*` events, a flight-recorder bundle on
failover storms, and a per-replica router section in
`debug.observability_summary()` / the HTTP `/summary`.
"""
from __future__ import annotations

import collections
import itertools
import time
from typing import Callable, List, Optional, Sequence

from .. import observability as _obs
from ..analysis.runtime import concurrency as _concurrency
from ..resilience.retry import is_transient
from .api import (FAILED, FINISHED, PRIORITY_LOW, QUEUED, RequestHandle,
                  SamplingParams)
from .engine import InferenceEngine
from .tenancy import (AdmissionRejected, TenantRegistry,
                      estimate_queue_rounds, parse_tenant_spec)

_router_ids = itertools.count()

# breaker states (gauge encoding: closed=0, half_open=1, open=2)
BREAKER_CLOSED = 'closed'
BREAKER_HALF_OPEN = 'half_open'
BREAKER_OPEN = 'open'
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


class ReplicaFailure(RuntimeError):
    """A replica failed with requests in flight. Raised `from` the
    underlying error, so the transient classifier (which walks
    `__cause__`) still sees the root cause; carried as the typed error
    on requests whose failover budget is exhausted (or whose root cause
    is fatal)."""

    def __init__(self, replica_id: int, msg: str):
        self.replica_id = replica_id
        super().__init__(msg)


class CircuitBreaker:
    """Per-replica circuit breaker: closed -> open after
    `failure_threshold` CONSECUTIVE failures -> half-open after
    `reset_after_s` -> one probe decides (success closes, failure
    reopens). `clock` is injectable for tests."""

    def __init__(self, name: str = '', failure_threshold: int = 3,
                 reset_after_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = str(name)
        self.failure_threshold = int(failure_threshold)
        self.reset_after_s = float(reset_after_s)
        self._clock = clock
        self._state = BREAKER_CLOSED
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probing = False

    def _transition(self, state: str, **attrs):
        if state == self._state:
            return
        self._state = state
        _obs.emit(f'breaker_{state}', replica=self.name, **attrs)
        if _obs.enabled():
            reg = _obs.get_registry()
            reg.counter('paddle_router_breaker_transitions_total',
                        'circuit-breaker state transitions',
                        ('replica', 'state')).labels(
                            replica=self.name, state=state).inc()
            reg.gauge('paddle_router_breaker_state',
                      'breaker state per replica (0 closed, 1 half-open,'
                      ' 2 open)', ('replica',)).labels(
                          replica=self.name).set(_BREAKER_GAUGE[state])

    @property
    def state(self) -> str:
        """Current state; an elapsed open-cooldown surfaces as
        half_open (the transition happens on inspection)."""
        if (self._state == BREAKER_OPEN and self._opened_at is not None
                and self._clock() - self._opened_at >= self.reset_after_s):
            self._probing = False
            self._transition(BREAKER_HALF_OPEN)
        return self._state

    def admits(self) -> bool:
        """May the router place NEW work here? Open: no. Half-open:
        only the single probe (claim it with `begin_probe`)."""
        s = self.state
        if s == BREAKER_CLOSED:
            return True
        if s == BREAKER_HALF_OPEN:
            return not self._probing
        return False

    def begin_probe(self):
        if self.state == BREAKER_HALF_OPEN:
            self._probing = True

    def record_success(self):
        self._consecutive = 0
        self._probing = False
        if self._state != BREAKER_CLOSED:
            self._transition(BREAKER_CLOSED)

    def record_failure(self):
        self._consecutive += 1
        self._probing = False
        if (self.state == BREAKER_HALF_OPEN
                or self._consecutive >= self.failure_threshold):
            self._opened_at = self._clock()
            self._transition(BREAKER_OPEN,
                             consecutive_failures=self._consecutive)


class Replica:
    """One engine + its breaker + its observability scope."""

    def __init__(self, rid: int, engine: InferenceEngine,
                 breaker: Optional[CircuitBreaker] = None):
        self.id = int(rid)
        self.engine = engine
        self.scope = f'replica:{self.id}'
        engine.obs_scope = self.scope
        self.breaker = breaker or CircuitBreaker(name=str(self.id))
        self.failures = 0

    def health_states(self) -> set:
        """Active degraded states for this replica: its own scope, plus
        process-global states (a process-wide 'resizing' grounds every
        replica), plus watchdog hang suspicion."""
        states = set(_obs.degraded_states(scope=self.scope))
        states |= set(_obs.degraded_states(scope=None))
        if _obs.hang_suspected():
            states.add('hang_suspected')
        return states

    def outstanding_tokens(self) -> int:
        """The placement score: decode tokens still owed to accepted
        requests (in-flight remaining budgets + queued full budgets)."""
        eng = self.engine
        out = 0
        for h in eng._slot_req.values():
            out += max(h.params.max_new_tokens - len(h.tokens), 0)
        for h in eng.scheduler.pending():
            out += h.params.max_new_tokens
        return out

    def __repr__(self):
        return (f'Replica({self.id}, breaker={self.breaker.state}, '
                f'states={sorted(self.health_states())}, '
                f'outstanding={self.outstanding_tokens()})')


class ReplicaSet:
    """N `InferenceEngine` replicas over the same model weights —
    independent slot pools, one shared parameter snapshot, and ONE
    shared program store: sibling replicas produce identical program
    keys, so the fleet compiles (or, with a persistent store, loads
    from disk) each decode/prefill executable exactly once instead of
    once per replica. `breaker_kwargs` feeds every replica's
    CircuitBreaker (tests inject clocks/thresholds here)."""

    def __init__(self, model, num_replicas: int = 2,
                 breaker_kwargs: Optional[dict] = None, **engine_kwargs):
        if num_replicas < 1:
            raise ValueError('num_replicas must be >= 1')
        from .. import programs as _programs
        store = _programs.get_store()
        if store.persistent:
            # one bulk preload for the whole fleet (each engine's own
            # preload is then an idempotent no-op); holds the
            # ref-counted `warming` degraded state on /healthz so the
            # router reports not-ready during the bulk load
            store.preload(match='serving.')
        self.replicas: List[Replica] = []
        for i in range(int(num_replicas)):
            eng = InferenceEngine(model, **engine_kwargs)
            self.replicas.append(Replica(
                i, eng, CircuitBreaker(name=str(i),
                                       **(breaker_kwargs or {}))))

    def __len__(self):
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    def __getitem__(self, i) -> Replica:
        return self.replicas[i]


class RouterHandle:
    """Router-level view of one ACCEPTED request. Proxies the live
    engine handle; survives failover (the inner handle is replaced and
    the request re-decodes deterministically from its prompt — greedy
    and seeded-sampling tokens are bit-identical to an undisturbed
    run). `status` is FAILED only with a typed error attached; accepted
    requests never dangle."""

    def __init__(self, router: 'Router', prompt_tokens: List[int],
                 params: SamplingParams, tenant: str, priority: int,
                 adapter_id: Optional[str] = None):
        self.router_id = next(_router_ids)
        self.prompt_tokens = list(prompt_tokens)
        self.params = params
        self.tenant = tenant
        self.priority = int(priority)
        # the LoRA adapter this request decodes under (None = base);
        # failover resubmits carry it, so the re-decoded response runs
        # under the same adapter id on the target replica
        self.adapter_id = adapter_id
        self.failovers = 0
        self.inner: Optional[RequestHandle] = None
        self.replica_id: Optional[int] = None
        self._router = router
        self._error: Optional[BaseException] = None
        self._finalized = False
        self._t_submit = time.perf_counter()
        self._t_first: Optional[float] = None
        # the per-request latency ledger record: adopted from the FIRST
        # engine placement (rebased to router submit so QoS/pick time
        # books as admission) and carried across failovers — one
        # waterfall spans replicas
        self._ledger_rec = None

    @property
    def tokens(self) -> List[int]:
        return self.inner.tokens if self.inner is not None else []

    @property
    def weight_version(self) -> Optional[int]:
        """The single weight version this response decodes under
        (stamped at engine admission). Failover replaces the inner
        handle and RE-decodes the whole response on the target replica,
        so the tag — like the tokens — is always the live attempt's:
        never mixed within one response."""
        return (self.inner.weight_version if self.inner is not None
                else None)

    @property
    def adapter_version(self) -> Optional[int]:
        """The adapter version the live attempt decodes under (pinned
        at engine admission; None for base requests or while queued).
        Like `weight_version`, failover re-decodes on the target
        replica, so the tag is always the live attempt's."""
        return (self.inner.adapter_version if self.inner is not None
                else None)

    @property
    def status(self) -> str:
        if self._error is not None:
            return FAILED
        return self.inner.status if self.inner is not None else QUEUED

    @property
    def error(self) -> Optional[BaseException]:
        if self._error is not None:
            return self._error
        return self.inner.error if self.inner is not None else None

    @property
    def done(self) -> bool:
        return self.status in (FINISHED, FAILED)

    @property
    def ttft(self) -> Optional[float]:
        """Seconds from ROUTER submit to the first observed token
        (failover does not reset it — the client's clock never
        restarts)."""
        if self._t_first is None:
            return None
        return self._t_first - self._t_submit

    def stream(self):
        """Per-token iterator driving the whole router (all replicas
        advance; failover happens under the hood). After a failover the
        re-decoded prefix is identical, so the cursor just waits for
        the new inner handle to catch up — no token is yielded twice."""
        cursor = 0
        while True:
            toks = self.tokens
            while cursor < len(toks):
                yield toks[cursor]
                cursor += 1
                toks = self.tokens
            if self.done:
                if self.status == FAILED:
                    raise self.error
                return
            self._router.step()

    def result(self) -> List[int]:
        """Drive the router until this request finishes; returns its
        tokens, or raises its typed error."""
        for _ in self.stream():
            pass
        return self.tokens

    def __repr__(self):
        return (f'RouterHandle(id={self.router_id}, tenant={self.tenant},'
                f' status={self.status}, replica={self.replica_id}, '
                f'failovers={self.failovers}, tokens={len(self.tokens)})')


class Router:
    """Health-checked, load-aware front of a `ReplicaSet`.

    Args:
        replicas: a ReplicaSet (or a plain sequence of Replica).
        tenants: TenantRegistry | {name: spec-dict} | CLI spec string |
            None (everyone is the default tenant: unlimited, NORMAL).
        max_failovers: per-request resubmission budget across replica
            failures; past it the request FAILs with `ReplicaFailure`.
        classify: transient/fatal judgment for failover decisions
            (default `resilience.retry.is_transient` — walks the
            exception chain).
        shed_queue_depth: total queued requests (across replicas) past
            which sheddable work is rejected (None = no depth shedding).
        ttft_budget_s: estimated-TTFT budget; when the queue would make
            a new request wait longer than this, sheddable work is
            rejected (None = off; the estimate is queue/replicas *
            observed round time, so it needs a few rounds of history).
        shed_priority: MINIMUM priority class that may be shed
            (default PRIORITY_LOW: only best-effort work sheds; set
            PRIORITY_NORMAL to protect only 'high').
        retry_after_s: the default `retry_after_s` hint when no better
            estimate exists.
        storm_threshold/storm_window_s: failover-storm detector — this
            many failovers inside the window emits
            `router_failover_storm` (a flight-recorder trigger).
        signal_window_s: width of the sliding signal windows (TTFT
            quantiles, queue depth, shed rate) behind
            `window_signals()` and the `paddle_ttft_p99_window`-family
            gauges — the autoscaler's control inputs. Cumulative
            per-request TTFT can't drive a control loop (an hour of
            history swamps the last 30 seconds); these age out by the
            clock.
    """

    # the replica map is mutated by scale actions (add_replica /
    # remove_replica, possibly on an operator/autoscaler thread) and
    # read per reap round and per stats() call (the /summary scrape
    # thread) — declared to the concurrency sanitizer so any access
    # outside `_lock` after the router is shared across threads is a
    # lockset-race report
    _by_id = _concurrency.guarded_by('_lock', mutable=True)

    def __init__(self, replicas, tenants=None, max_failovers: int = 2,
                 classify: Optional[Callable[[BaseException], bool]] = None,
                 shed_queue_depth: Optional[int] = None,
                 ttft_budget_s: Optional[float] = None,
                 shed_priority: int = PRIORITY_LOW,
                 retry_after_s: float = 1.0,
                 storm_threshold: int = 3, storm_window_s: float = 60.0,
                 signal_window_s: float = 30.0):
        if isinstance(replicas, ReplicaSet):
            self.replicas = list(replicas)
        else:
            self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError('router needs at least one replica')
        # guards replica-set mutation (add/remove/drain) against the
        # per-round reap reads and the stats()/scrape readers; RLock so
        # a locked scale action may refresh gauges (which re-reads)
        self._lock = _concurrency.RLock('Router._lock')
        self._by_id = {r.id: r for r in self.replicas}
        if isinstance(tenants, TenantRegistry):
            self.tenants = tenants
        elif isinstance(tenants, str):
            self.tenants = parse_tenant_spec(tenants)
        elif isinstance(tenants, dict):
            self.tenants = TenantRegistry(tenants)
        else:
            self.tenants = TenantRegistry()
        self.max_failovers = int(max_failovers)
        self.classify = classify or is_transient
        self.shed_queue_depth = shed_queue_depth
        self.ttft_budget_s = ttft_budget_s
        self.shed_priority = int(shed_priority)
        self.retry_after_s = float(retry_after_s)
        self.storm_threshold = int(storm_threshold)
        self.storm_window_s = float(storm_window_s)
        self._live: List[RouterHandle] = []
        self._rounds = 0
        self._ema_round_s: Optional[float] = None
        self._failover_times: collections.deque = collections.deque(
            maxlen=max(self.storm_threshold, 8))
        self._last_storm_t: Optional[float] = None
        self._counts = collections.Counter()
        # replica ids are NEVER reused: a removed replica's scoped
        # degraded states ('replica:N' draining) must not bleed onto a
        # later arrival that would otherwise inherit its id
        self._next_rid = max(r.id for r in self.replicas) + 1
        # sliding signal windows (autoscaler inputs + *_window gauges)
        self.signal_window_s = float(signal_window_s)
        self._win_ttft = _obs.SlidingWindow(self.signal_window_s)
        self._win_queue = _obs.SlidingWindow(self.signal_window_s)
        self._win_shed = _obs.SlidingWindow(self.signal_window_s)
        self._win_accept = _obs.SlidingWindow(self.signal_window_s)
        # queue-depth samples must be uniform in TIME, not per step: an
        # idle router steps thousands of times a second while a
        # backlogged one steps tens, so per-step sampling drowns the
        # backlog in idle zeros and the window quantiles lie
        self._queue_sample_interval = self.signal_window_s / 128.0
        self._last_queue_sample = float('-inf')
        self._init_metrics()

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------
    def _init_metrics(self):
        reg = _obs.get_registry()
        self._m_requests = reg.counter(
            'paddle_router_requests_total',
            'router requests by tenant and outcome',
            ('tenant', 'outcome'))
        self._m_failovers = reg.counter(
            'paddle_router_failovers_total',
            'requests resubmitted after a replica failure', ('replica',))
        self._m_shed = reg.counter(
            'paddle_router_shed_total',
            'admissions rejected fast, by tenant and reason',
            ('tenant', 'reason'))
        self._m_replicas = reg.gauge(
            'paddle_router_replicas', 'replicas behind the router')
        self._m_available = reg.gauge(
            'paddle_router_available_replicas',
            'replicas currently accepting placements')
        self._m_queue = reg.gauge(
            'paddle_router_queue_depth',
            'queued requests summed across replicas')
        self._m_outstanding = reg.gauge(
            'paddle_router_outstanding_tokens',
            'decode tokens owed to accepted requests, per replica',
            ('replica',))
        self._m_ttft = reg.histogram(
            'paddle_router_ttft_seconds',
            'router submit -> first token, by priority class',
            ('priority',))
        self._m_breaker = reg.gauge(
            'paddle_router_breaker_state',
            'breaker state per replica (0 closed, 1 half-open, 2 open)',
            ('replica',))
        self._m_weight_version = reg.gauge(
            'paddle_router_weight_version',
            'weight version each replica is serving (mixed values = '
            'rolling swap in flight)', ('replica',))
        # sliding-window signal gauges: what the cumulative families
        # above cannot say — "what does traffic look like RIGHT NOW" —
        # published so an autoscaler (or a dashboard alarm) can act on
        # /metrics alone
        self._m_ttft_p50_w = reg.gauge(
            'paddle_ttft_p50_window',
            'router TTFT p50 (seconds) over the sliding signal window')
        self._m_ttft_p99_w = reg.gauge(
            'paddle_ttft_p99_window',
            'router TTFT p99 (seconds) over the sliding signal window')
        self._m_queue_p50_w = reg.gauge(
            'paddle_queue_depth_p50_window',
            'fleet queue-depth p50 over the sliding signal window')
        self._m_queue_p99_w = reg.gauge(
            'paddle_queue_depth_p99_window',
            'fleet queue-depth p99 over the sliding signal window')
        self._m_shed_window = reg.gauge(
            'paddle_shed_rate_window',
            'admissions shed per second over the sliding signal window')
        if _obs.enabled():
            self._m_replicas.set(len(self.replicas))
            self._refresh_gauges()

    def _refresh_gauges(self):
        if not _obs.enabled():
            return
        avail = 0
        depth = 0
        for r in self.replicas:
            if not r.health_states() and r.breaker.state != BREAKER_OPEN:
                avail += 1
            depth += r.engine.scheduler.queue_depth
            self._m_outstanding.labels(replica=r.id).set(
                r.outstanding_tokens())
            self._m_breaker.labels(replica=r.id).set(
                _BREAKER_GAUGE[r.breaker.state])
            self._m_weight_version.labels(replica=r.id).set(
                r.engine.weight_version)
        self._m_available.set(avail)
        self._m_queue.set(depth)
        sig = self.window_signals()
        if sig['ttft_p50'] is not None:
            self._m_ttft_p50_w.set(sig['ttft_p50'])
            self._m_ttft_p99_w.set(sig['ttft_p99'])
        if sig['queue_p50'] is not None:
            self._m_queue_p50_w.set(sig['queue_p50'])
            self._m_queue_p99_w.set(sig['queue_p99'])
        self._m_shed_window.set(sig['shed_rate'])

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        return sum(r.engine.scheduler.queue_depth for r in self.replicas)

    def _estimated_ttft_s(self) -> Optional[float]:
        """Queue wait estimate for a NEW request: rounds of queued work
        ahead of it divided over serving replicas, times the observed
        round time. Chunking-aware: on a chunked-prefill engine each
        queued prompt costs ceil(prompt/chunk) CHEAP rounds (the round
        time the EMA observes is chunk-bounded), not one whole-prompt
        prefill — charging full prefills against chunk-sized round
        times would over-fire the shed budget. None until a round has
        been timed."""
        if self._ema_round_s is None:
            return None
        serving = sum(1 for r in self.replicas
                      if not r.health_states()
                      and r.breaker.state != BREAKER_OPEN) or 1
        rounds = sum(
            estimate_queue_rounds(
                (len(h.prompt_tokens)
                 for h in r.engine.scheduler.pending()),
                r.engine.prefill_chunk_tokens)
            for r in self.replicas)
        return (rounds / serving + 1) * self._ema_round_s

    def _reject(self, tenant: str, reason: str,
                retry_after: Optional[float], detail: str = '',
                depth_guard: Optional[int] = None):
        self._counts[f'rejected_{reason}'] += 1
        # shed-accounting invariant (ISSUE 14): a request rejected at
        # admission was never handed to any engine, so the fleet
        # queue-depth signal — which the autoscaler reads as DEMAND —
        # must be exactly what it was when this submission arrived.
        # Double-counting rejected work as demand would make a burst
        # that is being correctly shed look like a reason to scale up.
        if depth_guard is not None:
            depth_now = self.queue_depth
            assert depth_now == depth_guard, (
                f'shed accounting violated: queue depth moved '
                f'{depth_guard} -> {depth_now} while rejecting '
                f'({reason}) — a rejected request leaked into a '
                f'replica queue')
        if reason in ('shed', 'no_healthy_replica'):
            # capacity sheds (not per-tenant policy rejects like
            # rate_limited): the windowed shed-rate signal feeds the
            # autoscaler's scale-up decision
            self._win_shed.mark()
        if _obs.enabled():
            self._m_requests.labels(tenant=tenant, outcome=reason).inc()
            self._m_shed.labels(tenant=tenant, reason=reason).inc()
        raise AdmissionRejected(tenant, reason, retry_after, detail)

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               tenant: Optional[str] = None,
               priority: Optional[int] = None,
               adapter_id: Optional[str] = None, **kwargs) -> RouterHandle:
        """Admit one request for `tenant` (QoS checks first — a
        rejection is synchronous, typed, and consumed NO model work),
        then place it on the least-loaded healthy replica. Returns the
        live RouterHandle; raises `AdmissionRejected` (with
        `retry_after_s`) on rate limit / concurrency cap / load shed /
        adapter unavailable / no healthy replica, or ValueError on
        malformed requests. `adapter_id` names the LoRA adapter the
        request decodes under; unset, the tenant's default `adapter`
        (if any) applies."""
        if params is None:
            params = SamplingParams(**kwargs)
        elif kwargs:
            raise TypeError('pass params= or keyword sampling args, '
                            'not both')
        t = self.tenants.get(tenant)
        prio = int(priority) if priority is not None else t.priority
        if adapter_id is None:
            adapter_id = t.adapter
        # snapshot for the shed-accounting invariant: any rejection
        # below must leave the fleet queue depth exactly here
        depth0 = self.queue_depth

        # 0. adapter availability: fail FAST and typed before any QoS
        # token is spent — a request for a missing adapter can never
        # succeed, so it must not consume a rate-bucket token either
        if adapter_id is not None:
            for r in self.replicas:
                bank = getattr(r.engine, 'adapter_bank', None)
                if bank is None:
                    self._reject(t.name, 'adapter_unavailable', None,
                                 f'adapter {adapter_id!r} requested but '
                                 f'replica {r.id} serves no adapter bank',
                                 depth_guard=depth0)
                if not bank.available(adapter_id):
                    self._reject(
                        t.name, 'adapter_unavailable', None,
                        f'adapter {adapter_id!r} is not resident on '
                        f'replica {r.id} and has no servable store '
                        f'version', depth_guard=depth0)

        # 1. per-tenant token-bucket rate
        if t.bucket is not None and not t.bucket.try_acquire():
            self._reject(t.name, 'rate_limited', t.bucket.retry_after(),
                         f'rate {t.bucket.rate}/s exceeded',
                         depth_guard=depth0)
        # 2. per-tenant concurrency cap
        if (t.max_concurrency is not None
                and t.in_flight >= t.max_concurrency):
            est = self._estimated_ttft_s()
            self._reject(t.name, 'concurrency',
                         est if est is not None else self.retry_after_s,
                         f'{t.in_flight} in flight >= cap '
                         f'{t.max_concurrency}',
                         depth_guard=depth0)
        # 3. load shedding: overload rejects sheddable work FAST
        if prio >= self.shed_priority:
            est = self._estimated_ttft_s()
            depth_over = (self.shed_queue_depth is not None
                          and self.queue_depth >= self.shed_queue_depth)
            ttft_over = (self.ttft_budget_s is not None
                         and est is not None
                         and est > self.ttft_budget_s)
            if depth_over or ttft_over:
                reason_bits = []
                if depth_over:
                    reason_bits.append(
                        f'queue {self.queue_depth} >= '
                        f'{self.shed_queue_depth}')
                if ttft_over:
                    reason_bits.append(
                        f'est ttft {est:.3f}s > {self.ttft_budget_s}s')
                _obs.emit('request_shed', tenant=t.name, priority=prio,
                          queue_depth=self.queue_depth,
                          detail='; '.join(reason_bits))
                self._counts['shed'] += 1
                self._reject(
                    t.name, 'shed',
                    est if est is not None else self.retry_after_s,
                    '; '.join(reason_bits), depth_guard=depth0)
        # 4. placement on the least-loaded healthy replica
        replica = self._pick_replica()
        if replica is None:
            self._reject(t.name, 'no_healthy_replica',
                         self.retry_after_s,
                         'every replica is degraded or circuit-broken',
                         depth_guard=depth0)

        rh = RouterHandle(self, InferenceEngine._normalize_prompt(prompt),
                          params, t.name, prio, adapter_id=adapter_id)
        try:
            self._place(rh, replica)
        except RuntimeError:
            # the pick->place race: the chosen replica began draining
            # (autoscaler scale-down, preemption) after the health check.
            # One re-pick excluding it; if nobody else is healthy the
            # caller gets the TYPED rejection every other capacity path
            # produces, never a bare engine RuntimeError.
            replica = self._pick_replica(exclude=(replica,))
            if replica is None:
                self._reject(t.name, 'no_healthy_replica',
                             self.retry_after_s,
                             'every replica is degraded, draining, or '
                             'circuit-broken', depth_guard=depth0)
            self._place(rh, replica)
        t.in_flight += 1
        self._win_accept.mark()
        self._live.append(rh)
        self._counts['accepted'] += 1
        if _obs.enabled():
            self._m_requests.labels(tenant=t.name,
                                    outcome='accepted').inc()
            self._refresh_gauges()
        return rh

    def _pick_replica(self, exclude: Sequence[Replica] = ()
                      ) -> Optional[Replica]:
        best = None
        for r in self.replicas:
            if r in exclude or r.health_states() or not r.breaker.admits():
                continue
            score = (r.outstanding_tokens(), r.id)
            if best is None or score < best[0]:
                best = (score, r)
        return best[1] if best else None

    def _place(self, rh: RouterHandle, replica: Replica):
        if replica.breaker.state == BREAKER_HALF_OPEN:
            replica.breaker.begin_probe()   # this request IS the probe
        rh.inner = replica.engine.submit(rh.prompt_tokens, rh.params,
                                         priority=rh.priority,
                                         adapter_id=rh.adapter_id)
        rh.replica_id = replica.id
        rec = rh._ledger_rec
        if rec is None:
            # first placement: adopt the record engine.submit opened,
            # re-anchored at ROUTER submit — the tenancy/QoS checks and
            # replica pick in between book as admission
            rec = getattr(rh.inner, '_ledger_rec', None)
            if rec is not None:
                rec.rebase_submit(rh._t_submit)
                rec.tenant = rh.tenant
                rh._ledger_rec = rec
        else:
            # failover: drop the fresh record this submit opened and
            # keep the ORIGINAL following the request — the waterfall
            # spans replicas
            rh.inner._ledger_rec = rec
            rec.failovers = rh.failovers
        if rec is not None:
            rec.replica_id = replica.id

    # ------------------------------------------------------------------
    # the iteration loop
    # ------------------------------------------------------------------
    def step(self) -> int:
        """ONE fleet iteration: advance every replica that has work
        (degraded replicas still DRIVE their in-flight requests — they
        just receive no new placements), fail over anything a dying
        replica drops, retire finished requests. Returns the number of
        requests that progressed."""
        progressed = 0
        t0 = time.perf_counter()
        stepped = False
        for r in list(self.replicas):
            if not r.engine.has_work:
                continue
            try:
                progressed += r.engine.step()
                stepped = True
            except BaseException as exc:
                self._on_replica_failure(r, exc)
        if stepped:
            dt = time.perf_counter() - t0
            self._ema_round_s = (dt if self._ema_round_s is None
                                 else 0.8 * self._ema_round_s + 0.2 * dt)
        self._reap()
        self._rounds += 1
        # windowed demand sample: ACCEPTED queued work only, observed
        # after admission/reaping — never inside the submit path, so a
        # burst of shed submissions cannot pump the demand signal —
        # and throttled to a time-uniform cadence (see __init__)
        now_m = time.monotonic()
        if now_m - self._last_queue_sample >= self._queue_sample_interval:
            self._last_queue_sample = now_m
            self._win_queue.observe(self.queue_depth)
        # gauges are monitoring, not control flow: refreshing every 8th
        # round keeps the per-round router cost out of the decode path
        # (submit/finalize still refresh immediately where it matters)
        if _obs.enabled() and (self._rounds % 8 == 0 or not self._live):
            self._refresh_gauges()
        return progressed

    def run(self) -> int:
        """Drive until every accepted request is FINISHED or FAILED;
        returns the number of router iterations."""
        rounds = 0
        while self._live:
            progressed = self.step()
            rounds += 1
            if (not progressed and self._live
                    and not any(r.engine.has_work for r in self.replicas)):
                # defensive: a handle with no engine work behind it is a
                # router bug — fail it typed rather than spin forever
                for rh in self._live:
                    rh._error = ReplicaFailure(
                        rh.replica_id if rh.replica_id is not None else -1,
                        'request stranded with no engine work (router '
                        'invariant violated)')
                self._reap()
                break
        return rounds

    def _reap(self):
        now = time.perf_counter()
        still: List[RouterHandle] = []
        for rh in self._live:
            if (rh._t_first is None and rh.inner is not None
                    and rh.inner.tokens):
                rh._t_first = now
                self._win_ttft.observe(now - rh._t_submit)
            with self._lock:
                replica = self._by_id.get(rh.replica_id)
            if rh._error is not None:
                self._finalize(rh, 'failed')
            elif rh.inner is not None and rh.inner.status == FINISHED:
                if replica is not None:
                    replica.breaker.record_success()
                self._finalize(rh, 'completed')
                if _obs.enabled() and rh.ttft is not None:
                    self._m_ttft.labels(priority=rh.priority).observe(
                        rh.ttft)
            elif rh.inner is not None and rh.inner.status == FAILED:
                # request-level failure (engine already classified and
                # retried transients; this is final) — typed, not lost
                rh._error = rh.inner.error
                if (replica is not None
                        and replica.breaker.state == BREAKER_HALF_OPEN):
                    replica.breaker.record_failure()   # failed probe
                self._finalize(rh, 'failed')
            else:
                still.append(rh)
        self._live = still

    def _finalize(self, rh: RouterHandle, outcome: str):
        if rh._finalized:
            return
        rh._finalized = True
        self.tenants.get(rh.tenant).in_flight -= 1
        self._counts[outcome] += 1
        if rh.inner is not None and rh.inner._ledger_rec is not None:
            # completed/engine-failed requests already closed their
            # record via the handle hooks (finalize is idempotent);
            # this catches router-level failures (_error set with the
            # inner handle merely evicted, never failed)
            from ..observability import reqledger as _reqledger
            _reqledger.get_ledger().finalize(rh.inner, outcome=outcome)
        if _obs.enabled():
            self._m_requests.labels(tenant=rh.tenant,
                                    outcome=outcome).inc()

    # ------------------------------------------------------------------
    # failover
    # ------------------------------------------------------------------
    def _on_replica_failure(self, replica: Replica, exc: BaseException):
        """A replica failed mid-step: open-circuit accounting, evict its
        accepted requests, resubmit the ones the classifier deems
        recoverable (bounded per request), fail the rest typed."""
        replica.failures += 1
        replica.breaker.record_failure()
        orphans = replica.engine.evict_all()
        by_inner = {id(rh.inner): rh for rh in self._live
                    if rh.inner is not None}
        _obs.emit('router_failover', replica=replica.id,
                  error=type(exc).__name__, orphans=len(orphans))
        self._note_failover_storm()
        if _obs.enabled():
            self._m_failovers.labels(replica=replica.id).inc(
                len(orphans) or 1)
        transient = self.classify(self._wrap(replica, exc))
        for h in orphans:
            rh = by_inner.get(id(h))
            if rh is None:
                continue   # an engine-level handle the router never saw
            rec = rh._ledger_rec
            t_det = time.perf_counter()
            if rec is not None:
                if rec._q_mark is not None:
                    # the victim was still queued on the dead replica:
                    # its wait so far stays queue_wait
                    rec.queue_exit(t_det)
                else:
                    # mid-decode victim: the gap since its last round
                    # IS the failure-detection window
                    rec.add('failover_resubmit',
                            t_det - rec._last_touch, now=t_det)
            err = self._wrap(replica, exc)
            if not transient or rh.failovers >= self.max_failovers:
                rh._error = err
                continue
            target = self._pick_replica(exclude=(replica,))
            if target is None:
                if rec is not None:
                    # time from here to the failed-request reap books
                    # under the reason the victim actually died of
                    rec.queue_enter(t_det, 'no_healthy_replica')
                rh._error = ReplicaFailure(
                    replica.id,
                    f'replica {replica.id} failed and no healthy '
                    f'replica remains for failover')
                rh._error.__cause__ = exc
                continue
            rh.failovers += 1
            try:
                self._place(rh, target)
            except BaseException as place_exc:
                rh._error = ReplicaFailure(
                    target.id,
                    f'failover resubmission to replica {target.id} '
                    f'failed: {place_exc}')
                rh._error.__cause__ = place_exc
                continue
            if rec is not None:
                # re-placement work (re-submit incl. prompt re-prep on
                # the target) books as failover_resubmit, then the
                # request re-queues — behind the survivor's own load,
                # or breaker-gated if the target is probing
                t2 = time.perf_counter()
                rec.add('failover_resubmit', t2 - t_det, now=t2)
                rec.queue_enter(
                    t2, 'breaker_open' if not replica.breaker.admits()
                    else 'priority_queued')

    @staticmethod
    def _wrap(replica: Replica, exc: BaseException) -> ReplicaFailure:
        err = ReplicaFailure(
            replica.id,
            f'replica {replica.id} failed mid-flight: '
            f'{type(exc).__name__}: {exc}')
        err.__cause__ = exc   # the classifier walks this chain
        return err

    def _note_failover_storm(self):
        now = time.monotonic()
        self._failover_times.append(now)
        if len(self._failover_times) < self.storm_threshold:
            return
        window = now - self._failover_times[-self.storm_threshold]
        if window > self.storm_window_s:
            return
        if (self._last_storm_t is not None
                and now - self._last_storm_t < self.storm_window_s):
            return   # one storm event per window
        self._last_storm_t = now
        _obs.emit('router_failover_storm',
                  failovers=len(self._failover_times),
                  window_s=round(window, 3))

    # ------------------------------------------------------------------
    # windowed signals (the autoscaler's control inputs)
    # ------------------------------------------------------------------
    def serving_replica_count(self) -> int:
        """Replicas currently accepting placements (healthy, breaker
        not open). Draining replicas still DRIVE their work but count
        as leaving capacity."""
        return sum(1 for r in self.replicas
                   if not r.health_states()
                   and r.breaker.state != BREAKER_OPEN)

    def window_signals(self) -> dict:
        """One consistent snapshot of the sliding-window control
        signals: TTFT p50/p99 (None before the first in-window first
        token), fleet queue-depth p50/p99 over the per-step samples
        (None before the first routed step), capacity-shed rate and
        accept rate (requests/second), and the serving replica count.
        This — not the cumulative `paddle_router_*` families — is what
        the autoscaler polls: every value ages out of the window by the
        clock, so a burst that ended a minute ago stops arguing for
        more replicas."""
        return {
            'window_s': self.signal_window_s,
            'ttft_p50': self._win_ttft.quantile(0.50),
            'ttft_p99': self._win_ttft.quantile(0.99),
            'queue_p50': self._win_queue.quantile(0.50),
            'queue_p99': self._win_queue.quantile(0.99),
            'shed_rate': self._win_shed.rate(),
            'accept_rate': self._win_accept.rate(),
            'serving_replicas': self.serving_replica_count(),
        }

    # ------------------------------------------------------------------
    # operations
    # ------------------------------------------------------------------
    def add_replica(self, engine: InferenceEngine,
                    breaker_kwargs: Optional[dict] = None) -> Replica:
        """Join a freshly provisioned engine to the fleet under a new —
        never recycled — replica id (a removed replica's scoped
        degraded states must not bleed onto a later arrival). The
        engine should come from the same weights/geometry as its
        siblings so it resolves the identical ProgramStore keys (the
        warm scale-up path: it loads, not compiles). Returns the new
        Replica, immediately eligible for placement."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            r = Replica(rid, engine,
                        CircuitBreaker(name=str(rid),
                                       **(breaker_kwargs or {})))
            self.replicas.append(r)
            self._by_id[rid] = r
        if _obs.enabled():
            self._m_replicas.set(len(self.replicas))
            self._refresh_gauges()
        return r

    def remove_replica(self, rid: int) -> Replica:
        """Detach a DRAINED replica from the fleet (the scale-down
        endpoint: `drain_replica` first, keep stepping until its engine
        has no work, then remove). Refuses while the engine still holds
        accepted work — removal must never drop a request — and clears
        the replica's scoped `draining` health state so /healthz
        converges once the replica is gone."""
        with self._lock:
            r = self._by_id[rid]
            if r.engine.has_work:
                raise RuntimeError(
                    f'replica {rid} still holds accepted work '
                    f'(queued={r.engine.scheduler.queue_depth}, '
                    f'in_flight={len(r.engine._slot_req)}); drain it '
                    f'before removing')
            if len(self.replicas) <= 1:
                raise RuntimeError('refusing to remove the last replica')
            del self._by_id[rid]
            self.replicas.remove(r)
        _obs.clear_degraded('draining', scope=r.scope, force=True)
        if _obs.enabled():
            self._m_replicas.set(len(self.replicas))
            self._refresh_gauges()
        return r

    def drain_replica(self, rid: int):
        """Take replica `rid` out of rotation NOW (runbook: rolling
        restart / eviction). Its scoped `draining` state excludes it
        from placement immediately; router steps keep driving its
        accepted requests to completion. Returns the replica."""
        with self._lock:
            r = self._by_id[rid]
        r.engine.begin_drain()
        return r

    def generate_many(self, prompts, params=None, tenant=None,
                      priority=None,
                      adapter_id: Optional[str] = None
                      ) -> List[RouterHandle]:
        """Submit a batch and drive the fleet dry (the router analogue
        of `InferenceEngine.generate_many`)."""
        if params is None or isinstance(params, SamplingParams):
            params = [params or SamplingParams()] * len(prompts)
        if len(params) != len(prompts):
            raise ValueError('one SamplingParams per prompt')
        handles = [self.submit(p, sp, tenant=tenant, priority=priority,
                               adapter_id=adapter_id)
                   for p, sp in zip(prompts, params)]
        self.run()
        return handles

    def stats(self) -> dict:
        """Router-level counters + a per-replica health/load snapshot
        (the chaos tests' 'none dangle' assertions read this)."""
        per_replica = []
        # snapshot under the fleet lock: stats() runs on scrape threads
        # while add_replica/remove_replica resize the list
        with self._lock:
            replicas = list(self.replicas)
        for r in replicas:
            per_replica.append({
                'id': r.id,
                'breaker': r.breaker.state,
                'health_states': sorted(r.health_states()),
                'outstanding_tokens': r.outstanding_tokens(),
                'queued': r.engine.scheduler.queue_depth,
                'active_slots': len(r.engine._slot_req),
                'failures': r.failures,
                'weight_version': r.engine.weight_version,
            })
        return {
            'accepted': self._counts['accepted'],
            'completed': self._counts['completed'],
            'failed': self._counts['failed'],
            'shed': self._counts['shed'],
            'rejected': {k[len('rejected_'):]: v
                         for k, v in self._counts.items()
                         if k.startswith('rejected_')},
            'in_flight': len(self._live),
            'queue_depth': self.queue_depth,
            'replicas': per_replica,
            'tenants': {name: {'in_flight': t.in_flight, **t.spec()}
                        for name, t in self.tenants.tenants().items()},
        }
