"""Iteration-level FCFS scheduler (Orca-style continuous batching).

The engine calls `admissible()` between decode steps; the scheduler
hands back the queue head(s) that fit the currently free slots, under a
per-iteration prefill token budget so a burst of long prompts cannot
starve the decode of already-running requests (the prefill/decode
interleave knob). Admission is strictly FCFS — the head request is never
overtaken by a shorter one behind it (no starvation), and the FIRST
admission of an iteration ignores the budget so a single over-budget
prompt still makes progress.

Queue depth is exported as `paddle_serving_queue_depth` on every
mutation, so the gauge is live even between scrapes.
"""
from __future__ import annotations

import collections
from typing import Callable, Deque, List, Optional

from .. import observability as _obs
from .api import RequestHandle


class FCFSScheduler:
    """FCFS request queue + iteration-level admission policy.

    `max_prefill_tokens` caps the summed BUCKETED prompt lengths admitted
    in one scheduling iteration (0/None = unbounded). Bucketed — not raw
    — lengths, because the bucket is what the prefill actually computes.
    """

    def __init__(self, max_prefill_tokens: Optional[int] = None):
        self.max_prefill_tokens = (int(max_prefill_tokens)
                                   if max_prefill_tokens else 0)
        self._queue: Deque[RequestHandle] = collections.deque()
        self._gauge = None
        if _obs.enabled():
            self._gauge = _obs.get_registry().gauge(
                'paddle_serving_queue_depth',
                'requests waiting for a slot')
            self._gauge.set(0)

    def _note_depth(self):
        if self._gauge is not None:
            self._gauge.set(len(self._queue))

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def submit(self, handle: RequestHandle):
        self._queue.append(handle)
        self._note_depth()

    def cancel(self, handle: RequestHandle) -> bool:
        """Drop a still-queued request; False if it already left the
        queue (running requests retire through the engine)."""
        try:
            self._queue.remove(handle)
        except ValueError:
            return False
        self._note_depth()
        return True

    def drain(self) -> List[RequestHandle]:
        """Pop and return every queued request (drain-deadline expiry:
        the engine fails them rather than dropping them silently)."""
        out = list(self._queue)
        self._queue.clear()
        self._note_depth()
        return out

    def admissible(self, free_slots: int,
                   bucket_for: Callable[[int], int]) -> List[RequestHandle]:
        """Pop the FCFS prefix that fits `free_slots` and the prefill
        token budget this iteration."""
        admitted: List[RequestHandle] = []
        budget = self.max_prefill_tokens
        while self._queue and free_slots > 0:
            cost = bucket_for(len(self._queue[0].prompt_tokens))
            if admitted and self.max_prefill_tokens and cost > budget:
                break   # budget spent; head waits for the next iteration
            admitted.append(self._queue.popleft())
            free_slots -= 1
            budget -= cost
        if admitted:
            self._note_depth()
        return admitted
