"""Iteration-level scheduler (Orca-style continuous batching) with
priority classes.

The engine calls `admissible()` between decode steps; the scheduler
hands back the queued request(s) that fit the currently free slots,
under a per-iteration prefill token budget so a burst of long prompts
cannot starve the decode of already-running requests (the
prefill/decode interleave knob).

Admission order is a STABLE priority key: (priority class, then FCFS
within class). With a single priority class — the default, every
handle is PRIORITY_NORMAL — this degenerates to exactly the original
FCFS policy: the head request is never overtaken by a shorter one
behind it, and the FIRST admission of an iteration ignores the budget
so a single over-budget prompt still makes progress. The router's
tenancy layer maps tenants onto classes so paid traffic overtakes
best-effort traffic at the queue, not mid-decode.

Starvation guard: a request that has waited longer than `max_wait_s`
is promoted ONE class (once), so an overload of high-priority work can
delay low-priority requests but never park them forever.

Queue depth is exported as `paddle_serving_queue_depth` on every
mutation, so the gauge is live even between scrapes.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

from .. import observability as _obs
from .api import PRIORITY_NORMAL, RequestHandle


class FCFSScheduler:
    """Priority + FCFS request queue and iteration-level admission.

    `max_prefill_tokens` caps the summed BUCKETED prompt lengths admitted
    in one scheduling iteration (0/None = unbounded). Bucketed — not raw
    — lengths, because the bucket is what the prefill actually computes.

    `max_wait_s` arms the starvation guard (None = off): a request older
    than this is promoted one priority class, once, and counted in
    `promotions`.
    """

    def __init__(self, max_prefill_tokens: Optional[int] = None,
                 max_wait_s: Optional[float] = None):
        self.max_prefill_tokens = (int(max_prefill_tokens)
                                   if max_prefill_tokens else 0)
        self.max_wait_s = (float(max_wait_s) if max_wait_s else None)
        self.promotions = 0
        self._queue: List[RequestHandle] = []
        self._gauge = None
        if _obs.enabled():
            self._gauge = _obs.get_registry().gauge(
                'paddle_serving_queue_depth',
                'requests waiting for a slot')
            self._gauge.set(0)

    def _note_depth(self):
        if self._gauge is not None:
            self._gauge.set(len(self._queue))

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def pending(self) -> List[RequestHandle]:
        """Snapshot of the queued handles (router introspection)."""
        return list(self._queue)

    def submit(self, handle: RequestHandle):
        self._queue.append(handle)
        self._note_depth()

    def requeue(self, handle: RequestHandle):
        """Put an already-admitted handle back at the queue FRONT (the
        engine could not actually seat it — e.g. the free slot it was
        promised got pinned by a prefix-cache hit in the same admission
        pass, or its page reservation hit PagePoolExhausted). Front
        insertion preserves FCFS-within-class order, and the handle's
        `_t_submit` is deliberately NOT touched: queue_wait, ttft, and
        the starvation guard all measure from FIRST submit, however many
        times the request bounces back (test_reqledger pins this)."""
        self._queue.insert(0, handle)
        self._note_depth()

    def cancel(self, handle: RequestHandle) -> bool:
        """Drop a still-queued request; False if it already left the
        queue (running requests retire through the engine)."""
        try:
            self._queue.remove(handle)
        except ValueError:
            return False
        self._note_depth()
        return True

    def drain(self) -> List[RequestHandle]:
        """Pop and return every queued request (drain-deadline expiry:
        the engine fails them rather than dropping them silently)."""
        out = list(self._queue)
        self._queue.clear()
        self._note_depth()
        return out

    def _effective_priority(self, handle: RequestHandle,
                            now: float) -> int:
        p = int(getattr(handle, 'priority', PRIORITY_NORMAL))
        if (self.max_wait_s is not None and p > 0
                and now - handle._t_submit > self.max_wait_s):
            if not getattr(handle, '_promoted', False):
                handle._promoted = True
                self.promotions += 1
                _obs.emit('request_promoted',
                          request_id=handle.request_id,
                          from_priority=p, to_priority=p - 1,
                          waited_s=round(now - handle._t_submit, 3))
            p -= 1
        return p

    def admissible(self, free_slots: int,
                   bucket_for: Callable[[int], int]) -> List[RequestHandle]:
        """Pop the admission-order prefix that fits `free_slots` and the
        prefill token budget this iteration. Order = stable sort by
        (effective priority, submit order); the prefix rule is the same
        as FCFS — once the next-in-order request doesn't fit the budget,
        nothing behind it is considered (no overtaking)."""
        if not self._queue or free_slots <= 0:
            return []
        now = time.perf_counter()
        # python's sort is stable: within a class, list order == FCFS
        order = sorted(self._queue,
                       key=lambda h: self._effective_priority(h, now))
        admitted: List[RequestHandle] = []
        budget = self.max_prefill_tokens
        for h in order:
            if len(admitted) >= free_slots:
                break
            cost = bucket_for(len(h.prompt_tokens))
            if admitted and self.max_prefill_tokens and cost > budget:
                break   # budget spent; the head waits, nothing overtakes
            admitted.append(h)
            budget -= cost
        for h in admitted:
            self._queue.remove(h)
        if admitted:
            self._note_depth()
        return admitted
