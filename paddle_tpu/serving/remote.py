"""Cross-process replica RPC: checksummed JSONL frames + RemoteReplica.

The one-process wall (ROADMAP "Break the one-process wall"): until this
module, the trainer, the Router, and every InferenceEngine replica
shared one Python process, so a replica "crash" was a monkeypatched
exception, autoscaling moved latency but never added throughput, and a
hot swap had never crossed a process boundary. This module is the
client half of the fix; `replica_main.py` is the process entrypoint and
`supervisor.py` spawns/heals the fleet.

Wire protocol (stdlib only — no grpc/msgpack in the image):

- one request or response per frame over an AF_UNIX stream socket
- frame = 4-byte big-endian payload length, then a 64-hex-char sha256
  digest, then the UTF-8 JSON payload (JSONL in spirit: one JSON doc
  per frame, newline-free)
- a short read raises `IncompleteFrameError` (a ConnectionError: the
  peer died mid-frame) and a digest mismatch raises
  `FrameChecksumError` — both classify TRANSIENT through
  `resilience.retry`, so the Router's existing failover path treats a
  torn frame exactly like a PjRt device loss: evict + resubmit to
  survivors, never trust a half-message
- every call carries a per-call deadline via socket timeouts;
  `socket.timeout` (TimeoutError) is already transient by type

`RemoteReplica` implements the exact duck-type surface the Router and
Autoscaler already place against an in-process `InferenceEngine`
(submit/step/has_work/evict_all/begin_drain/drain/stats/healthz/
swap_weights, plus the `scheduler.queue_depth`/`pending()` and
`_slot_req` views the load estimator reads), so routing, QoS, breakers
and failover code are UNTOUCHED by the process split. The client keeps
a local mirror `RequestHandle` per in-flight request, updated from each
`step` RPC response — which is what makes crash isolation work: when
the child dies mid-decode, `evict_all()` cannot ask it anything, so it
returns the local mirrors and the Router resubmits them elsewhere,
bit-exact for greedy/seeded decodes.

Weights never travel over this socket. `swap_weights` ships only the
VERSION; the child loads that exact version from its own `WeightStore`
handle — the store (stale-writer-safe, sha256-verified) IS the weight
plane, and the RPC is just the control signal. Same for programs: a
new process warm-starts from the ProgramStore persistent tier and
never compiles (tier-1-guarded in test_fleet_proc).
"""
from __future__ import annotations

import hashlib
import json
import socket
import struct
import time
from typing import Any, Dict, List, Optional

from .. import observability as _obs
from ..observability import reqledger as _reqledger
from ..analysis.runtime import concurrency as _concurrency
from ..resilience.retry import (FatalError, TransientError,
                                register_transient)
from .api import FAILED, FINISHED, QUEUED, RUNNING, RequestHandle, \
    SamplingParams
from .engine import InferenceEngine

_LEN = struct.Struct('>I')
_DIGEST_LEN = 64
FRAME_MAX = 64 * 1024 * 1024   # a frame past this is corruption, not data


class RpcError(RuntimeError):
    """Base for RPC-layer failures that are not connection losses."""


class IncompleteFrameError(ConnectionError):
    """Peer closed (or the kernel gave up) mid-frame: a length prefix or
    payload arrived short. ConnectionError subclass → transient by type."""


class FrameChecksumError(ConnectionError):
    """Frame arrived complete but its sha256 does not match: torn or
    corrupted stream. The connection is untrustworthy from here on, so
    this is a connection-class (transient) failure, not a protocol bug."""


class RemoteTransientError(TransientError):
    """Child-side failure the child itself classified transient."""


class RemoteFatalError(FatalError):
    """Child-side failure classified fatal (poisons the failover chain)."""


# the error vocabulary a child may rehydrate by name on the client side:
# submit()-time validation must raise the SAME types remotely as locally
# (the Router catches ValueError from engine.submit, tenancy tests rely
# on TypeError for bad kwargs)
_REHYDRATE: Dict[str, type] = {
    'ValueError': ValueError,
    'TypeError': TypeError,
    'RuntimeError': RuntimeError,
    'KeyError': KeyError,
    'TimeoutError': TimeoutError,
}

register_transient(IncompleteFrameError)
register_transient(FrameChecksumError)


def _digest(payload: bytes) -> bytes:
    return hashlib.sha256(payload).hexdigest().encode('ascii')


def send_msg(sock: socket.socket, obj: Dict[str, Any]) -> int:
    """Serialize + frame + send one message; returns bytes on the wire."""
    payload = json.dumps(obj, separators=(',', ':')).encode('utf-8')
    frame = _LEN.pack(len(payload)) + _digest(payload) + payload
    sock.sendall(frame)
    return len(frame)


def _recv_exact(sock: socket.socket, n: int, what: str) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise IncompleteFrameError(
                f'incomplete frame: peer closed after {len(buf)}/{n} '
                f'bytes of {what}')
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Dict[str, Any]:
    """Receive one framed message; verifies length and sha256 before any
    byte of the payload is trusted (torn-frame rejection)."""
    header = _recv_exact(sock, _LEN.size, 'length prefix')
    (length,) = _LEN.unpack(header)
    if length > FRAME_MAX:
        raise FrameChecksumError(
            f'frame length {length} exceeds FRAME_MAX ({FRAME_MAX}): '
            f'corrupt length prefix')
    digest = _recv_exact(sock, _DIGEST_LEN, 'sha256 digest')
    payload = _recv_exact(sock, length, 'payload')
    if _digest(payload) != digest:
        raise FrameChecksumError(
            f'frame sha256 mismatch over {length} payload bytes')
    return json.loads(payload.decode('utf-8'))


def params_to_wire(params: SamplingParams) -> Dict[str, Any]:
    return {k: getattr(params, k) for k in SamplingParams.__slots__}


def params_from_wire(d: Dict[str, Any]) -> SamplingParams:
    return SamplingParams(**d)


def _rehydrate_error(err: Dict[str, Any]) -> BaseException:
    """Turn a child-side error descriptor back into a typed exception.
    Known builtins come back as themselves (submit validation); anything
    else becomes Remote{Transient,Fatal}Error per the CHILD's
    classification — the child ran `is_transient` over the live
    exception chain, which the wire cannot carry."""
    name = err.get('type', 'RuntimeError')
    msg = err.get('message', '')
    cls = _REHYDRATE.get(name)
    if cls is not None:
        return cls(msg)
    if err.get('transient'):
        return RemoteTransientError(f'{name}: {msg}')
    return RemoteFatalError(f'{name}: {msg}')


class RpcClient:
    """One AF_UNIX connection speaking the framed protocol, with per-call
    deadlines and call/error/bytes accounting."""

    def __init__(self, socket_path: str, *, connect_timeout_s: float = 10.0,
                 call_timeout_s: float = 30.0):
        self.socket_path = socket_path
        self.call_timeout_s = float(call_timeout_s)
        self._lock = _concurrency.RLock('RpcClient._lock')
        self._sock: Optional[socket.socket] = None
        self._connect_timeout_s = float(connect_timeout_s)

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def connect(self, deadline_s: Optional[float] = None):
        with self._lock:
            if self._sock is not None:
                return
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(deadline_s if deadline_s is not None
                            else self._connect_timeout_s)
            try:
                sock.connect(self.socket_path)
            except BaseException:
                sock.close()
                raise
            self._sock = sock

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    _obs.count_suppressed('rpc_close')
                self._sock = None

    def call(self, method: str, timeout_s: Optional[float] = None,
             **args) -> Dict[str, Any]:
        """One request/response round trip. Any connection-class failure
        closes the socket (the stream is unusable mid-frame) and
        propagates — the caller's failover logic owns recovery."""
        deadline = self.call_timeout_s if timeout_s is None else timeout_s
        with self._lock:
            if self._sock is None:
                self.connect()
            sock = self._sock
            sock.settimeout(deadline)
            if _obs.enabled():
                _obs.get_registry().counter(
                    'paddle_rpc_calls_total',
                    'replica RPC round trips by method',
                    ('method',)).labels(method=method).inc()
            try:
                sent = send_msg(sock, {'method': method, 'args': args})
                resp = recv_msg(sock)
            except (ConnectionError, OSError, TimeoutError):
                if _obs.enabled():
                    _obs.get_registry().counter(
                        'paddle_rpc_errors_total',
                        'replica RPC calls lost to connection failures',
                        ('method',)).labels(method=method).inc()
                self.close()
                raise
        if 'error' in resp:
            raise _rehydrate_error(resp['error'])
        if _obs.enabled():
            _obs.get_registry().counter(
                'paddle_rpc_bytes_total',
                'replica RPC bytes by direction', ('direction',)
            ).labels(direction='sent').inc(sent)
        return resp.get('result', {})


class _MirrorScheduler:
    """The two attributes the Router's load estimator reads off
    `replica.engine.scheduler`, served from the client-side mirrors
    (zero RPCs on the placement hot path)."""

    def __init__(self, owner: 'RemoteReplica'):
        self._owner = owner

    @property
    def queue_depth(self) -> int:
        return sum(1 for h in self._owner._handles.values()
                   if h.status == QUEUED)

    def pending(self) -> List[RequestHandle]:
        return [h for h in self._owner._handles.values()
                if h.status == QUEUED]


class RemoteReplica:
    """Engine-duck-typed client for one replica process.

    Router/Autoscaler integration points served LOCALLY (no RPC):
    `has_work`, `scheduler.queue_depth`, `scheduler.pending()`,
    `_slot_req`, `weight_version`, `prefill_chunk_tokens` — all are
    read every router step, and all are derivable from the mirrors the
    last `step` response refreshed. RPCs happen only where work
    happens: submit, step, drain, evict, swap, stats, healthz.
    """

    def __init__(self, socket_path: str, *, name: Optional[str] = None,
                 connect_timeout_s: float = 10.0,
                 call_timeout_s: float = 30.0,
                 supervisor=None):
        self._rpc = RpcClient(socket_path,
                              connect_timeout_s=connect_timeout_s,
                              call_timeout_s=call_timeout_s)
        self.name = name or socket_path
        self.socket_path = socket_path
        self.supervisor = supervisor
        self._lock = _concurrency.RLock('RemoteReplica._lock')
        # remote-rid -> local mirror handle, in submission order
        self._handles: Dict[int, RequestHandle] = {}
        # fake-slot -> RUNNING mirror (Replica.outstanding_tokens reads
        # `.params.max_new_tokens` and `.tokens` off the values)
        self._slot_req: Dict[int, RequestHandle] = {}
        self.scheduler = _MirrorScheduler(self)
        self.weight_version: Optional[int] = None
        self.prefill_chunk_tokens: Optional[int] = None
        self.num_slots: Optional[int] = None
        self.max_length: Optional[int] = None
        self.pid: Optional[int] = None
        self.process_uid: Optional[str] = None
        self._obs_scope: Optional[str] = None
        self._draining = False

    # -- lifecycle ---------------------------------------------------------
    def connect(self, deadline_s: Optional[float] = None) -> Dict[str, Any]:
        """Handshake: connect and pull the engine geometry the Router's
        estimators need (slots, lengths, live weight version)."""
        self._rpc.connect(deadline_s)
        info = self._rpc.call('hello')
        with self._lock:
            self.weight_version = info.get('weight_version')
            self.prefill_chunk_tokens = info.get('prefill_chunk_tokens')
            self.num_slots = info.get('num_slots')
            self.max_length = info.get('max_length')
            self.pid = info.get('pid')
            self.process_uid = info.get('uid')
        return info

    def close(self):
        self._rpc.close()

    # -- observability scope (Replica.__init__ assigns this) ---------------
    @property
    def obs_scope(self) -> Optional[str]:
        return self._obs_scope

    @obs_scope.setter
    def obs_scope(self, scope: Optional[str]):
        self._obs_scope = scope
        # best effort: the child tags ITS engine metrics/events with the
        # same scope so stitched fleet traces attribute per replica. A
        # dead child just misses the retag until respawn re-applies it.
        try:
            self._rpc.call('set_obs_scope', scope=scope)
        except (ConnectionError, OSError, TimeoutError):
            _obs.count_suppressed('rpc_set_obs_scope')

    # -- engine surface ----------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def has_work(self) -> bool:
        with self._lock:
            return any(not h.done for h in self._handles.values())

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               priority: Optional[int] = None,
               adapter_id: Optional[str] = None, **kwargs) -> RequestHandle:
        """Mirror of `InferenceEngine.submit`: validation errors raise
        here (rehydrated by type from the child), accepted requests get
        a LOCAL handle whose stream()/result() drive `self.step()`.
        `adapter_id` ships over the wire — a bank-less child rejects it
        with the same typed ValueError an in-process engine raises (and
        the Router's step-0 availability check keeps adapter traffic
        off process replicas entirely until child banks are wired)."""
        if params is None:
            params = SamplingParams(**kwargs)
        elif kwargs:
            raise TypeError('pass params= or keyword sampling args, '
                            'not both')
        toks = InferenceEngine._normalize_prompt(prompt)
        res = self._rpc.call('submit', prompt_tokens=toks,
                             params=params_to_wire(params),
                             priority=priority, adapter_id=adapter_id)
        h = RequestHandle(toks, params, engine=self)
        if priority is not None:
            h.priority = int(priority)
        h.adapter_id = adapter_id
        if _reqledger.enabled():
            # the PARENT keeps this request's ledger record (the child's
            # engine ships its own over the wire plane; the mirror's is
            # what the Router adopts and the client sees)
            rec = _reqledger.get_ledger().open_for(h)
            if rec is not None:
                rec.queue_enter(h._t_submit, 'priority_queued')
        rid = res.get('rid')
        with self._lock:
            self._handles[int(rid)] = h
        return h

    def step(self) -> int:
        """One decode-block step in the child; applies the per-request
        mirror updates from the response. A connection failure
        propagates (transient by type) so `Router.step` runs its normal
        evict-and-resubmit failover — crash isolation, same code path."""
        t0 = time.perf_counter()
        res = self._rpc.call('step')
        t1 = time.perf_counter()
        # ledger attribution uses PRE-update mirror statuses and runs
        # BEFORE _apply_updates: the round that produces a first token
        # must land in that request's TTFT sub-book (mark_first fires
        # inside _emit during the update apply). Each RUNNING mirror's
        # timeline tiles exactly: the parent-loop gap since its last
        # touch books as decode (the request was mid-decode, waiting
        # for its replica's turn), the framing surplus as
        # rpc_transport, the child's reported step wall as decode
        # (fair-share + engine-wall books ride note_round). QUEUED
        # mirrors stay in queue_wait.
        step_wall = float(res.get('step_wall_s') or 0.0)
        rpc_surplus = max((t1 - t0) - step_wall, 0.0)
        with self._lock:
            running = [h._ledger_rec for h in self._handles.values()
                       if h.status == RUNNING]
        t_round0 = t1 - step_wall
        for rec in running:
            if rec is None:
                continue
            gap = (t0 - rec._last_touch)
            if gap > 0.0:
                rec.add('decode', gap, now=t0)
            if rpc_surplus > 0.0:
                rec.add('rpc_transport', rpc_surplus,
                        now=min(t0 + rpc_surplus, t_round0))
        _reqledger.get_ledger().note_round(step_wall, running,
                                           'decode', now=t1)
        return self._apply_updates(res)

    def _apply_updates(self, res: Dict[str, Any]) -> int:
        now = time.perf_counter()
        with self._lock:
            for rid_s, upd in res.get('updates', {}).items():
                h = self._handles.get(int(rid_s))
                if h is None:
                    continue
                status = upd.get('status')
                if (h.status == QUEUED and h._ledger_rec is not None
                        and status in (RUNNING, FINISHED, FAILED)):
                    # first round the child reported it past the queue:
                    # the mirror's queue_wait ends here — BEFORE the
                    # token emit below fires mark_first, so the final
                    # queue interval still lands in the TTFT sub-book
                    h._ledger_rec.queue_exit(now)
                toks = upd.get('tokens', [])
                for tok in toks[len(h.tokens):]:
                    h._emit(tok, now)
                if upd.get('weight_version') is not None:
                    h.weight_version = upd['weight_version']
                if upd.get('adapter_version') is not None:
                    h.adapter_version = upd['adapter_version']
                if status == RUNNING and h.status == QUEUED:
                    h.status = RUNNING
                elif status == FINISHED and not h.done:
                    h._finish(now)
                elif status == FAILED and not h.done:
                    h._fail(_rehydrate_error(upd.get('error') or {}))
            self._refresh_slots()
        return int(res.get('progressed', 0))

    def _refresh_slots(self):
        # caller holds self._lock
        self._slot_req.clear()
        slot = 0
        for h in self._handles.values():
            if h.status == RUNNING:
                self._slot_req[slot] = h
                slot += 1

    def evict_all(self) -> List[RequestHandle]:
        """Failover hand-off. ALWAYS serves from the local mirrors — the
        caller is usually standing over a corpse, and the mirrors are
        exactly what the child had accepted. When the child is still
        alive (drain-triggered evictions), a best-effort RPC clears its
        side too so slots free for the next tenant of the socket."""
        with self._lock:
            orphans = [h for h in self._handles.values() if not h.done]
            self._handles.clear()
            self._slot_req.clear()
        try:
            self._rpc.call('evict_all', timeout_s=5.0)
        except (ConnectionError, OSError, TimeoutError):
            # the dead-child case: mirrors already harvested above
            _obs.count_suppressed('rpc_evict_dead')
        return orphans

    def begin_drain(self):
        """Cordon: flip the child draining AND mirror the scoped
        `draining` degraded state into THIS process — the Router's
        health gate reads the parent-side observability server, which
        cannot see into the child."""
        self._draining = True
        with self._lock:
            info = {'queued': self.scheduler.queue_depth,
                    'in_flight': len(self._slot_req)}
        _obs.note_degraded('draining', info, scope=self._obs_scope)
        try:
            self._rpc.call('begin_drain')
        except (ConnectionError, OSError, TimeoutError):
            _obs.count_suppressed('rpc_begin_drain')

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Drive the child's drain to completion. The RPC deadline wraps
        the child-side drain deadline with margin, so a hung child
        surfaces as a timeout here rather than a silent stall."""
        self._draining = True
        _obs.note_degraded('draining', {}, scope=self._obs_scope)
        deadline = 30.0 if deadline_s is None else float(deadline_s)
        res = self._rpc.call('drain', timeout_s=deadline + 10.0,
                             deadline_s=deadline)
        self._apply_updates(res)
        return bool(res.get('ok', False))

    def swap_weights(self, state=None, *, version: int, strict: bool = True):
        """Cross-process hot swap: ships ONLY the version number. The
        child loads that exact version from its own WeightStore handle
        (sha256-verified at read) — device arrays never serialize over
        the control socket. `state` is accepted for surface parity with
        the in-process engine and ignored: the store is authoritative."""
        res = self._rpc.call('swap_weights', timeout_s=120.0,
                             version=int(version), strict=bool(strict))
        with self._lock:
            self.weight_version = res.get('weight_version', int(version))
        return res.get('prev_version')

    def restore_weights(self, prev):
        """Rollback partner of swap_weights: `prev` is the version token
        swap_weights returned (the previous version number)."""
        if prev is None:
            raise RuntimeError('no previous weight version to restore')
        return self.swap_weights(version=int(prev))

    def healthz(self, deadline_s: float = 5.0) -> Dict[str, Any]:
        """Liveness probe: cheap by design (no engine lock in the child)
        so a heartbeat answers even mid-decode-block. SIGSTOPped or hung
        children time out here — the supervisor's hang detector."""
        return self._rpc.call('healthz', timeout_s=deadline_s)

    def stats(self) -> Dict[str, Any]:
        res = self._rpc.call('stats')
        res['remote'] = {'socket': self.socket_path, 'pid': self.pid,
                         'uid': self.process_uid}
        return res

    def generate_many(self, prompts, params=None) -> List[RequestHandle]:
        handles = [self.submit(p, params=params) for p in prompts]
        while any(not h.done for h in handles):
            self.step()
        return handles

    def retire(self, deadline_s: float = 30.0):
        """Tear the PROCESS down: through the supervisor when one owns
        this replica (SIGTERM → graceful drain → reap → pidfile/socket
        cleanup), else a direct shutdown RPC. The Autoscaler calls this
        after `remove_replica` so scale-down retires real processes."""
        if self.supervisor is not None:
            self.supervisor.retire(self.name, deadline_s=deadline_s)
            return
        try:
            self._rpc.call('shutdown', timeout_s=deadline_s)
        except (ConnectionError, OSError, TimeoutError):
            _obs.count_suppressed('rpc_shutdown')
        self.close()

    def __repr__(self):
        return (f'RemoteReplica(name={self.name!r}, pid={self.pid}, '
                f'socket={self.socket_path!r})')
