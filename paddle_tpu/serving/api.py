"""Serving user API: per-request sampling params + request handles.

The continuous-batching engine (engine.py) is iteration-level: requests
enter and leave the running batch between decode steps (Orca, Yu et al.
OSDI'22), so the unit of user interaction is a `RequestHandle` — a
live view of one request's tokens/status that the caller can poll,
`stream()` per token, or block on with `result()`. `SamplingParams` is
plain data; the engine lowers it into per-slot arrays so ONE compiled
decode step serves heterogeneous requests.
"""
from __future__ import annotations

import itertools
import time
from typing import Any, List, Optional

GREEDY = 'greedy_search'
SAMPLING = 'sampling'

# priority classes (lower = more urgent). The scheduler orders admission
# by (priority, FCFS-within-class); the router maps tenants onto these.
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2
PRIORITY_NAMES = {'high': PRIORITY_HIGH, 'normal': PRIORITY_NORMAL,
                  'low': PRIORITY_LOW}

_request_ids = itertools.count()


class SamplingParams:
    """Per-request decode configuration (upstream analogue: the scalar
    kwargs of `GenerationMixin.generate`, here carried per request so a
    mixed batch shares one compiled step).

    - ``strategy``: 'greedy_search' (raw argmax — bit-identical to
      `generate(decode_strategy='greedy_search')`) or 'sampling'.
    - ``temperature`` / ``top_k`` / ``top_p``: sampling filters;
      ``top_k=0`` and ``top_p=1.0`` disable the respective filter.
    - ``eos_token_id``: emitting this token finishes the request (the
      eos itself is emitted, matching `generate`); ``None`` defers to
      the engine default, ``-1`` disables early stop.
    - ``seed``: per-request PRNG seed for 'sampling' (same seed + same
      prompt => same tokens, independent of batch neighbours).
    """

    __slots__ = ('max_new_tokens', 'strategy', 'temperature', 'top_k',
                 'top_p', 'eos_token_id', 'seed')

    def __init__(self, max_new_tokens: int = 16, strategy: str = GREEDY,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0, eos_token_id: Optional[int] = None,
                 seed: Optional[int] = None):
        if strategy not in (GREEDY, SAMPLING):
            raise ValueError(f'unknown strategy {strategy!r}')
        if max_new_tokens < 1:
            raise ValueError('max_new_tokens must be >= 1')
        self.max_new_tokens = int(max_new_tokens)
        self.strategy = strategy
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = eos_token_id
        self.seed = seed

    def __repr__(self):
        return (f'SamplingParams(max_new_tokens={self.max_new_tokens}, '
                f'strategy={self.strategy!r}, temperature={self.temperature},'
                f' top_k={self.top_k}, top_p={self.top_p}, '
                f'eos_token_id={self.eos_token_id}, seed={self.seed})')


# request lifecycle states
QUEUED = 'QUEUED'
RUNNING = 'RUNNING'
FINISHED = 'FINISHED'
FAILED = 'FAILED'


class RequestHandle:
    """Live view of one submitted request.

    ``tokens`` grows as the engine decodes; ``status`` moves
    QUEUED -> RUNNING -> FINISHED (or FAILED, carrying ``error`` — a
    request-level failure never kills the engine). Latency marks:
    ``ttft`` (submit -> first token) and ``tpot`` (mean inter-token
    time after the first) are available once the request finishes.

    ``weight_version`` is stamped at ADMISSION with the engine's live
    weight version — and because a hot swap only lands on a drained
    replica, every token of the response was decoded under that single
    version (the no-mixed-version-within-a-request guarantee the
    hotswap tests assert).
    """

    def __init__(self, prompt_tokens: List[int], params: SamplingParams,
                 engine=None):
        self.request_id = next(_request_ids)
        self.prompt_tokens = list(prompt_tokens)
        self.params = params
        self.priority = PRIORITY_NORMAL   # scheduler admission class
        self.tokens: List[int] = []
        self.status = QUEUED
        self.error: Optional[BaseException] = None
        self._engine = engine
        self._t_submit = time.perf_counter()
        self._t_first: Optional[float] = None
        self._t_done: Optional[float] = None
        # `request_id` doubles as the trace id: the engine threads it
        # through the serving.queue/prefill/decode_round spans and the
        # serving_request_failed event, so one request's lifecycle can
        # be followed in /trace and flight-recorder bundles
        self._queue_span = None
        # prefix-cache attachment: the node this request was admitted
        # off (pinned until retirement) and how many prompt tokens its
        # copied KV covered
        self._prefix_node = None
        self._prefix_len = 0
        # the weight version this request decodes under (stamped at
        # admission; None while still queued)
        self.weight_version: Optional[int] = None
        # multi-tenant adapter serving: the LoRA adapter this request
        # decodes under (None = base model), the adapter VERSION pinned
        # at admission (the whole response decodes under it — publish
        # never touches a pinned slot), and the engine-side bank pin
        self.adapter_id: Optional[str] = None
        self.adapter_version: Optional[int] = None
        self._adapter_pin: Optional[int] = None
        # per-request latency ledger record (observability.reqledger);
        # None when the ledger is disabled. Owned by whatever thread
        # drives this handle (engine loop / router / mirror updater).
        self._ledger_rec = None

    @property
    def trace_id(self) -> int:
        """The id threaded through this request's spans/events in the
        observability trace view."""
        return self.request_id

    # -- engine-side transitions -------------------------------------------
    def _emit(self, token: int, now: float):
        if self._t_first is None:
            self._t_first = now
            if self._ledger_rec is not None:
                self._ledger_rec.mark_first(now)
        self.tokens.append(int(token))

    def _finish(self, now: Optional[float] = None):
        self.status = FINISHED
        self._t_done = time.perf_counter() if now is None else now
        if self._ledger_rec is not None:
            from ..observability import reqledger as _reqledger
            _reqledger.get_ledger().finalize(self, now=self._t_done,
                                             outcome='completed')

    def _fail(self, exc: BaseException):
        self.status = FAILED
        self.error = exc
        self._t_done = time.perf_counter()
        if self._ledger_rec is not None:
            from ..observability import reqledger as _reqledger
            _reqledger.get_ledger().finalize(self, now=self._t_done,
                                             outcome='failed')

    # -- user-side views ---------------------------------------------------
    @property
    def done(self) -> bool:
        return self.status in (FINISHED, FAILED)

    @property
    def ttft(self) -> Optional[float]:
        """Seconds from submit to the first generated token."""
        if self._t_first is None:
            return None
        return self._t_first - self._t_submit

    @property
    def tpot(self) -> Optional[float]:
        """Mean seconds per output token after the first."""
        if self._t_done is None or self._t_first is None \
                or len(self.tokens) < 2:
            return None
        return (self._t_done - self._t_first) / (len(self.tokens) - 1)

    def stream(self):
        """Per-token iterator: drives the engine until this request is
        done, yielding each generated token as it lands. Re-entrant with
        other handles' streams (each step advances every running
        request)."""
        if self._engine is None:
            raise RuntimeError('handle is not bound to an engine')
        cursor = 0
        while True:
            while cursor < len(self.tokens):
                yield self.tokens[cursor]
                cursor += 1
            if self.done:
                if self.status == FAILED:
                    raise self.error
                return
            self._engine.step()

    def result(self) -> List[int]:
        """Block (drive the engine) until done; returns the token list.
        Raises the request's error if it FAILED."""
        for _ in self.stream():
            pass
        return self.tokens

    def __repr__(self):
        return (f'RequestHandle(id={self.request_id}, status={self.status}, '
                f'prompt_len={len(self.prompt_tokens)}, '
                f'tokens={len(self.tokens)})')
