"""Continuous-batching inference engine over a slot-pooled KV cache.

`GenerationMixin.generate()` is batch-synchronous: the whole batch is
admitted together, decodes in lock-step, and every sequence waits for
the slowest one. This engine is the iteration-level alternative (Orca,
Yu et al. OSDI'22): a fixed pool of KV slots (kv_pool.SlotPool), an
FCFS scheduler that admits queued requests into freed slots BETWEEN
decode steps (scheduler.FCFSScheduler), and ONE compiled decode step
that advances every occupied slot a block of tokens at a time with
per-slot position offsets, an active-slot mask, and per-slot sampling
params carried as arrays — so heterogeneous requests (different prompt
lengths, token budgets, temperatures, eos ids) share a single XLA
program and admission/retirement never recompiles anything.

Compiled-program inventory (asserted by the zero-recompile tests):
- one decode-block step (shapes fixed by num_slots/max_length/block),
- one prefill program per length bucket (right-padded prompts; pad KV
  lands above the live position where the slot-causal mask hides it
  until the slot's own decode overwrites it — the stale-slot argument
  speculative decoding already relies on),
- the slot-pool writer.

Greedy requests take the raw argmax exactly like `generate()`, so their
outputs are token-for-token identical to a per-request generate() call
(the bench.py `serving` phase guards this bit-for-bit).

Resilience: host<->device transfers ride `resilience.call_with_retry`
(transient blips retried with backoff); any prefill/transfer failure is
a REQUEST-level error — the handle turns FAILED, the slot frees, and
the engine keeps serving everyone else.
"""
from __future__ import annotations

import collections
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..jit import functional_state
from ..nlp.generation import _NEG_INF, cached_forward
from ..resilience import RetryPolicy, call_with_retry
from ..tensor import Tensor
from .api import GREEDY, RUNNING, RequestHandle, SamplingParams
from .kv_pool import SlotPool
from .scheduler import FCFSScheduler

# occupancy is a ratio; the latency-shaped default buckets are wrong here
_OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def _to_device(x):
    """Host->device staging of prompts (module-level so fault-injection
    tests can patch it; production call sites wrap it in retry)."""
    return jnp.asarray(x)


def _from_device(x):
    """Device->host fetch of sampled tokens (patchable, see _to_device)."""
    return np.asarray(x)


def sample_rows(logits, temp, topk, topp, greedy, keys, steps):
    """Vectorized per-row sampling over a [N, V] logits slab with PER-ROW
    params (arrays, not static config — one compiled program serves every
    request mix). Greedy rows take the raw argmax — bit-identical to
    `_next_token`'s greedy path — so a greedy request's tokens never
    depend on its batch neighbours. Sampling rows apply temperature, then
    top-k, then top-p (the `_process_logits` order) and draw
    categorically with their own folded key."""
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def do_sample(_):
        scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
        # per-row top-k: k <= 0 or >= v disables (mirrors _process_logits)
        srt = jax.lax.top_k(scaled, v)[0]                   # descending
        k_eff = jnp.where((topk > 0) & (topk < v), topk,
                          v).astype(jnp.int32)
        kth = jnp.take_along_axis(srt, k_eff[:, None] - 1, axis=-1)
        x = jnp.where(scaled < kth, _NEG_INF, scaled)
        # per-row top-p over the already-top-k-filtered slab
        srt_p = jax.lax.top_k(x, v)[0]
        probs = jax.nn.softmax(srt_p, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum((cum - probs) < topp[:, None], axis=-1) - 1
        cutoff = jnp.take_along_axis(
            srt_p, jnp.clip(cutoff_idx, 0, v - 1)[:, None], axis=-1)
        x = jnp.where((topp[:, None] < 1.0) & (x < cutoff), _NEG_INF, x)
        keys_f = jax.vmap(jax.random.fold_in)(keys, steps)
        return jax.vmap(jax.random.categorical)(keys_f,
                                                x).astype(jnp.int32)

    # all-greedy batches (the common serving mix) skip the two full-vocab
    # sorts + RNG entirely — lax.cond picks the branch at RUN time, so
    # the mix can change step to step without recompiling
    sampled = jax.lax.cond(jnp.all(greedy), lambda _: greedy_tok,
                           do_sample, None)
    return jnp.where(greedy, greedy_tok, sampled)


class InferenceEngine:
    """Single-host continuous-batching engine around one causal-LM.

    Args:
        model: any `GenerationMixin` model honoring the `init_cache` /
            cached-forward contract (weights are snapshotted at
            construction). Put the model in eval() yourself if it holds
            dropout state; the engine forces eval.
        num_slots: KV slots = max concurrently decoding requests.
        max_length: per-slot cache length; every request needs
            prompt_len + max_new_tokens <= max_length.
        decode_block: tokens decoded per compiled step (device-side
            lax.scan). Larger blocks amortize host dispatch; a request
            finishing mid-block wastes at most block-1 sub-steps.
        buckets: prefill length buckets (default: powers of two).
        max_prefill_tokens: per-iteration prefill budget (scheduler).
        eos_token_id: default eos (-1 = never); per-request params win.
        retry_policy: resilience.RetryPolicy for host<->device
            transfers (default: flag-configured policy).

    Not thread-safe: one engine is one event loop; drive it with
    `step()`, `run()`, `stream()`, or `generate_many()`.
    """

    def __init__(self, model, num_slots: int = 8, max_length: int = 256,
                 decode_block: int = 4,
                 buckets: Optional[Sequence[int]] = None,
                 max_prefill_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 dtype=None, retry_policy: Optional[RetryPolicy] = None,
                 max_wait_s: Optional[float] = None):
        cfg = getattr(model, 'config', None)
        max_pos = getattr(cfg, 'max_position_embeddings', None)
        if max_pos is not None and max_length > max_pos:
            raise ValueError(
                f'max_length {max_length} exceeds the model\'s '
                f'max_position_embeddings {max_pos}')
        if decode_block < 1:
            raise ValueError('decode_block must be >= 1')
        model.eval()
        self.model = model
        self._params, self._frozen, self._buffers = functional_state(model)
        self.eos_token_id = int(
            getattr(cfg, 'eos_token_id', -1) if eos_token_id is None
            else eos_token_id)
        self.decode_block = int(decode_block)
        self.pool = SlotPool(model, num_slots, max_length, dtype, buckets)
        self.scheduler = FCFSScheduler(max_prefill_tokens,
                                       max_wait_s=max_wait_s)
        self._retry = retry_policy or RetryPolicy()
        self._draining = False
        self._drain_deadline_s: Optional[float] = None
        self._preempt = None
        # observability scope for degraded-state notes: None = the whole
        # process (single-engine deployments); the router tags each
        # replica's engine 'replica:N' so /healthz and placement can
        # tell WHICH replica is draining
        self.obs_scope: Optional[str] = None

        n = self.pool.num_slots
        # per-slot decode state + sampling params, host-authoritative
        # (tiny arrays re-staged every step; the KV pool stays on device)
        self._tok = np.zeros(n, np.int32)       # pending (last emitted)
        self._pos = np.zeros(n, np.int32)       # its cache slot/position
        self._steps = np.zeros(n, np.int32)     # per-request sample index
        self._active = np.zeros(n, bool)
        self._temp = np.ones(n, np.float32)
        self._topk = np.zeros(n, np.int32)
        self._topp = np.ones(n, np.float32)
        self._greedy = np.ones(n, bool)
        self._keys = np.zeros((n, 2), np.uint32)
        self._slot_req: dict = {}               # slot -> RequestHandle

        self._trace_counts = collections.Counter()
        self._counts = collections.Counter()
        # enrolled in the program store: per-program FLOPs/bytes/peak
        # attribution for the decode block and each prefill bucket, off
        # the same single compile each program costs anyway — and, with
        # a persistent store, a cold replica LOADS these instead of
        # compiling. The statics cover what the avals cannot: the model
        # body/config and the engine geometry (decode_block is a scan
        # length, invisible in any input aval). Sibling replicas over
        # the same model produce identical keys, so N replicas compile
        # (or load) each program once.
        from .. import programs as _programs
        store = _programs.get_store()
        engine_statics = {
            'model': type(model).__qualname__,
            'model_src': _programs.code_token(type(model)),
            'config': _programs.describe_statics(cfg),
            'num_slots': self.pool.num_slots,
            'max_length': self.pool.max_length,
            'decode_block': self.decode_block,
        }
        self._decode_jit = store.wrap_jit(
            jax.jit(self._decode_block_fn), name='serving.decode_block',
            kind='serving', statics=engine_statics)
        self._prefill_jit = store.wrap_jit(   # 1 trace per bucket
            jax.jit(self._prefill_fn),
            name_fn=lambda args: f'serving.prefill_{args[5].shape[1]}',
            kind='serving', statics=engine_statics)
        self._init_metrics()
        if store.persistent:
            # cold-replica warm start: materialize persisted serving
            # executables BEFORE the first request (holds the
            # ref-counted /healthz `warming` state while loading);
            # idempotent, so sibling replicas after the first skip it
            self.preload_programs()

    def preload_programs(self) -> dict:
        """Bulk-load this engine's persisted executables (decode block,
        prefill buckets) from the program store into memory, so the
        first submitted request decodes instead of compiling. No-op
        without a persistent store."""
        from .. import programs as _programs
        return _programs.get_store().preload(match='serving.')

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------
    def _init_metrics(self):
        reg = _obs.get_registry()
        self._m_requests = reg.counter(
            'paddle_serving_requests_total',
            'serving requests by lifecycle event', ('status',))
        self._m_tokens = reg.counter(
            'paddle_serving_tokens_total', 'generated tokens')
        self._m_prefills = reg.counter(
            'paddle_serving_prefills_total', 'prefills by length bucket',
            ('bucket',))
        self._m_prefill_tokens = reg.counter(
            'paddle_serving_prefill_tokens_total',
            'real (unpadded) prompt tokens prefilled')
        self._m_decode_steps = reg.counter(
            'paddle_serving_decode_steps_total',
            'single-token decode sub-steps executed')
        self._m_rounds = reg.counter(
            'paddle_serving_decode_rounds_total',
            'compiled decode-block invocations')
        self._m_slots = reg.gauge(
            'paddle_serving_slots', 'KV slot capacity')
        self._m_active = reg.gauge(
            'paddle_serving_active_slots', 'slots currently decoding')
        self._m_occupancy = reg.histogram(
            'paddle_serving_slot_occupancy',
            'occupied-slot fraction per decode round',
            buckets=_OCCUPANCY_BUCKETS)
        self._m_ttft = reg.histogram(
            'paddle_serving_ttft_seconds',
            'submit -> first token latency')
        self._m_tpot = reg.histogram(
            'paddle_serving_tpot_seconds',
            'mean inter-token latency per finished request')
        if _obs.enabled():
            self._m_slots.set(self.pool.num_slots)

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _decode_block_fn(self, params, frozen, buffers, pool, tok, pos,
                         steps, active, temp, topk, topp, greedy, keys):
        """One compiled program: `decode_block` single-token steps over
        ALL slots (lax.scan), per-slot positions/masks/sampling."""
        self._trace_counts['decode_step'] += 1   # python-level trace count
        fwd = cached_forward(self.model, params, frozen, buffers)
        max_len = self.pool.max_length
        k_slot = jnp.arange(max_len, dtype=jnp.int32)

        def sub(carry, _):
            tok, pos, steps, pool = carry
            # pending token writes its KV at slot `pos` and attends to
            # every slot <= pos; freed/stale rows above are masked out
            mask = (k_slot[None, :] <= pos[:, None])[:, None, None, :]
            logits, pool = fwd(tok[:, None], pool, pos, pos, mask)
            nxt = sample_rows(logits[:, -1], temp, topk, topp, greedy,
                              keys, steps)
            nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
            pos = jnp.minimum(pos + 1, jnp.int32(max_len - 1))
            return (nxt, pos, steps + 1, pool), nxt

        (tok, pos, steps, pool), toks = jax.lax.scan(
            sub, (tok, pos, steps, pool), None, length=self.decode_block)
        return jnp.transpose(toks), pool         # [num_slots, block]

    def _prefill_fn(self, params, frozen, buffers, pool, slot, ids):
        """Prefill ONE request (batch-1, right-padded to its bucket) and
        scatter the resulting KV slab into the pool row `slot`. KV-only
        and fully async: no logits leave the device — the request's
        FIRST token falls out of the next decode block, which re-forwards
        the last prompt token at position s-1 (an identical overwrite of
        its KV slot) and samples from the same last-position logits the
        prefill computed. One compile per bucket (ids.shape), everything
        else traced."""
        self._trace_counts[f'prefill_{ids.shape[1]}'] += 1
        fwd = cached_forward(self.model, params, frozen, buffers)
        slab = jax.tree_util.tree_map(
            lambda c: jnp.zeros((1,) + c.shape[1:], c.dtype), pool)
        _, slab = fwd(ids, slab, jnp.int32(0), jnp.int32(0), None)
        return jax.tree_util.tree_map(
            lambda c, s: jax.lax.dynamic_update_slice(
                c, s.astype(c.dtype), (slot,) + (0,) * (c.ndim - 1)),
            pool, slab)

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    @staticmethod
    def _normalize_prompt(prompt) -> List[int]:
        if isinstance(prompt, Tensor):
            prompt = prompt.numpy()
        arr = np.asarray(prompt)
        if arr.ndim == 2 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.ndim != 1 or arr.size < 1:
            raise ValueError(
                f'prompt must be a non-empty 1-D token sequence, got '
                f'shape {arr.shape}')
        return [int(t) for t in arr]

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               priority: Optional[int] = None, **kwargs) -> RequestHandle:
        """Queue one request; returns its live handle. Validation errors
        raise HERE (caller bug); runtime failures mark the handle
        FAILED instead. `priority` sets the scheduler admission class
        (PRIORITY_HIGH/NORMAL/LOW; default NORMAL)."""
        if params is None:
            params = SamplingParams(**kwargs)
        elif kwargs:
            raise TypeError('pass params= or keyword sampling args, '
                            'not both')
        self._check_drain()
        if self._draining:
            self._counts['rejected'] += 1
            if _obs.enabled():
                self._m_requests.labels(status='rejected').inc()
            raise RuntimeError(
                'engine is draining (preemption signal received): not '
                'admitting new requests')
        toks = self._normalize_prompt(prompt)
        self.pool.bucket_for(len(toks))   # raises when no bucket fits
        if len(toks) + params.max_new_tokens > self.pool.max_length:
            raise ValueError(
                f'prompt ({len(toks)}) + max_new_tokens '
                f'({params.max_new_tokens}) exceeds the slot length '
                f'({self.pool.max_length})')
        h = RequestHandle(toks, params, engine=self)
        if priority is not None:
            h.priority = int(priority)
        h._eos = int(self.eos_token_id if params.eos_token_id is None
                     else params.eos_token_id)
        self._counts['submitted'] += 1
        if _obs.enabled():
            self._m_requests.labels(status='submitted').inc()
            # queue span: begins now, ends at admission — the request's
            # trace id (request_id) threads every span/event it touches
            h._queue_span = _obs.Span('serving.queue',
                                      request_id=h.request_id).begin()
        self.scheduler.submit(h)
        return h

    # ------------------------------------------------------------------
    # graceful drain (preemption)
    # ------------------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    def enable_graceful_drain(self, handler=None, deadline_s: float = 30.0,
                              signals=None):
        """Wire a `resilience.PreemptionHandler` into the engine: on
        SIGTERM (the pod eviction grace window) the engine stops
        admitting NEW submissions, finishes every already-accepted
        request — queued and in-flight — under `deadline_s`, flips
        /healthz to a 503 `draining` state so routers stop sending
        traffic, and `run()`/`drain()` return so the caller can exit 0.
        Pass a ready handler to share one across subsystems; returns
        the handler in use."""
        if handler is None:
            import signal as _signal
            from ..resilience.preemption import PreemptionHandler
            handler = PreemptionHandler(
                signals=signals or (_signal.SIGTERM,)).install()
        self._preempt = handler
        self._drain_deadline_s = float(deadline_s)
        return handler

    def _check_drain(self):
        if (not self._draining and self._preempt is not None
                and self._preempt.requested):
            self._begin_drain()

    def begin_drain(self):
        """Stop admitting new submissions NOW, without driving decode:
        the non-blocking half of `drain()`. The router uses this to take
        one replica out of rotation (its scoped `draining` state excludes
        it from placement) while router steps keep finishing its
        accepted requests."""
        self._begin_drain()

    def _begin_drain(self):
        if self._draining:
            return
        self._draining = True
        self._drain_t0 = time.monotonic()
        info = {'queued': self.scheduler.queue_depth,
                'in_flight': len(self._slot_req)}
        # 503 from here on: the replica is leaving the pool
        _obs.note_degraded('draining', info, scope=self.obs_scope)
        _obs.emit('serving_drain_begin', **info)

    def _fail_remaining(self, exc: BaseException):
        for h in self.scheduler.drain():
            h._fail(exc)
            self._counts['failed'] += 1
            if _obs.enabled():
                self._m_requests.labels(status='failed').inc()
        for slot, h in list(self._slot_req.items()):
            del self._slot_req[slot]
            self._active[slot] = False
            self.pool.free(slot)
            h._fail(exc)
            self._counts['failed'] += 1
            if _obs.enabled():
                self._m_requests.labels(status='failed').inc()
        if _obs.enabled():
            self._m_active.set(self.pool.used_count)

    def evict_all(self) -> List[RequestHandle]:
        """Pull every accepted request — queued AND in-flight — out of
        the engine WITHOUT failing it, returning the handles in
        submission order (queued first is irrelevant to the router; it
        re-sorts). This is the failover hand-off: when the router
        declares this replica dead, the orphans are resubmitted
        elsewhere, so their handles must leave this engine untouched.
        Slots free, actives clear; the engine itself stays serviceable
        (a transient device blip doesn't scrap the pool)."""
        out = self.scheduler.drain()
        for slot, h in list(self._slot_req.items()):
            del self._slot_req[slot]
            self._active[slot] = False
            self.pool.free(slot)
            out.append(h)
        for h in out:
            if h._queue_span is not None:   # don't leak open queue spans
                h._queue_span.end()
                h._queue_span = None
        if _obs.enabled():
            self._m_active.set(self.pool.used_count)
        return out

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Stop admitting new submissions and drive decode until every
        accepted request (queued + in-flight) finishes, bounded by the
        deadline. Past the deadline the stragglers FAIL (handles carry
        the TimeoutError) rather than being silently dropped. Returns
        True when everything completed in time. /healthz stays
        `draining` afterwards — the process is expected to exit."""
        if deadline_s is None:
            deadline_s = self._drain_deadline_s
        self._begin_drain()
        timed_out = False
        while self.has_work:
            if deadline_s is not None and \
                    time.monotonic() - self._drain_t0 > deadline_s:
                timed_out = True
                self._fail_remaining(TimeoutError(
                    f'drain deadline {deadline_s}s exceeded'))
                break
            self.step()
        _obs.emit('serving_drain_complete',
                  timed_out=timed_out,
                  seconds=round(time.monotonic() - self._drain_t0, 3))
        return not timed_out

    # ------------------------------------------------------------------
    # the iteration loop
    # ------------------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return bool(self._slot_req) or self.scheduler.queue_depth > 0

    def step(self) -> int:
        """ONE scheduler iteration: admit queued requests into free
        slots, then advance every occupied slot one decode block.
        Returns the number of requests that progressed."""
        self._check_drain()
        self._admit()
        if not self._slot_req:
            return 0
        with _obs.span('serving.decode_round',
                       slots=len(self._slot_req),
                       requests=[h.request_id
                                 for h in self._slot_req.values()]):
            toks_dev, new_pool = self._decode_jit(
                self._params, self._frozen, self._buffers, self.pool.cache,
                self._tok, self._pos, self._steps, self._active, self._temp,
                self._topk, self._topp, self._greedy, self._keys)
            self.pool.cache = new_pool
            toks = call_with_retry(_from_device, toks_dev,
                                   policy=self._retry, site='serving.d2h')
        _obs.note_progress('decode')   # /healthz decode liveness beat
        now = time.perf_counter()
        n = len(self._slot_req)
        self._counts['decode_rounds'] += 1
        self._counts['decode_steps'] += self.decode_block
        if _obs.enabled():
            self._m_rounds.inc()
            self._m_decode_steps.inc(self.decode_block)
            self._m_occupancy.observe(self.pool.occupancy)
            self._m_tokens.inc(0)   # ensure the family exists even idle
        for slot, h in list(self._slot_req.items()):
            done = False
            emitted = 0
            first = not h.tokens
            for j in range(self.decode_block):
                t = int(toks[slot, j])
                h._emit(t, now)
                emitted += 1
                if (len(h.tokens) >= h.params.max_new_tokens
                        or t == h._eos):
                    done = True
                    break
            self._counts['tokens'] += emitted
            if _obs.enabled():
                self._m_tokens.inc(emitted)
                if first:
                    self._m_ttft.observe(h.ttft)
            if done:
                self._retire(slot, h, now)
            else:
                self._tok[slot] = toks[slot, self.decode_block - 1]
                self._pos[slot] += self.decode_block
                self._steps[slot] += self.decode_block
        return n

    def run(self) -> int:
        """Drive until queue and slots drain; returns decode rounds."""
        rounds = 0
        while self.has_work:
            self.step()
            rounds += 1
        return rounds

    def stream(self, handle: RequestHandle):
        """Per-token iterator for one request (see RequestHandle.stream)."""
        return handle.stream()

    def generate_many(self, prompts, params=None) -> List[RequestHandle]:
        """Submit a batch of prompts and drain the engine — the
        continuous-batching replacement for a sequential `generate()`
        loop on mixed-length workloads. `params` is one SamplingParams
        for all, or a per-prompt sequence."""
        if params is None or isinstance(params, SamplingParams):
            params = [params or SamplingParams()] * len(prompts)
        if len(params) != len(prompts):
            raise ValueError('one SamplingParams per prompt')
        handles = [self.submit(p, sp) for p, sp in zip(prompts, params)]
        self.run()
        return handles

    # ------------------------------------------------------------------
    # admission / retirement
    # ------------------------------------------------------------------
    def _admit(self):
        for h in self.scheduler.admissible(self.pool.free_count,
                                           self.pool.bucket_for):
            slot = self.pool.alloc()
            try:
                self._prefill_into(slot, h)
            except Exception as exc:
                # REQUEST-level failure: free the slot, fail the handle,
                # keep the engine serving everyone else
                self.pool.free(slot)
                h._fail(exc)
                self._counts['failed'] += 1
                if _obs.enabled():
                    self._m_requests.labels(status='failed').inc()
                    _obs.emit('serving_request_failed',
                              request_id=h.request_id,
                              error=type(exc).__name__)
        if _obs.enabled():
            self._m_active.set(self.pool.used_count)

    def _prefill_into(self, slot: int, h: RequestHandle):
        p = h.params
        s = len(h.prompt_tokens)
        bucket = self.pool.bucket_for(s)
        if h._queue_span is not None:
            h._queue_span.end()   # admission closes the queue span
            h._queue_span = None
        with _obs.span('serving.prefill', request_id=h.request_id,
                       bucket=bucket, slot=slot, prompt_len=s):
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :s] = h.prompt_tokens
            ids_dev = call_with_retry(_to_device, ids, policy=self._retry,
                                      site='serving.h2d')
            greedy = p.strategy == GREEDY
            key = (np.zeros(2, np.uint32) if greedy else np.asarray(
                jax.random.PRNGKey(h.request_id if p.seed is None
                                   else p.seed), np.uint32))
            self.pool.cache = self._prefill_jit(
                self._params, self._frozen, self._buffers, self.pool.cache,
                jnp.int32(slot), ids_dev)
        h.status = RUNNING
        self._counts['prefills'] += 1
        self._counts['prefill_tokens'] += s
        if _obs.enabled():
            self._m_prefills.labels(bucket=bucket).inc()
            self._m_prefill_tokens.inc(s)
        # pending = the LAST prompt token at position s-1: the next decode
        # block re-forwards it (identical KV overwrite) and its sampled
        # output is the request's first generated token
        self._tok[slot] = h.prompt_tokens[-1]
        self._pos[slot] = s - 1
        self._steps[slot] = 0
        self._active[slot] = True
        self._temp[slot] = p.temperature
        self._topk[slot] = p.top_k
        self._topp[slot] = p.top_p
        self._greedy[slot] = greedy
        self._keys[slot] = key
        self._slot_req[slot] = h

    def _retire(self, slot: int, h: RequestHandle, now: float):
        h._finish(now)
        del self._slot_req[slot]
        self._active[slot] = False
        self.pool.free(slot)
        self._counts['completed'] += 1
        if _obs.enabled():
            self._m_requests.labels(status='completed').inc()
            self._m_active.set(self.pool.used_count)
            tpot = h.tpot
            if tpot is not None:
                self._m_tpot.observe(tpot)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Host-side counters + compile-trace counts (the zero-recompile
        assertions read `traces`: after warmup it must stop growing
        across admissions)."""
        return {
            'submitted': self._counts['submitted'],
            'completed': self._counts['completed'],
            'failed': self._counts['failed'],
            'tokens': self._counts['tokens'],
            'prefills': self._counts['prefills'],
            'prefill_tokens': self._counts['prefill_tokens'],
            'decode_rounds': self._counts['decode_rounds'],
            'decode_steps': self._counts['decode_steps'],
            'queue_depth': self.scheduler.queue_depth,
            'active_slots': self.pool.used_count,
            'traces': dict(self._trace_counts),
            'pool': self.pool.stats(),
        }

    def reset_stats(self):
        """Zero the host-side counters (trace counts survive — they
        track compiles, which persist in the jit caches)."""
        self._counts.clear()
