"""Continuous-batching inference engine over a slot-pooled KV cache.

`GenerationMixin.generate()` is batch-synchronous: the whole batch is
admitted together, decodes in lock-step, and every sequence waits for
the slowest one. This engine is the iteration-level alternative (Orca,
Yu et al. OSDI'22): a fixed pool of KV slots (kv_pool.SlotPool), an
FCFS scheduler that admits queued requests into freed slots BETWEEN
decode steps (scheduler.FCFSScheduler), and ONE compiled decode step
that advances every occupied slot a block of tokens at a time with
per-slot position offsets, an active-slot mask, and per-slot sampling
params carried as arrays — so heterogeneous requests (different prompt
lengths, token budgets, temperatures, eos ids) share a single XLA
program and admission/retirement never recompiles anything.

Compiled-program inventory (asserted by the zero-recompile tests):
- one decode-block step (shapes fixed by num_slots/max_length/block),
- one prefill program per length bucket (right-padded prompts; pad KV
  lands above the live position where the slot-causal mask hides it
  until the slot's own decode overwrites it — the stale-slot argument
  speculative decoding already relies on),
and, when the latency stack is enabled (ISSUE 9):
- one chunk-prefill program per chunk bucket (chunked prefill AND
  prefix-cache suffix prefill — `start`/`slot`/`src` are traced),
- one speculation round per k (draft + verify; replaces the decode
  block when a draft model is configured),
- one draft prefill program per bucket.

Copy surface (ISSUE 13): the pool lives as PER-SLOT rows
(kv_pool.SlotPool), so prefill/chunk programs take and return one row —
the old jitted pool writer/copier and their full-pool round trips are
gone. The decode block stacks the rows inside the program and splits
its output back; when the donation gauntlet (programs/donation.py)
allows it, the pool rows are DONATED so even that round trip aliases
in place. Donation never changes values, and the engine guards the
failure mode it introduces: a donated decode program dying mid-call
invalidates its input rows, so the engine rebuilds zero rows and
force-clears the prefix cache before re-raising (`_recover_pool`) —
the error still fails over normally, but the engine stays serviceable.

Greedy requests take the raw argmax exactly like `generate()`, so their
outputs are token-for-token identical to a per-request generate() call
(the bench.py `serving` phase guards this bit-for-bit).

Resilience: host<->device transfers ride `resilience.call_with_retry`
(transient blips retried with backoff); any prefill/transfer failure is
a REQUEST-level error — the handle turns FAILED, the slot frees, and
the engine keeps serving everyone else.
"""
from __future__ import annotations

import collections
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..observability import reqledger as _reqledger
from ..jit import functional_state
from ..nlp.generation import _NEG_INF, cached_forward
from ..resilience import RetryPolicy, call_with_retry
from ..tensor import Tensor
from .adapters.apply import adapter_scope as _adapter_scope
from .api import GREEDY, RUNNING, RequestHandle, SamplingParams
from .kv_pool import (PagePoolExhausted, PagedSlotPool, SlotPool,
                      gather_pages, scatter_pages, split_rows,
                      stack_rows)
from .prefix_cache import PagedPrefixCache, RadixPrefixCache
from .scheduler import FCFSScheduler

# occupancy is a ratio; the latency-shaped default buckets are wrong here
_OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def _to_device(x):
    """Host->device staging of prompts (module-level so fault-injection
    tests can patch it; production call sites wrap it in retry)."""
    return jnp.asarray(x)


def _from_device(x):
    """Device->host fetch of sampled tokens (patchable, see _to_device)."""
    return np.asarray(x)


def sample_rows(logits, temp, topk, topp, greedy, keys, steps):
    """Vectorized per-row sampling over a [N, V] logits slab with PER-ROW
    params (arrays, not static config — one compiled program serves every
    request mix). Greedy rows take the raw argmax — bit-identical to
    `_next_token`'s greedy path — so a greedy request's tokens never
    depend on its batch neighbours. Sampling rows apply temperature, then
    top-k, then top-p (the `_process_logits` order) and draw
    categorically with their own folded key."""
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def do_sample(_):
        scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
        # per-row top-k: k <= 0 or >= v disables (mirrors _process_logits)
        srt = jax.lax.top_k(scaled, v)[0]                   # descending
        k_eff = jnp.where((topk > 0) & (topk < v), topk,
                          v).astype(jnp.int32)
        kth = jnp.take_along_axis(srt, k_eff[:, None] - 1, axis=-1)
        x = jnp.where(scaled < kth, _NEG_INF, scaled)
        # per-row top-p over the already-top-k-filtered slab
        srt_p = jax.lax.top_k(x, v)[0]
        probs = jax.nn.softmax(srt_p, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum((cum - probs) < topp[:, None], axis=-1) - 1
        cutoff = jnp.take_along_axis(
            srt_p, jnp.clip(cutoff_idx, 0, v - 1)[:, None], axis=-1)
        x = jnp.where((topp[:, None] < 1.0) & (x < cutoff), _NEG_INF, x)
        keys_f = jax.vmap(jax.random.fold_in)(keys, steps)
        return jax.vmap(jax.random.categorical)(keys_f,
                                                x).astype(jnp.int32)

    # all-greedy batches (the common serving mix) skip the two full-vocab
    # sorts + RNG entirely — lax.cond picks the branch at RUN time, so
    # the mix can change step to step without recompiling
    sampled = jax.lax.cond(jnp.all(greedy), lambda _: greedy_tok,
                           do_sample, None)
    return jnp.where(greedy, greedy_tok, sampled)


class InferenceEngine:
    """Single-host continuous-batching engine around one causal-LM.

    Args:
        model: any `GenerationMixin` model honoring the `init_cache` /
            cached-forward contract (weights are snapshotted at
            construction). Put the model in eval() yourself if it holds
            dropout state; the engine forces eval.
        num_slots: KV slots = max concurrently decoding requests.
        max_length: per-slot cache length; every request needs
            prompt_len + max_new_tokens <= max_length.
        decode_block: tokens decoded per compiled step (device-side
            lax.scan). Larger blocks amortize host dispatch; a request
            finishing mid-block wastes at most block-1 sub-steps.
        buckets: prefill length buckets (default: powers of two).
        max_prefill_tokens: per-iteration prefill budget (scheduler).
        eos_token_id: default eos (-1 = never); per-request params win.
        retry_policy: resilience.RetryPolicy for host<->device
            transfers (default: flag-configured policy).
        prefix_cache: radix prefix cache over the slot pool — shared
            prompt prefixes (system prompts) prefill once. True = cache
            at the default 0.5 pool fraction, a float = that fraction,
            a ready `RadixPrefixCache` = use it, None/False = off.
        prefill_chunk_tokens: prompts longer than this prefill in
            bucket-shaped chunks across successive decode rounds
            (Sarathi-Serve-style interleaving) instead of stalling
            every in-flight request's TPOT behind one long prefill.
            None = whole-prompt prefill (the PR-4 behavior).
        draft_model: optional smaller causal LM for per-slot
            speculative decoding: each round it proposes
            `num_draft_tokens` greedily and the decode step verifies
            k+1 positions in ONE target forward, accepting the longest
            matching prefix (output identical to plain greedy). Draft
            KV lives in a parallel SlotPool. Sampling requests in the
            same engine simply decode one token per round.
        num_draft_tokens: draft proposals per speculation round (k).
        donate_pool: donate the KV rows into the decode/spec programs
            so the pool aliases in place instead of round-tripping
            (value-neutral; the store-served variant additionally
            requires a donation-gauntlet-safe verdict and runs
            sentinel-guarded). Default True; the bench donation phase
            A/Bs False against it.
        kv_page_size: setting this (or kv_pages/kv_quant) switches the
            KV cache to the PAGED layout (kv_pool.PagedSlotPool):
            fixed-size pages + a per-slot page table, reservation-based
            admission (page exhaustion requeues instead of failing),
            prefix retention by PAGE (copy-on-write shared), and the
            paged decode/prefill/spec programs that gather/scatter
            through the table. max_length must be a multiple.
        kv_pages: total pages in the paged pool (page 0 is the null
            page). Default num_slots * pages_per_slot + 1 — set LOWER
            to oversubscribe HBM: short requests then reserve only the
            pages they can touch, admitting more concurrent requests
            than row slots would at the same byte budget.
        kv_quant: 'int8' stores paged KV as int8 with per-(page, head)
            absmax scales (half/quarter the bytes of bf16/f32 KV);
            gather dequantizes, scatter requantizes touched pages. The
            bench `paged_ab` phase measures the logit-RMSE cost.
        adapter_bank: a `serving.adapters.AdapterBank` attached to this
            model — enables `submit(..., adapter_id=)` multi-tenant
            LoRA serving: the packed bank arrays and a per-slot adapter
            row vector ride every decode/prefill/spec program as TRACED
            inputs, so one compiled program serves any heterogeneous
            adapter mix (loads/evictions/hot-swaps never recompile).
            Requests pin their bank slot at admission and release it at
            retirement; the prefix cache keys adapter requests under
            `(adapter_id, adapter_version)` namespaces so tenants never
            share prefix KV across adapters.

    Not thread-safe: one engine is one event loop; drive it with
    `step()`, `run()`, `stream()`, or `generate_many()`.
    """

    def __init__(self, model, num_slots: int = 8, max_length: int = 256,
                 decode_block: int = 4,
                 buckets: Optional[Sequence[int]] = None,
                 max_prefill_tokens: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 dtype=None, retry_policy: Optional[RetryPolicy] = None,
                 max_wait_s: Optional[float] = None,
                 prefix_cache=None,
                 prefill_chunk_tokens: Optional[int] = None,
                 draft_model=None, num_draft_tokens: int = 4,
                 weight_version: int = 0,
                 donate_pool: Optional[bool] = None,
                 kv_page_size: Optional[int] = None,
                 kv_pages: Optional[int] = None,
                 kv_quant: Optional[str] = None,
                 adapter_bank=None):
        cfg = getattr(model, 'config', None)
        max_pos = getattr(cfg, 'max_position_embeddings', None)
        if max_pos is not None and max_length > max_pos:
            raise ValueError(
                f'max_length {max_length} exceeds the model\'s '
                f'max_position_embeddings {max_pos}')
        if decode_block < 1:
            raise ValueError('decode_block must be >= 1')
        model.eval()
        self.model = model
        self._params, self._frozen, self._buffers = functional_state(model)
        # monotone weight-version tag: bumped by swap_weights (the
        # trainer→serving hot-swap path); every request is stamped with
        # the version it decodes under at admission
        self.weight_version = int(weight_version)
        self.eos_token_id = int(
            getattr(cfg, 'eos_token_id', -1) if eos_token_id is None
            else eos_token_id)
        self.decode_block = int(decode_block)
        self._paged = (kv_page_size is not None or kv_pages is not None
                       or kv_quant is not None)
        if self._paged:
            self.pool = PagedSlotPool(
                model, num_slots, max_length, dtype, buckets,
                page_size=int(kv_page_size) if kv_page_size else 16,
                num_pages=kv_pages, quant=kv_quant)
        else:
            self.pool = SlotPool(model, num_slots, max_length, dtype,
                                 buckets)
        self.scheduler = FCFSScheduler(max_prefill_tokens,
                                       max_wait_s=max_wait_s)
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 1:
            raise ValueError('prefill_chunk_tokens must be >= 1')
        self.prefill_chunk_tokens = (int(prefill_chunk_tokens)
                                     if prefill_chunk_tokens else None)
        self.pool.prefill_chunk_tokens = self.prefill_chunk_tokens
        if isinstance(prefix_cache, RadixPrefixCache):
            if self._paged != isinstance(prefix_cache, PagedPrefixCache):
                raise ValueError(
                    'prefix cache layout does not match the pool: a '
                    'paged engine needs a PagedPrefixCache (and a row '
                    'engine a RadixPrefixCache)')
            self.prefix_cache: Optional[RadixPrefixCache] = prefix_cache
        elif prefix_cache:
            fraction = (0.5 if prefix_cache is True
                        else float(prefix_cache))
            cache_cls = (PagedPrefixCache if self._paged
                         else RadixPrefixCache)
            self.prefix_cache = cache_cls(self.pool, fraction)
        else:
            self.prefix_cache = None
        if self.prefix_cache is not None:
            budget = (self.prefix_cache.budget_pages if self._paged
                      else self.prefix_cache.budget_slots)
            if budget < 1:
                raise ValueError(
                    'prefix cache budget rounds to zero '
                    + ('pages' if self._paged else 'slots')
                    + '; raise the fraction or the pool size (retention '
                    'must leave capacity for decode)')
        if self.prefix_cache is not None:
            self.prefix_cache.set_version(self.weight_version)
        self.draft_model = draft_model
        self.spec_k = int(num_draft_tokens)
        if draft_model is not None:
            if self.spec_k < 1:
                raise ValueError('num_draft_tokens must be >= 1')
            d_cfg = getattr(draft_model, 'config', None)
            d_pos = getattr(d_cfg, 'max_position_embeddings', None)
            if d_pos is not None and max_length > d_pos:
                raise ValueError(
                    f'max_length {max_length} exceeds the DRAFT model\'s '
                    f'max_position_embeddings {d_pos}')
            draft_model.eval()
            self._draft_state = functional_state(draft_model)
            # parallel draft KV: same slot indices as the target pool
            # (never alloc/freed itself — slot i of both pools always
            # belongs to the same request)
            self.draft_pool = SlotPool(draft_model, num_slots,
                                       max_length, dtype, buckets)
        else:
            self._draft_state = None
            self.draft_pool = None
        # multi-tenant LoRA serving (ISSUE 19): the bank's packed
        # factor arrays + a per-slot adapter row vector are TRACED
        # inputs to every program below — adapter loads, evictions and
        # hot-swaps move array contents, never avals, so the compiled
        # set is exactly the bank-less engine's (one decode block, one
        # prefill per bucket, ...), just with wider signatures
        self.adapter_bank = adapter_bank
        # slot -> [handle, prefill cursor]: slots mid-chunked-prefill
        # (inactive for decode until the cursor reaches the prompt end)
        self._prefilling: dict = {}
        self._retry = retry_policy or RetryPolicy()
        self._draining = False
        self._drain_deadline_s: Optional[float] = None
        self._preempt = None
        # observability scope for degraded-state notes: None = the whole
        # process (single-engine deployments); the router tags each
        # replica's engine 'replica:N' so /healthz and placement can
        # tell WHICH replica is draining
        self.obs_scope: Optional[str] = None

        n = self.pool.num_slots
        # per-slot decode state + sampling params, host-authoritative
        # (tiny arrays re-staged every step; the KV pool stays on device)
        self._tok = np.zeros(n, np.int32)       # pending (last emitted)
        self._pos = np.zeros(n, np.int32)       # its cache slot/position
        self._steps = np.zeros(n, np.int32)     # per-request sample index
        self._active = np.zeros(n, bool)
        self._temp = np.ones(n, np.float32)
        self._topk = np.zeros(n, np.int32)
        self._topp = np.ones(n, np.float32)
        self._greedy = np.ones(n, bool)
        self._keys = np.zeros((n, 2), np.uint32)
        self._eos_arr = np.full(n, -1, np.int32)   # spec accept stop
        self._adapter_rows = np.zeros(n, np.int32)  # 0 = base adapter
        self._slot_req: dict = {}               # slot -> RequestHandle

        self._trace_counts = collections.Counter()
        self._counts = collections.Counter()
        # enrolled in the program store: per-program FLOPs/bytes/peak
        # attribution for the decode block and each prefill bucket, off
        # the same single compile each program costs anyway — and, with
        # a persistent store, a cold replica LOADS these instead of
        # compiling. The statics cover what the avals cannot: the model
        # body/config and the engine geometry (decode_block is a scan
        # length, invisible in any input aval). Sibling replicas over
        # the same model produce identical keys, so N replicas compile
        # (or load) each program once.
        from .. import programs as _programs
        store = _programs.get_store()
        # pool donation (the "kill the copy" half the gauntlet governs):
        # the decode/spec programs DONATE their row inputs so the pool
        # aliases in place. Direct in-process compiles donate as
        # declared (PR-8-safe); the store's export path re-applies the
        # recorded argnums only on a gauntlet-safe verdict, sentinel-
        # guarded. donate_pool rides the statics: a donated and an
        # undonated engine must never share one store key.
        self._donate_pool = True if donate_pool is None else bool(
            donate_pool)
        engine_statics = {
            'model': type(model).__qualname__,
            'model_src': _programs.code_token(type(model)),
            'config': _programs.describe_statics(cfg),
            'num_slots': self.pool.num_slots,
            'max_length': self.pool.max_length,
            'decode_block': self.decode_block,
            'donate_pool': self._donate_pool,
        }
        if self.adapter_bank is not None:
            # ONLY the packed geometry + target-site set ride the key:
            # which adapters are resident is array CONTENT, invisible
            # to the program — but an adapter engine must never share
            # a store key with a base engine (different signatures)
            engine_statics['adapters'] = \
                self.adapter_bank.describe_statics()
        if self._paged:
            # page geometry is invisible in the contiguous avals the
            # decode scan sees (the table aval only fixes num_slots x
            # pages_per_slot), so it MUST ride the statics — and paged
            # vs row programs must never share a store key
            engine_statics.update(
                kv_layout='paged',
                kv_page_size=self.pool.page_size,
                kv_pages=self.pool.num_pages,
                kv_quant=self.pool.quant or 'none')
        if self._paged:
            # page buffers (and scales) donate through the PR-13
            # gauntlet exactly like the row pool did: decode/spec alias
            # the pool in place; prefill/chunk stay UNDONATED so a
            # prefill failure remains request-level (a donated prefill
            # dying would invalidate the whole pool)
            self._decode_jit = store.wrap_jit(
                self._paged_decode_fn, name='serving.paged_decode_block',
                kind='serving', statics=engine_statics,
                donate_argnums=(3, 4) if self._donate_pool else ())
            self._prefill_jit = store.wrap_jit(   # 1 trace per bucket
                self._paged_prefill_fn,
                name_fn=lambda args: f'serving.paged_prefill_'
                                     f'{args[6].shape[1]}',
                kind='serving', statics=engine_statics)
            self._chunk_prefill_jit = store.wrap_jit(
                self._paged_chunk_prefill_fn,
                name_fn=lambda args: f'serving.paged_chunk_prefill_'
                                     f'{args[6].shape[1]}',
                kind='serving', statics=engine_statics)
        else:
            self._decode_jit = store.wrap_jit(
                self._decode_block_fn, name='serving.decode_block',
                kind='serving', statics=engine_statics,
                donate_argnums=(3,) if self._donate_pool else ())
            self._prefill_jit = store.wrap_jit(   # 1 trace per bucket
                self._prefill_fn,
                name_fn=lambda args: f'serving.prefill_'
                                     f'{args[3].shape[1]}',
                kind='serving', statics=engine_statics)
            self._chunk_prefill_jit = store.wrap_jit(  # 1 / chunk bucket
                self._chunk_prefill_fn,
                name_fn=lambda args: f'serving.chunk_prefill_'
                                     f'{args[4].shape[1]}',
                kind='serving', statics=engine_statics)
        if draft_model is not None:
            spec_statics = dict(
                engine_statics,
                draft_model=type(draft_model).__qualname__,
                draft_src=_programs.code_token(type(draft_model)),
                draft_config=_programs.describe_statics(
                    getattr(draft_model, 'config', None)),
                spec_k=self.spec_k)
            # one compiled speculation round per k: the drafts/verify
            # shapes are internal, invisible in any input aval, so k
            # MUST ride the statics
            if self._paged:
                self._spec_jit = store.wrap_jit(
                    self._paged_spec_fn,
                    name=f'serving.paged_spec_decode_k{self.spec_k}',
                    kind='serving', statics=spec_statics,
                    donate_argnums=(3, 4, 9) if self._donate_pool
                    else ())
            else:
                self._spec_jit = store.wrap_jit(
                    self._spec_decode_fn,
                    name=f'serving.spec_decode_k{self.spec_k}',
                    kind='serving', statics=spec_statics,
                    donate_argnums=(3, 7) if self._donate_pool else ())
            self._draft_prefill_jit = store.wrap_jit(
                self._draft_prefill_fn,
                name_fn=lambda args: f'serving.draft_prefill_'
                                     f'{args[3].shape[1]}',
                kind='serving', statics=spec_statics)
        self._init_metrics()
        if store.persistent:
            # cold-replica warm start: materialize persisted serving
            # executables BEFORE the first request (holds the
            # ref-counted /healthz `warming` state while loading);
            # idempotent, so sibling replicas after the first skip it
            self.preload_programs()

    def preload_programs(self) -> dict:
        """Bulk-load this engine's persisted executables (decode block,
        prefill buckets) from the program store into memory, so the
        first submitted request decodes instead of compiling. No-op
        without a persistent store."""
        from .. import programs as _programs
        return _programs.get_store().preload(match='serving.')

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------
    def _init_metrics(self):
        reg = _obs.get_registry()
        self._m_requests = reg.counter(
            'paddle_serving_requests_total',
            'serving requests by lifecycle event', ('status',))
        self._m_tokens = reg.counter(
            'paddle_serving_tokens_total', 'generated tokens')
        self._m_prefills = reg.counter(
            'paddle_serving_prefills_total', 'prefills by length bucket',
            ('bucket',))
        self._m_prefill_tokens = reg.counter(
            'paddle_serving_prefill_tokens_total',
            'real (unpadded) prompt tokens prefilled')
        self._m_decode_steps = reg.counter(
            'paddle_serving_decode_steps_total',
            'single-token decode sub-steps executed')
        self._m_rounds = reg.counter(
            'paddle_serving_decode_rounds_total',
            'compiled decode-block invocations')
        self._m_slots = reg.gauge(
            'paddle_serving_slots', 'KV slot capacity')
        self._m_active = reg.gauge(
            'paddle_serving_active_slots', 'slots currently decoding')
        self._m_occupancy = reg.histogram(
            'paddle_serving_slot_occupancy',
            'occupied-slot fraction per decode round',
            buckets=_OCCUPANCY_BUCKETS)
        self._m_ttft = reg.histogram(
            'paddle_serving_ttft_seconds',
            'submit -> first token latency')
        self._m_tpot = reg.histogram(
            'paddle_serving_tpot_seconds',
            'mean inter-token latency per finished request')
        self._m_chunk_rounds = reg.counter(
            'paddle_serving_chunk_rounds_total',
            'chunked-prefill rounds executed')
        self._m_chunk_tokens = reg.counter(
            'paddle_serving_chunk_tokens_total',
            'prompt tokens prefilled via chunk rounds')
        self._m_spec_rounds = reg.counter(
            'paddle_serving_spec_rounds_total',
            'speculation rounds (draft + verify) executed')
        self._m_spec_proposed = reg.counter(
            'paddle_serving_spec_proposed_total',
            'draft tokens proposed to the verifier')
        self._m_spec_accepted = reg.counter(
            'paddle_serving_spec_accepted_total',
            'draft tokens accepted by the verifier')
        # one reporting surface with standalone speculative_generate():
        # the paddle_spec_* family, labeled by source
        self._m_spec_shared = reg.counter(
            'paddle_spec_rounds_total',
            'speculative-decode rounds by source', ('source',))
        self._m_spec_shared_prop = reg.counter(
            'paddle_spec_proposed_drafts_total',
            'draft tokens proposed by source', ('source',))
        self._m_spec_shared_acc = reg.counter(
            'paddle_spec_accepted_drafts_total',
            'draft tokens accepted by source', ('source',))
        if _obs.enabled():
            self._m_slots.set(self.pool.num_slots)

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _decode_block_fn(self, params, frozen, buffers, pool, tok, pos,
                         steps, active, temp, topk, topp, greedy, keys,
                         adapters=None, adapter_rows=None):
        """One compiled program: `decode_block` single-token steps over
        ALL slots (lax.scan), per-slot positions/masks/sampling. `pool`
        arrives as the tuple of per-slot rows and is stacked/split
        inside the program (bit-identical math); with `donate_pool` the
        row inputs are donated so the round trip aliases in place.
        `adapters`/`adapter_rows` (bank-attached engines only) are the
        packed LoRA banks + per-slot bank rows — traced inputs, so any
        adapter mix replays this same program."""
        self._trace_counts['decode_step'] += 1   # python-level trace count
        fwd = cached_forward(self.model, params, frozen, buffers)
        max_len = self.pool.max_length
        k_slot = jnp.arange(max_len, dtype=jnp.int32)
        pool = stack_rows(pool)

        def sub(carry, _):
            tok, pos, steps, pool = carry
            # pending token writes its KV at slot `pos` and attends to
            # every slot <= pos; freed/stale rows above are masked out
            mask = (k_slot[None, :] <= pos[:, None])[:, None, None, :]
            logits, pool = fwd(tok[:, None], pool, pos, pos, mask)
            nxt = sample_rows(logits[:, -1], temp, topk, topp, greedy,
                              keys, steps)
            nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
            pos = jnp.minimum(pos + 1, jnp.int32(max_len - 1))
            return (nxt, pos, steps + 1, pool), nxt

        # the scope is trace-time thread-local state: every tagged
        # Linear the scan body traces adds its gathered per-row delta
        with _adapter_scope(adapters, adapter_rows):
            (tok, pos, steps, pool), toks = jax.lax.scan(
                sub, (tok, pos, steps, pool), None,
                length=self.decode_block)
        # [num_slots, block] tokens + the pool back as per-slot rows
        return jnp.transpose(toks), split_rows(pool, self.pool.num_slots)

    def _prefill_fn(self, params, frozen, buffers, ids,
                    adapters=None, adapter_rows=None):
        """Prefill ONE request (batch-1, right-padded to its bucket) and
        return the resulting KV ROW — the host stores it as the slot's
        row, so the undonated copy surface is one row, never the pool.
        KV-only and fully async: no logits leave the device — the
        request's FIRST token falls out of the next decode block, which
        re-forwards the last prompt token at position s-1 (an identical
        overwrite of its KV slot) and samples from the same
        last-position logits the prefill computed. One compile per
        bucket (ids.shape), everything else traced."""
        self._trace_counts[f'prefill_{ids.shape[1]}'] += 1
        fwd = cached_forward(self.model, params, frozen, buffers)
        slab = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self.pool.row_spec)
        with _adapter_scope(adapters, adapter_rows):
            _, slab = fwd(ids, slab, jnp.int32(0), jnp.int32(0), None)
        return slab

    def _chunk_prefill_fn(self, params, frozen, buffers, row, ids, start,
                          adapters=None, adapter_rows=None):
        """Prefill ONE chunk of ONE request's prompt at positions
        [start, start+chunk): the shared program behind both chunked
        prefill and prefix-cache suffix prefill. Forwards against an
        EXISTING row — the slot's own row for follow-up chunks, the
        RETAINED row on a prefix-cache hit's first chunk (the prefix
        copy IS the row input, so a hit costs exactly one row write,
        never copy + prefill) — with an explicit slot-causal mask
        because `start` is traced. Takes and returns ONE row; one
        compile per chunk bucket (ids.shape); `start` traced."""
        self._trace_counts[f'chunk_prefill_{ids.shape[1]}'] += 1
        fwd = cached_forward(self.model, params, frozen, buffers)
        b = ids.shape[1]
        k_slot = jnp.arange(self.pool.max_length, dtype=jnp.int32)
        q_pos = start + jnp.arange(b, dtype=jnp.int32)
        mask = (k_slot[None, :] <= q_pos[:, None])[None, None]
        with _adapter_scope(adapters, adapter_rows):
            _, row = fwd(ids, row, start, start, mask)
        return row

    def _draft_prefill_fn(self, params, frozen, buffers, ids):
        """`_prefill_fn` for the DRAFT model/pool: the draft needs its
        own prompt KV before it can propose. One compile per bucket."""
        self._trace_counts[f'draft_prefill_{ids.shape[1]}'] += 1
        fwd = cached_forward(self.draft_model, params, frozen, buffers)
        slab = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            self.draft_pool.row_spec)
        _, slab = fwd(ids, slab, jnp.int32(0), jnp.int32(0), None)
        return slab

    def _spec_decode_fn(self, params, frozen, buffers, pool,
                        d_params, d_frozen, d_buffers, d_pool,
                        tok, pos, steps, active, temp, topk, topp,
                        greedy, keys, eos,
                        adapters=None, adapter_rows=None):
        """One compiled SPECULATION round over all slots (replaces the
        plain decode block when a draft model is configured): the draft
        proposes k tokens autoregressively for every slot, the target
        verifies [pending, d_1..d_k] — k+1 positions — in ONE forward,
        and each greedy slot accepts its longest matching draft prefix
        plus the target's own next token (`_spec_decode_jit` semantics:
        output EXACTLY plain greedy, in fewer target passes). Sampling
        slots ignore the drafts and sample one token from the pending
        position's logits, exactly like the plain block. Rejected draft
        KV (target and draft pools) is stale-above-live and overwritten
        next round before anything attends it.

        Returns (tokens [N, k+1], accepted-counts [N], new pools)."""
        k = self.spec_k
        self._trace_counts[f'spec_decode_k{k}'] += 1
        fwd_t = cached_forward(self.model, params, frozen, buffers)
        fwd_d = cached_forward(self.draft_model, d_params, d_frozen,
                               d_buffers)
        pool = stack_rows(pool)
        d_pool = stack_rows(d_pool)
        max_len = self.pool.max_length
        k_slot = jnp.arange(max_len, dtype=jnp.int32)
        n = tok.shape[0]

        def draft_body(j, carry):
            cur, d_pool, drafts = carry
            p = pos + j
            mask = (k_slot[None, :] <= p[:, None])[:, None, None, :]
            lg, d_pool = fwd_d(cur[:, None], d_pool, p, p, mask)
            nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
            return nxt, d_pool, drafts.at[:, j].set(nxt)

        _, d_pool, drafts = jax.lax.fori_loop(
            0, k, draft_body,
            (tok, d_pool, jnp.zeros((n, k), jnp.int32)))

        # target scores [pending, d_1..d_k] at positions pos..pos+k —
        # the adapter scope covers ONLY the target verify: the draft
        # model is untagged (drafts stay base-model proposals; a miss
        # costs acceptance rate, never correctness — the verify's
        # adapter logits decide what is emitted)
        block = jnp.concatenate([tok[:, None], drafts], axis=1)
        q_pos = pos[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        mask = (k_slot[None, None, :] <= q_pos[:, :, None])[:, None]
        with _adapter_scope(adapters, adapter_rows):
            logits, pool = fwd_t(block, pool, pos, pos, mask)

        choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [N,k+1]
        # longest accepted draft prefix; acceptance stops at EOS
        # (everything after an emitted EOS is discarded anyway) and is
        # zero for sampling rows — they take the plain-sampling path
        match = ((drafts == choice[:, :k])
                 & (drafts != eos[:, None]) & greedy[:, None])
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        sampled = sample_rows(logits[:, 0], temp, topk, topp, greedy,
                              keys, steps)
        v_new = jnp.where(
            greedy,
            jnp.take_along_axis(choice, a[:, None], axis=1)[:, 0],
            sampled)
        j = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        draft_ext = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)
        toks = jnp.where(j < a[:, None], draft_ext,
                         jnp.where(j == a[:, None], v_new[:, None], 0))
        toks = jnp.where(active[:, None], toks, 0).astype(jnp.int32)
        counts = jnp.where(active, a + 1, 0).astype(jnp.int32)
        return (toks, counts, split_rows(pool, n),
                split_rows(d_pool, self.draft_pool.num_slots))

    # ------------------------------------------------------------------
    # compiled programs: PAGED layout
    # ------------------------------------------------------------------
    def _paged_decode_fn(self, params, frozen, buffers, pages, scales,
                         table, tok, pos, steps, active, temp, topk,
                         topp, greedy, keys,
                         adapters=None, adapter_rows=None):
        """The decode block over the PAGE-TABLE pool: gather every
        slot's pages into the contiguous [N, max_length, H, D] view the
        row-pool scan already consumes (dequantizing int8 pages in the
        same expression), run the IDENTICAL per-token scan, then scatter
        only the pages overlapping [pos, pos+block) back — untouched
        pages are never rewritten, which makes the unquantized path a
        bit-exact writeback and keeps settled int8 pages from
        requantization drift. Inactive slots (parked mid-prefill, free)
        have their table row redirected to the null page so their junk
        token-0 writes can land nowhere real. `pages`/`scales` are
        donated (argnums 3, 4) so the pool aliases in place."""
        self._trace_counts['paged_decode_step'] += 1
        fwd = cached_forward(self.model, params, frozen, buffers)
        max_len = self.pool.max_length
        k_slot = jnp.arange(max_len, dtype=jnp.int32)
        sc = scales if self.pool.quant else None
        table = jnp.where(active[:, None], table, 0)
        contig = gather_pages(pages, table, sc,
                              out_dtype=self.pool.compute_dtype)
        pos0 = pos

        def sub(carry, _):
            tok, pos, steps, pool = carry
            mask = (k_slot[None, :] <= pos[:, None])[:, None, None, :]
            logits, pool = fwd(tok[:, None], pool, pos, pos, mask)
            nxt = sample_rows(logits[:, -1], temp, topk, topp, greedy,
                              keys, steps)
            nxt = jnp.where(active, nxt, 0).astype(jnp.int32)
            pos = jnp.minimum(pos + 1, jnp.int32(max_len - 1))
            return (nxt, pos, steps + 1, pool), nxt

        with _adapter_scope(adapters, adapter_rows):
            (tok, pos, steps, contig), toks = jax.lax.scan(
                sub, (tok, pos, steps, contig), None,
                length=self.decode_block)
        pages, sc = scatter_pages(pages, table, contig, pos0,
                                  self.decode_block,
                                  self.pool.page_size, sc)
        return (jnp.transpose(toks), pages,
                sc if sc is not None else ())

    def _paged_prefill_fn(self, params, frozen, buffers, pages, scales,
                          table, ids, adapters=None, adapter_rows=None):
        """Whole-prompt prefill into the PAGE pool: same batch-1 forward
        over a zero slab as `_prefill_fn`, then one scatter of
        [0, bucket) through the slot's table row ([1, P]). Pad rows past
        the reservation fall on null-table entries and vanish. UNDONATED
        on purpose: a prefill failure must stay request-level."""
        b = ids.shape[1]
        self._trace_counts[f'paged_prefill_{b}'] += 1
        fwd = cached_forward(self.model, params, frozen, buffers)
        slab = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self.pool.row_spec)
        with _adapter_scope(adapters, adapter_rows):
            _, slab = fwd(ids, slab, jnp.int32(0), jnp.int32(0), None)
        sc = scales if self.pool.quant else None
        pages, sc = scatter_pages(pages, table, slab,
                                  jnp.zeros(1, jnp.int32), b,
                                  self.pool.page_size, sc)
        return pages, sc if sc is not None else ()

    def _paged_chunk_prefill_fn(self, params, frozen, buffers, pages,
                                scales, table, ids, start, floor,
                                adapters=None, adapter_rows=None):
        """One chunk of one prompt through the PAGE table: gather the
        slot's contiguous view (attached prefix pages included — the
        chunk attends the shared prefix through its own table, no src
        row needed), forward [start, start+chunk) with the slot-causal
        mask, scatter back. `floor` is the prefix-attach boundary
        (page-aligned): a tail-shifted window re-forwards rows below the
        cursor with bit-identical values, and the floor redirect makes
        sure those duplicate writes can never touch a SHARED page (int8
        requantization there would drift siblings)."""
        b = ids.shape[1]
        self._trace_counts[f'paged_chunk_prefill_{b}'] += 1
        fwd = cached_forward(self.model, params, frozen, buffers)
        sc = scales if self.pool.quant else None
        row = gather_pages(pages, table, sc,
                           out_dtype=self.pool.compute_dtype)
        k_slot = jnp.arange(self.pool.max_length, dtype=jnp.int32)
        q_pos = start + jnp.arange(b, dtype=jnp.int32)
        mask = (k_slot[None, :] <= q_pos[:, None])[None, None]
        with _adapter_scope(adapters, adapter_rows):
            _, row = fwd(ids, row, start, start, mask)
        pages, sc = scatter_pages(pages, table, row,
                                  jnp.reshape(start, (1,)), b,
                                  self.pool.page_size, sc,
                                  floor=jnp.reshape(floor, (1,)))
        return pages, sc if sc is not None else ()

    def _paged_spec_fn(self, params, frozen, buffers, pages, scales,
                       table, d_params, d_frozen, d_buffers, d_pool,
                       tok, pos, steps, active, temp, topk, topp,
                       greedy, keys, eos,
                       adapters=None, adapter_rows=None):
        """The speculation round over the PAGED target pool: identical
        draft-propose / k+1-verify / longest-prefix-accept math as
        `_spec_decode_fn`, with the target KV gathered through the page
        table and the verify's k+1-row span scattered back (reservation
        headroom guarantees the span never clamps past max_length). The
        DRAFT pool stays a row SlotPool — it is small, never shared,
        and keeping it row-shaped bounds this PR's blast radius.
        Donates pages, scales, and the draft rows (argnums 3, 4, 9)."""
        k = self.spec_k
        self._trace_counts[f'paged_spec_decode_k{k}'] += 1
        fwd_t = cached_forward(self.model, params, frozen, buffers)
        fwd_d = cached_forward(self.draft_model, d_params, d_frozen,
                               d_buffers)
        sc = scales if self.pool.quant else None
        table = jnp.where(active[:, None], table, 0)
        pool = gather_pages(pages, table, sc,
                            out_dtype=self.pool.compute_dtype)
        d_pool = stack_rows(d_pool)
        max_len = self.pool.max_length
        k_slot = jnp.arange(max_len, dtype=jnp.int32)
        n = tok.shape[0]

        def draft_body(j, carry):
            cur, d_pool, drafts = carry
            p = pos + j
            mask = (k_slot[None, :] <= p[:, None])[:, None, None, :]
            lg, d_pool = fwd_d(cur[:, None], d_pool, p, p, mask)
            nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
            return nxt, d_pool, drafts.at[:, j].set(nxt)

        _, d_pool, drafts = jax.lax.fori_loop(
            0, k, draft_body,
            (tok, d_pool, jnp.zeros((n, k), jnp.int32)))

        block = jnp.concatenate([tok[:, None], drafts], axis=1)
        q_pos = pos[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        mask = (k_slot[None, None, :] <= q_pos[:, :, None])[:, None]
        with _adapter_scope(adapters, adapter_rows):
            logits, pool = fwd_t(block, pool, pos, pos, mask)

        choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        match = ((drafts == choice[:, :k])
                 & (drafts != eos[:, None]) & greedy[:, None])
        a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
        sampled = sample_rows(logits[:, 0], temp, topk, topp, greedy,
                              keys, steps)
        v_new = jnp.where(
            greedy,
            jnp.take_along_axis(choice, a[:, None], axis=1)[:, 0],
            sampled)
        j = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        draft_ext = jnp.concatenate([drafts, drafts[:, -1:]], axis=1)
        toks = jnp.where(j < a[:, None], draft_ext,
                         jnp.where(j == a[:, None], v_new[:, None], 0))
        toks = jnp.where(active[:, None], toks, 0).astype(jnp.int32)
        counts = jnp.where(active, a + 1, 0).astype(jnp.int32)
        pages, sc = scatter_pages(pages, table, pool, pos, k + 1,
                                  self.pool.page_size, sc)
        return (toks, counts, pages, sc if sc is not None else (),
                split_rows(d_pool, self.draft_pool.num_slots))

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    @staticmethod
    def _normalize_prompt(prompt) -> List[int]:
        if isinstance(prompt, Tensor):
            prompt = prompt.numpy()
        arr = np.asarray(prompt)
        if arr.ndim == 2 and arr.shape[0] == 1:
            arr = arr[0]
        if arr.ndim != 1 or arr.size < 1:
            raise ValueError(
                f'prompt must be a non-empty 1-D token sequence, got '
                f'shape {arr.shape}')
        return [int(t) for t in arr]

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               priority: Optional[int] = None,
               adapter_id: Optional[str] = None, **kwargs
               ) -> RequestHandle:
        """Queue one request; returns its live handle. Validation errors
        raise HERE (caller bug); runtime failures mark the handle
        FAILED instead. `priority` sets the scheduler admission class
        (PRIORITY_HIGH/NORMAL/LOW; default NORMAL). `adapter_id` decodes
        the request under that LoRA adapter from the engine's bank
        (None = base model); an unknown/unservable adapter fast-fails
        HERE with `adapters.AdapterUnavailable` — the typed miss the
        router maps onto `AdmissionRejected(reason=
        'adapter_unavailable')`."""
        if params is None:
            params = SamplingParams(**kwargs)
        elif kwargs:
            raise TypeError('pass params= or keyword sampling args, '
                            'not both')
        if adapter_id is not None:
            from .adapters.bank import AdapterUnavailable
            if self.adapter_bank is None:
                raise ValueError(
                    f'adapter_id={adapter_id!r} needs an engine built '
                    f'with adapter_bank=')
            if not self.adapter_bank.available(adapter_id):
                raise AdapterUnavailable(
                    adapter_id, 'not resident and no servable store '
                                'version')
        self._check_drain()
        if self._draining:
            self._counts['rejected'] += 1
            if _obs.enabled():
                self._m_requests.labels(status='rejected').inc()
            raise RuntimeError(
                'engine is draining (preemption signal received): not '
                'admitting new requests')
        toks = self._normalize_prompt(prompt)
        self.pool.bucket_for(len(toks))   # raises when no bucket fits
        # speculating engines verify a [pos, pos+k] block every round,
        # so every slot needs k tokens of cache headroom past its
        # budget (and the headroom is what keeps clamped block writes
        # above every retained prefix's kv_len)
        headroom = self.spec_k if self.draft_model is not None else 0
        if len(toks) + params.max_new_tokens + headroom \
                > self.pool.max_length:
            raise ValueError(
                f'prompt ({len(toks)}) + max_new_tokens '
                f'({params.max_new_tokens})'
                + (f' + speculation headroom ({headroom})' if headroom
                   else '')
                + f' exceeds the slot length ({self.pool.max_length})')
        h = RequestHandle(toks, params, engine=self)
        h.adapter_id = adapter_id
        if priority is not None:
            h.priority = int(priority)
        h._eos = int(self.eos_token_id if params.eos_token_id is None
                     else params.eos_token_id)
        self._counts['submitted'] += 1
        if _obs.enabled():
            self._m_requests.labels(status='submitted').inc()
            # queue span: begins now, ends at admission — the request's
            # trace id (request_id) threads every span/event it touches
            h._queue_span = _obs.Span('serving.queue',
                                      request_id=h.request_id).begin()
        if _reqledger.enabled():
            rec = _reqledger.get_ledger().open_for(h)
            if rec is not None:
                rec.queue_enter(h._t_submit, 'priority_queued')
        self.scheduler.submit(h)
        return h

    # ------------------------------------------------------------------
    # graceful drain (preemption)
    # ------------------------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    def enable_graceful_drain(self, handler=None, deadline_s: float = 30.0,
                              signals=None):
        """Wire a `resilience.PreemptionHandler` into the engine: on
        SIGTERM (the pod eviction grace window) the engine stops
        admitting NEW submissions, finishes every already-accepted
        request — queued and in-flight — under `deadline_s`, flips
        /healthz to a 503 `draining` state so routers stop sending
        traffic, and `run()`/`drain()` return so the caller can exit 0.
        Pass a ready handler to share one across subsystems; returns
        the handler in use."""
        if handler is None:
            import signal as _signal
            from ..resilience.preemption import PreemptionHandler
            handler = PreemptionHandler(
                signals=signals or (_signal.SIGTERM,)).install()
        self._preempt = handler
        self._drain_deadline_s = float(deadline_s)
        return handler

    def _check_drain(self):
        if (not self._draining and self._preempt is not None
                and self._preempt.requested):
            self._begin_drain()

    def begin_drain(self):
        """Stop admitting new submissions NOW, without driving decode:
        the non-blocking half of `drain()`. The router uses this to take
        one replica out of rotation (its scoped `draining` state excludes
        it from placement) while router steps keep finishing its
        accepted requests."""
        self._begin_drain()

    def _begin_drain(self):
        if self._draining:
            return
        self._draining = True
        self._drain_t0 = time.monotonic()
        info = {'queued': self.scheduler.queue_depth,
                'in_flight': len(self._slot_req)}
        # 503 from here on: the replica is leaving the pool
        _obs.note_degraded('draining', info, scope=self.obs_scope)
        _obs.emit('serving_drain_begin', **info)

    def _detach_slot(self, slot: int, h: RequestHandle):
        """Common slot teardown for fail/evict/retire: drop the engine's
        references, release the request's prefix pin, and unpin its
        adapter bank slot. Does NOT free the pool slot — retirement may
        hand it to the prefix cache."""
        del self._slot_req[slot]
        self._active[slot] = False
        self._prefilling.pop(slot, None)
        if h._prefix_node is not None:
            self.prefix_cache.release(h._prefix_node)
            h._prefix_node = None
        self._unpin_adapter(slot, h)

    def _unpin_adapter(self, slot: int, h: RequestHandle):
        """Release the request's adapter bank pin (idempotent) and point
        the pool slot's adapter row back at the zero base adapter. The
        handle keeps `adapter_id`/`adapter_version` — failover resubmits
        it elsewhere, and the version stamp is a per-response fact."""
        if h._adapter_pin is not None:
            self.adapter_bank.unpin(h._adapter_pin)
            h._adapter_pin = None
        self._adapter_rows[slot] = 0

    def _prefix_ns(self, h: RequestHandle):
        """The prefix-cache namespace this request's KV belongs to:
        adapter requests key under (adapter_id, adapter_version) — an
        adapter's prefill KV contains its LoRA deltas, so tenants with
        different adapters (or versions of one) must NEVER share a
        cached prefix; base requests share the default namespace."""
        if h.adapter_id is None:
            return None
        return (h.adapter_id, h.adapter_version)

    def _fail_remaining(self, exc: BaseException):
        for h in self.scheduler.drain():
            h._fail(exc)
            self._counts['failed'] += 1
            if _obs.enabled():
                self._m_requests.labels(status='failed').inc()
        for slot, h in list(self._slot_req.items()):
            self._detach_slot(slot, h)
            self.pool.free(slot)
            h._fail(exc)
            self._counts['failed'] += 1
            if _obs.enabled():
                self._m_requests.labels(status='failed').inc()
        if _obs.enabled():
            self._m_active.set(len(self._slot_req))

    def evict_all(self) -> List[RequestHandle]:
        """Pull every accepted request — queued AND in-flight — out of
        the engine WITHOUT failing it, returning the handles in
        submission order (queued first is irrelevant to the router; it
        re-sorts). This is the failover hand-off: when the router
        declares this replica dead, the orphans are resubmitted
        elsewhere, so their handles must leave this engine untouched.
        Slots free, actives clear; the engine itself stays serviceable
        (a transient device blip doesn't scrap the pool)."""
        out = self.scheduler.drain()
        for slot, h in list(self._slot_req.items()):
            self._detach_slot(slot, h)
            self.pool.free(slot)
            out.append(h)
        for h in out:
            if h._queue_span is not None:   # don't leak open queue spans
                h._queue_span.end()
                h._queue_span = None
        if _obs.enabled():
            self._m_active.set(len(self._slot_req))
        return out

    def drain(self, deadline_s: Optional[float] = None) -> bool:
        """Stop admitting new submissions and drive decode until every
        accepted request (queued + in-flight) finishes, bounded by the
        deadline. Past the deadline the stragglers FAIL (handles carry
        the TimeoutError) rather than being silently dropped. Returns
        True when everything completed in time. /healthz stays
        `draining` afterwards — the process is expected to exit."""
        if deadline_s is None:
            deadline_s = self._drain_deadline_s
        self._begin_drain()
        timed_out = False
        # the drain span books this window as `preemption_drain` in the
        # goodput ledger — minus the nested decode/prefill spans, which
        # stay productive serving time
        with _obs.span('serving.drain'):
            while self.has_work:
                if deadline_s is not None and \
                        time.monotonic() - self._drain_t0 > deadline_s:
                    timed_out = True
                    self._fail_remaining(TimeoutError(
                        f'drain deadline {deadline_s}s exceeded'))
                    break
                self.step()
        _obs.emit('serving_drain_complete',
                  timed_out=timed_out,
                  seconds=round(time.monotonic() - self._drain_t0, 3))
        return not timed_out

    # ------------------------------------------------------------------
    # online weight updates (trainer→serving hot-swap, ISSUE 12)
    # ------------------------------------------------------------------
    def swap_weights(self, state, *, version: int, strict: bool = True):
        """Replace the engine's weights IN PLACE with a published
        host-canonical snapshot (``{name: array}`` as produced by
        ``Layer.state_dict()`` / ``hotswap.WeightStore.load``), without
        touching a single compiled program: every staged leaf must match
        the live leaf's shape and is cast to its dtype, so the decode /
        prefill avals — and therefore the ProgramStore keys — are
        bit-identical before and after (zero XLA recompiles on swap,
        tier-1-guarded).

        Requires a DRAINED engine (no queued or in-flight requests):
        that is what makes the per-request ``weight_version`` stamp a
        whole-response guarantee. The `ReplicaUpdater` drains through
        the router first; direct callers get a loud error instead of a
        torn batch.

        `strict=True` (default) demands every live param present in
        `state`; buffers may be absent (non-persistable buffers never
        travel through `state_dict`) and keep their current values.

        Returns the PREVIOUS weight state — an opaque token for
        `restore_weights`, which the updater holds for the rollback
        path (the old device arrays stay alive by reference, so a
        revert is a pointer swap, not a reload)."""
        if self._slot_req or self.scheduler.queue_depth > 0:
            raise RuntimeError(
                f'swap_weights requires a drained engine, but '
                f'{len(self._slot_req)} slot(s) are decoding and '
                f'{self.scheduler.queue_depth} request(s) are queued '
                f'(drain through the router/updater first)')
        prev = (self._params, self._frozen, self._buffers,
                self.weight_version)
        self._params = self._stage_swap(self._params, state,
                                        'parameter', strict)
        self._frozen = self._stage_swap(self._frozen, state,
                                        'frozen parameter', strict)
        self._buffers = self._stage_swap(self._buffers, state,
                                         'buffer', False)
        self._set_weight_version(version)
        return prev

    def restore_weights(self, prev):
        """Roll back to a weight state captured by `swap_weights` (the
        failed-health-gate path). Same drained-engine requirement; the
        prefix cache's entries for the restored version re-validate for
        free (they were never flushed, only version-shadowed)."""
        if self._slot_req or self.scheduler.queue_depth > 0:
            raise RuntimeError(
                'restore_weights requires a drained engine')
        self._params, self._frozen, self._buffers, version = prev
        self._set_weight_version(version)

    def _set_weight_version(self, version: int):
        self.weight_version = int(version)
        if self.prefix_cache is not None:
            # no flush: entries from other versions go stale and are
            # lazily reclaimed; this version's survivors serve again
            self.prefix_cache.set_version(self.weight_version)
        _obs.note_weight_version(self.weight_version,
                                 scope=self.obs_scope)
        if _obs.enabled():
            _obs.get_registry().gauge(
                'paddle_weight_version',
                'live weight version per serving scope',
                ('scope',)).labels(
                    scope=self.obs_scope or 'engine').set(
                        self.weight_version)

    @staticmethod
    def _stage_swap(old_dict, state, kind: str, strict: bool):
        """Stage one functional-state dict from a published snapshot:
        shape-checked against the live aval (a mismatch means the
        checkpoint is structurally different — fail the SWAP, loudly,
        before any program could retrace) and cast to the live dtype so
        the program key cannot move."""
        new = {}
        for name, old in old_dict.items():
            if name not in state:
                if strict:
                    raise KeyError(
                        f'published weights missing {kind} {name!r}: '
                        f'refusing a partial swap')
                new[name] = old
                continue
            arr = np.asarray(getattr(state[name], 'value', state[name]))
            if tuple(arr.shape) != tuple(old.shape):
                raise ValueError(
                    f'{kind} {name!r} shape {tuple(arr.shape)} does not '
                    f'match the live aval {tuple(old.shape)}: swapping '
                    f'it would change the program key and force a '
                    f'recompile')
            new[name] = jnp.asarray(arr, dtype=old.dtype)
        return new

    # ------------------------------------------------------------------
    # the iteration loop
    # ------------------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return bool(self._slot_req) or self.scheduler.queue_depth > 0

    def step(self) -> int:
        """ONE scheduler iteration: admit queued requests into free
        slots, advance every mid-prefill slot one chunk, then advance
        every ACTIVE slot one decode round (a plain block, or one
        speculation round when a draft model is configured). Returns
        the number of requests that progressed."""
        self._check_drain()
        self._admit()
        self._advance_prefills()
        n = len(self._slot_req)
        if not np.any(self._active):
            return n            # chunk-prefill-only progress this round
        t_round0 = time.perf_counter()
        if self.draft_model is not None:
            toks, counts = self._spec_round()
        else:
            toks, counts = self._decode_round()
        now = time.perf_counter()
        # ledger BEFORE the emission loop, so the round that produced a
        # request's first token still lands in its TTFT sub-book
        # (mark_first fires inside _emit below). Waterfall book: every
        # active participant waited the full round wall; fair-share
        # book: the wall splits evenly, closing to the engine decode
        # wall.
        round_recs = [h._ledger_rec for slot, h in self._slot_req.items()
                      if self._active[slot]]
        _reqledger.get_ledger().note_round(
            now - t_round0, round_recs,
            'spec_verify' if self.draft_model is not None else 'decode',
            now=now, absorb=True)
        self._counts['decode_rounds'] += 1
        if _obs.enabled():
            self._m_rounds.inc()
            self._m_occupancy.observe(self.pool.occupancy)
            self._m_tokens.inc(0)   # ensure the family exists even idle
        for slot, h in list(self._slot_req.items()):
            if not self._active[slot]:
                continue            # mid-chunked-prefill: no tokens yet
            c = self.decode_block if counts is None else \
                int(counts[slot])  # paddle-lint: disable=host-sync -- spec accept counts gate the emission loop; one d2h per round, already materialized by toks
            if self.draft_model is not None and self._greedy[slot]:
                self._counts['spec_proposed'] += self.spec_k
                self._counts['spec_accepted'] += c - 1
                if _obs.enabled():
                    self._m_spec_proposed.inc(self.spec_k)
                    self._m_spec_accepted.inc(c - 1)
                    self._m_spec_shared_prop.labels(
                        source='engine').inc(self.spec_k)
                    self._m_spec_shared_acc.labels(
                        source='engine').inc(c - 1)
            done = False
            emitted = 0
            first = not h.tokens
            for j in range(c):
                t = int(toks[slot, j])  # paddle-lint: disable=host-sync -- THE emission d2h: tokens must reach the client; one blocking read per round for all slots
                h._emit(t, now)
                emitted += 1
                if (len(h.tokens) >= h.params.max_new_tokens
                        or t == h._eos):
                    done = True
                    break
            self._counts['tokens'] += emitted
            if _obs.enabled():
                self._m_tokens.inc(emitted)
                if first:
                    self._m_ttft.observe(h.ttft)
            if done:
                self._retire(slot, h, now)
            else:
                self._tok[slot] = toks[slot, c - 1]
                self._pos[slot] += c
                self._steps[slot] += (1 if counts is not None else c)
                # stranded-capacity accounting: rows actually written
                self.pool.note_written(slot, self._pos[slot] + 1)
        return n

    def _recover_pool(self):
        """A DONATED decode/spec program failed mid-call: its input rows
        may already be invalidated, so every retained buffer is suspect.
        Rebuild zero rows and force-clear the prefix cache (its KV
        floors are gone) BEFORE re-raising — the error still classifies
        and fails over normally, but the engine itself stays
        serviceable for the next admission."""
        if self._paged:
            self.pool.reset_pages()
        else:
            self.pool.reset_rows()
        if self.draft_pool is not None:
            self.draft_pool.reset_rows()
        if self.prefix_cache is not None:
            self.prefix_cache.clear(force=True)
        _obs.emit('serving_pool_recovered',
                  slots=self.pool.num_slots)

    def _adapter_args(self, slot: Optional[int] = None) -> tuple:
        """Trailing (bank arrays, per-row bank slots) appended to a
        program call — () on a bank-less engine, whose signatures and
        program-store keys stay exactly the pre-adapter ones. `slot`
        narrows the row vector to one slot's view for the batch-1
        prefill/chunk programs."""
        if self.adapter_bank is None:
            return ()
        rows = (self._adapter_rows if slot is None
                else self._adapter_rows[slot:slot + 1])
        return (self.adapter_bank.device_arrays(), rows)

    def _decode_round(self):
        """The plain compiled decode block (no draft model): every
        active slot advances `decode_block` tokens."""
        with _obs.span('serving.decode_round',
                       slots=len(self._slot_req),
                       requests=[h.request_id
                                 for h in self._slot_req.values()]):
            try:
                if self._paged:
                    pages, scales = self.pool.device_state()
                    table = call_with_retry(
                        _to_device, self.pool.page_table,
                        policy=self._retry, site='serving.h2d')
                    toks_dev, new_pages, new_scales = self._decode_jit(
                        self._params, self._frozen, self._buffers,
                        pages, scales, table, self._tok, self._pos,
                        self._steps, self._active, self._temp,
                        self._topk, self._topp, self._greedy,
                        self._keys, *self._adapter_args())
                    self.pool.set_device_state(new_pages, new_scales)
                else:
                    toks_dev, new_pool = self._decode_jit(
                        self._params, self._frozen, self._buffers,
                        self.pool.cache, self._tok, self._pos,
                        self._steps, self._active, self._temp,
                        self._topk, self._topp, self._greedy,
                        self._keys, *self._adapter_args())
                    self.pool.cache = new_pool
            except Exception:
                if self._donate_pool:
                    self._recover_pool()
                raise
            toks = call_with_retry(_from_device, toks_dev,
                                   policy=self._retry, site='serving.d2h')
        _obs.note_progress('decode')   # /healthz decode liveness beat
        self._counts['decode_steps'] += self.decode_block
        if _obs.enabled():
            self._m_decode_steps.inc(self.decode_block)
        return toks, None

    def _spec_round(self):
        """One compiled speculation round: k draft proposals + one
        k+1-position target verify; greedy slots advance by their
        accepted count, sampling slots by one."""
        d_params, d_frozen, d_buffers = self._draft_state
        with _obs.span('serving.spec_round',
                       slots=len(self._slot_req), k=self.spec_k,
                       requests=[h.request_id
                                 for h in self._slot_req.values()]):
            try:
                if self._paged:
                    pages, scales = self.pool.device_state()
                    table = call_with_retry(
                        _to_device, self.pool.page_table,
                        policy=self._retry, site='serving.h2d')
                    (toks_dev, counts_dev, new_pages, new_scales,
                     new_d_pool) = self._spec_jit(
                        self._params, self._frozen, self._buffers,
                        pages, scales, table, d_params, d_frozen,
                        d_buffers, self.draft_pool.cache, self._tok,
                        self._pos, self._steps, self._active,
                        self._temp, self._topk, self._topp,
                        self._greedy, self._keys, self._eos_arr,
                        *self._adapter_args())
                    self.pool.set_device_state(new_pages, new_scales)
                else:
                    toks_dev, counts_dev, new_pool, new_d_pool = \
                        self._spec_jit(
                            self._params, self._frozen, self._buffers,
                            self.pool.cache, d_params, d_frozen,
                            d_buffers, self.draft_pool.cache,
                            self._tok, self._pos, self._steps,
                            self._active, self._temp, self._topk,
                            self._topp, self._greedy, self._keys,
                            self._eos_arr, *self._adapter_args())
                    self.pool.cache = new_pool
            except Exception:
                if self._donate_pool:
                    self._recover_pool()
                raise
            self.draft_pool.cache = new_d_pool
            toks = call_with_retry(_from_device, toks_dev,
                                   policy=self._retry, site='serving.d2h')
            counts = call_with_retry(_from_device, counts_dev,
                                     policy=self._retry,
                                     site='serving.d2h')
        _obs.note_progress('decode')
        self._counts['decode_steps'] += 1   # one target verify pass
        self._counts['spec_rounds'] += 1
        if _obs.enabled():
            self._m_decode_steps.inc(1)
            self._m_spec_rounds.inc()
            self._m_spec_shared.labels(source='engine').inc()
        return toks, counts

    def run(self) -> int:
        """Drive until queue and slots drain; returns decode rounds."""
        rounds = 0
        while self.has_work:
            self.step()
            rounds += 1
        return rounds

    def stream(self, handle: RequestHandle):
        """Per-token iterator for one request (see RequestHandle.stream)."""
        return handle.stream()

    def generate_many(self, prompts, params=None,
                      adapter_ids=None) -> List[RequestHandle]:
        """Submit a batch of prompts and drain the engine — the
        continuous-batching replacement for a sequential `generate()`
        loop on mixed-length workloads. `params` is one SamplingParams
        for all, or a per-prompt sequence; `adapter_ids` is one adapter
        id (or None) for all, or a per-prompt sequence — a mixed batch
        decodes every adapter in the same compiled step."""
        if params is None or isinstance(params, SamplingParams):
            params = [params or SamplingParams()] * len(prompts)
        if len(params) != len(prompts):
            raise ValueError('one SamplingParams per prompt')
        if adapter_ids is None or isinstance(adapter_ids, str):
            adapter_ids = [adapter_ids] * len(prompts)
        if len(adapter_ids) != len(prompts):
            raise ValueError('one adapter id (or None) per prompt')
        handles = [self.submit(p, sp, adapter_id=aid)
                   for p, sp, aid in zip(prompts, params, adapter_ids)]
        self.run()
        return handles

    # ------------------------------------------------------------------
    # admission / retirement
    # ------------------------------------------------------------------
    def _admission_cost(self, prompt_len: int) -> int:
        """Prefill cost charged against the scheduler's per-iteration
        budget: with chunking, an admission costs ONE chunk bucket this
        round (the rest spreads over later rounds); without, the whole
        prompt's bucket."""
        if self.prefill_chunk_tokens:
            prompt_len = min(prompt_len, self.prefill_chunk_tokens)
        return self.pool.bucket_for(prompt_len)

    def _effective_free(self) -> int:
        """Slots admissible right now: free-list + (row mode) zero-ref
        cached prefixes the pool can reclaim on demand. Paged retention
        pins PAGES, not slots, so there the free list is the truth —
        page pressure surfaces at reservation and requeues."""
        free = self.pool.free_count
        if self.prefix_cache is not None and not self._paged:
            free += self.prefix_cache.reclaimable_count
        return free

    def _alloc_slot(self) -> int:
        if (not self._paged and self.pool.free_count == 0
                and self.prefix_cache is not None):
            # pool pressure: retained prefixes yield to live requests
            self.prefix_cache.evict_lru()
        return self.pool.alloc()

    def _requeue_blocked(self, handles, reason: str):
        """Requeue (queue FRONT, original order, first-submit timestamp
        preserved) and sample the blocking reason into each request's
        ledger record: elapsed queue time settles under the reason that
        was just observed, and a fresh interval opens."""
        now = time.perf_counter()
        for h in handles:
            rec = h._ledger_rec
            if rec is not None:
                if rec._q_mark is None and now > rec._last_touch:
                    # this handle reached _begin_request (queue_exit
                    # ran) before the seat aborted: the aborted seating
                    # work — an adapter store load that found the bank
                    # full, the page-reservation walk — is admission
                    # time, not a residual
                    rec.add('admission', now - rec._last_touch, now=now)
                rec.queue_block(now, reason)
        for back in reversed(handles):
            self.scheduler.requeue(back)

    def _admit(self):
        admitted = self.scheduler.admissible(self._effective_free(),
                                             self._admission_cost)
        for idx, h in enumerate(admitted):
            try:
                slot = self._alloc_slot()
            except RuntimeError:
                # the reclaimable slot this admission was promised got
                # pinned mid-pass (a sibling admission hit its prefix):
                # not a failure — THIS handle and everything behind it
                # in the popped batch go back to the queue front in
                # order (admissible() already removed them)
                self._requeue_blocked(admitted[idx:], 'pool_exhausted')
                break
            try:
                self._begin_request(slot, h)
            except PagePoolExhausted as exc:
                # paged admission could not reserve its pages even after
                # reclaiming retention: NOT a failure — free the slot
                # (returning whatever was attached) and send this handle
                # and everything behind it back to the queue front; the
                # pages free up as in-flight requests retire
                self.pool.free(slot)
                _obs.emit('page_pool_exhausted',
                          request_id=h.request_id,
                          queued=self.scheduler.queue_depth,
                          detail=str(exc))
                self._requeue_blocked(admitted[idx:], 'pool_exhausted')
                break
            except Exception as exc:
                from .adapters.bank import AdapterUnavailable
                if isinstance(exc, AdapterUnavailable) \
                        and exc.transient:
                    # adapter bank momentarily full of PINNED slots:
                    # pins free as in-flight requests retire, so this
                    # is back-pressure, not a failure — requeue just
                    # this handle and keep admitting the rest
                    self.pool.free(slot)
                    _obs.emit('adapter_bank_saturated',
                              request_id=h.request_id,
                              adapter_id=h.adapter_id,
                              detail=str(exc))
                    self._requeue_blocked([h], 'adapter_pinned')
                    continue
                # REQUEST-level failure: free the slot, fail the handle,
                # keep the engine serving everyone else
                if slot in self._slot_req:
                    self._detach_slot(slot, h)
                self.pool.free(slot)
                h._fail(exc)
                self._counts['failed'] += 1
                if _obs.enabled():
                    self._m_requests.labels(status='failed').inc()
                    _obs.emit('serving_request_failed',
                              request_id=h.request_id,
                              error=type(exc).__name__)
        if _obs.enabled():
            self._m_active.set(len(self._slot_req))

    def _seat_paged(self, slot: int, h: RequestHandle, s: int):
        """Page-table admission, BEFORE any handle/engine bookkeeping:
        attach the longest PAGE-ALIGNED cached prefix read-only, then
        reserve every page the request can touch (prompt + token budget
        + speculation headroom) all-or-nothing, reclaiming zero-ref
        retained holds under pressure. Raises PagePoolExhausted with the
        handle untouched — still QUEUED — so `_admit` can requeue it.
        Returns (node, cursor): cursor is the page-aligned prefix rows
        already seated (suffix prefill starts there, in fresh pages)."""
        ps = self.pool.page_size
        node, cursor = None, 0
        if self.prefix_cache is not None:
            t_pfx = time.perf_counter()
            node, matched = self.prefix_cache.lookup(
                h.prompt_tokens, namespace=self._prefix_ns(h))
            if node is not None:
                # whole pages only: the suffix [cursor, s) prefills
                # into FRESH exclusive pages, so a shared page is never
                # in any suffix/decode scatter window
                cursor = (min(matched, node.slot.kv_len) // ps) * ps
                if cursor < 1:
                    node = None
                else:
                    self.prefix_cache.acquire(node)
            if h._ledger_rec is not None:
                t1 = time.perf_counter()
                h._ledger_rec.add('prefix_lookup', t1 - t_pfx, now=t1)
        try:
            if node is not None:
                self.pool.attach_prefix(slot, node.slot, cursor // ps)
            headroom = (self.spec_k if self.draft_model is not None
                        else 0)
            self._reserve_pages(
                slot, min(s + h.params.max_new_tokens + headroom,
                          self.pool.max_length))
            if cursor >= s:
                # full-page hit: the pending-token re-forward at s-1
                # writes INTO the last shared page — COW-split it first
                while True:
                    try:
                        if self.pool.ensure_exclusive(slot, s - 1):
                            _obs.emit('paged_cow', slot=slot,
                                      request_id=h.request_id, pos=s - 1)
                        break
                    except PagePoolExhausted:
                        if self.prefix_cache is None or \
                                not self.prefix_cache.evict_lru():
                            raise
        except PagePoolExhausted:
            if node is not None:
                self.prefix_cache.release(node)
            raise
        return node, cursor

    def _reserve_pages(self, slot: int, total: int):
        """`PagedSlotPool.reserve` with pressure relief: zero-ref
        retained prefix holds yield their pages to live admissions,
        LRU-first, until the reservation fits or nothing is left."""
        while True:
            try:
                self.pool.reserve(slot, total)
                return
            except PagePoolExhausted:
                if self.prefix_cache is None or \
                        not self.prefix_cache.evict_lru():
                    raise

    def _begin_request(self, slot: int, h: RequestHandle):
        """Admission: claim the longest cached prefix (row mode: jitted
        row copy + suffix-only prefill; paged mode: read-only page
        attach + page reservation), then either whole-prompt prefill
        (short cold prompts — the PR-4 path, one compile per bucket) or
        enter the chunked-prefill state machine."""
        t_adm0 = time.perf_counter()
        rec = h._ledger_rec
        pfx0 = 0.0
        if rec is not None:
            rec.queue_exit(t_adm0)   # queue_wait ends; admission begins
            pfx0 = rec.phases['prefix_lookup']
        s = len(h.prompt_tokens)
        cursor = 0
        src = slot
        node = None
        if h.adapter_id is not None:
            # pin BEFORE the prefix lookup: the namespace key needs the
            # version this request will actually decode under (pin()
            # hot-swaps to the store's latest good version, so this is
            # also where a published v2 takes effect for new requests).
            # AdapterUnavailable propagates as a request-level failure.
            pin, version = self.adapter_bank.pin(h.adapter_id)
            h._adapter_pin = pin
            h.adapter_version = version
            self._adapter_rows[slot] = pin
        else:
            self._adapter_rows[slot] = 0
        if self._paged:
            # seating raises PagePoolExhausted BEFORE any bookkeeping:
            # the handle stays queueable for the requeue path (the
            # adapter pin must roll back with it)
            try:
                node, cursor = self._seat_paged(slot, h, s)
            except PagePoolExhausted:
                self._unpin_adapter(slot, h)
                raise
            if node is not None:
                h._prefix_node = node
                h._prefix_len = cursor
        elif self.prefix_cache is not None:
            t_pfx = time.perf_counter()
            node, matched = self.prefix_cache.lookup(
                h.prompt_tokens, namespace=self._prefix_ns(h))
            if node is not None:
                self.prefix_cache.acquire(node)
                h._prefix_node = node
                h._prefix_len = matched
                cursor = matched
                src = node.slot
            if rec is not None:
                t1 = time.perf_counter()
                rec.add('prefix_lookup', t1 - t_pfx, now=t1)
        if h._queue_span is not None:
            h._queue_span.end()   # admission closes the queue span
            h._queue_span = None
        self._slot_req[slot] = h
        h.status = RUNNING
        # the no-mixed-version guarantee: stamped ONCE, here — a hot
        # swap requires a drained engine, so every token this request
        # emits decodes under this version
        h.weight_version = self.weight_version
        if rec is not None:
            # admission = seating work since queue exit, minus the
            # prefix-lookup seconds already booked inside this window
            # (phases stay non-overlapping in seconds)
            t1 = time.perf_counter()
            rec.add('admission', (t1 - t_adm0)
                    - (rec.phases['prefix_lookup'] - pfx0), now=t1)
        if node is not None:
            _obs.emit('prefix_hit', request_id=h.request_id,
                      matched=h._prefix_len, prompt_len=s, slot=slot)
        if cursor >= s:
            # full-prompt hit: ZERO prefill — row mode copies the
            # retained row; paged mode already shares the pages — then
            # the pending token re-forwards the last prompt position
            if not self._paged:
                self.pool.copy_slot(src, slot)
            self.pool.note_written(slot, s)
            self._activate(slot, h)
            return
        chunk = self.prefill_chunk_tokens
        if cursor == 0 and (chunk is None or s <= chunk):
            self._whole_prefill(slot, h)
            self._activate(slot, h)
            return
        # suffix and/or long prompt: per-slot cursor, one bucket-shaped
        # chunk per scheduler iteration (the first lands this step via
        # _advance_prefills, gathering its KV floor from `src` — the
        # retained row on a prefix hit); the slot stays inactive for
        # decode — its position parks at the last row, where stray
        # inactive-row KV writes land above every live position
        self._pos[slot] = self.pool.max_length - 1
        self._tok[slot] = 0
        self._active[slot] = False
        self._prefilling[slot] = [h, cursor, src]
        self._counts['chunked_prefills'] += 1

    def _note_prefill(self, h: RequestHandle, t0: float):
        """Ledger: the prefill that just ran books as `prefill` for its
        owner and `prefill_wait` for every OTHER seated request — the
        chunked-prefill convoy, named instead of smeared."""
        now = time.perf_counter()
        _reqledger.get_ledger().note_prefill(
            now - t0, h._ledger_rec,
            [o._ledger_rec for o in self._slot_req.values()], now=now)

    def _whole_prefill(self, slot: int, h: RequestHandle):
        s = len(h.prompt_tokens)
        bucket = self.pool.bucket_for(s)
        t_pf0 = time.perf_counter()
        with _obs.span('serving.prefill', request_id=h.request_id,
                       bucket=bucket, slot=slot, prompt_len=s):
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :s] = h.prompt_tokens
            ids_dev = call_with_retry(_to_device, ids, policy=self._retry,
                                      site='serving.h2d')
            if self._paged:
                pages, scales = self.pool.device_state()
                table = call_with_retry(
                    _to_device, self.pool.page_table[slot:slot + 1],
                    policy=self._retry, site='serving.h2d')
                new_pages, new_scales = self._prefill_jit(
                    self._params, self._frozen, self._buffers,
                    pages, scales, table, ids_dev,
                    *self._adapter_args(slot))
                self.pool.set_device_state(new_pages, new_scales)
            else:
                # row in, row out: the undonated copy surface is pool/N
                self.pool.set_row(slot, self._prefill_jit(
                    self._params, self._frozen, self._buffers, ids_dev,
                    *self._adapter_args(slot)))
        self.pool.note_written(slot, s)
        self._note_prefill(h, t_pf0)
        self._counts['prefills'] += 1
        self._counts['prefill_tokens'] += s
        if _obs.enabled():
            self._m_prefills.labels(bucket=bucket).inc()
            self._m_prefill_tokens.inc(s)

    def _advance_prefills(self):
        """Drive every mid-prefill slot forward one bucket-shaped chunk
        (FCFS by admission). A slot whose cursor reaches the prompt end
        activates for decode in the same round."""
        for slot in list(self._prefilling):
            h, cursor, src = self._prefilling[slot]
            try:
                self._prefill_chunk(slot, h, cursor, src)
            except Exception as exc:
                self._detach_slot(slot, h)
                self.pool.free(slot)
                h._fail(exc)
                self._counts['failed'] += 1
                if _obs.enabled():
                    self._m_requests.labels(status='failed').inc()
                    _obs.emit('serving_request_failed',
                              request_id=h.request_id,
                              error=type(exc).__name__)

    def _prefill_chunk(self, slot: int, h: RequestHandle, cursor: int,
                       src: int):
        s = len(h.prompt_tokens)
        c = min(self.prefill_chunk_tokens or s, s - cursor)
        bucket = self.pool.bucket_for(c)
        # tail chunks whose bucket would overrun the slot shift their
        # window start down and RE-forward already-prefilled tokens —
        # an identical KV overwrite (the pending-token trick), so the
        # window always fits and pad queries stay above the prompt
        start = min(cursor, self.pool.max_length - bucket)
        window = h.prompt_tokens[start:start + bucket]
        t_pf0 = time.perf_counter()
        with _obs.span('serving.prefill_chunk', request_id=h.request_id,
                       bucket=bucket, slot=slot, start=start,
                       cursor=cursor, prompt_len=s):
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :len(window)] = window
            ids_dev = call_with_retry(_to_device, ids, policy=self._retry,
                                      site='serving.h2d')
            if self._paged:
                # the slot's own table carries any attached prefix
                # pages, so there is no src row: the chunk gathers,
                # attends, and scatters through the table. The floor
                # (the page-aligned attach boundary) keeps tail-shifted
                # duplicate writes out of the shared pages.
                pages, scales = self.pool.device_state()
                table = call_with_retry(
                    _to_device, self.pool.page_table[slot:slot + 1],
                    policy=self._retry, site='serving.h2d')
                new_pages, new_scales = self._chunk_prefill_jit(
                    self._params, self._frozen, self._buffers,
                    pages, scales, table, ids_dev, jnp.int32(start),
                    jnp.int32(h._prefix_len), *self._adapter_args(slot))
                self.pool.set_device_state(new_pages, new_scales)
            else:
                # forwards against the src ROW (the retained row on a
                # prefix hit's first chunk, the slot's own row after);
                # returns the slot's new row — one-row surface either way
                self.pool.set_row(slot, self._chunk_prefill_jit(
                    self._params, self._frozen, self._buffers,
                    self.pool.row(src), ids_dev, jnp.int32(start),
                    *self._adapter_args(slot)))
        new_cursor = min(start + bucket, s)
        self.pool.note_written(slot, new_cursor)
        self._note_prefill(h, t_pf0)
        self._prefilling[slot][1] = new_cursor
        self._prefilling[slot][2] = slot   # later chunks extend own row
        self._counts['chunk_rounds'] += 1
        self._counts['prefill_tokens'] += new_cursor - cursor
        if _obs.enabled():
            self._m_chunk_rounds.inc()
            self._m_chunk_tokens.inc(new_cursor - cursor)
            self._m_prefill_tokens.inc(new_cursor - cursor)
        if new_cursor >= s:
            del self._prefilling[slot]
            self._activate(slot, h)

    def _activate(self, slot: int, h: RequestHandle):
        """Prompt KV complete (prefilled, copied, or both): arm the slot
        for decode. The pending token is the LAST prompt token at
        position s-1 — the next decode round re-forwards it (identical
        KV overwrite) and its sampled output is the request's first
        generated token."""
        p = h.params
        s = len(h.prompt_tokens)
        if self.draft_model is not None:
            self._draft_prefill(slot, h)
        greedy = p.strategy == GREEDY
        key = (np.zeros(2, np.uint32) if greedy else np.asarray(  # paddle-lint: disable=host-sync -- once per admission, not per round: seeds the per-slot sampling key row
            jax.random.PRNGKey(h.request_id if p.seed is None
                               else p.seed), np.uint32))
        self._tok[slot] = h.prompt_tokens[-1]
        self._pos[slot] = s - 1
        self._steps[slot] = 0
        self._active[slot] = True
        self._temp[slot] = p.temperature
        self._topk[slot] = p.top_k
        self._topp[slot] = p.top_p
        self._greedy[slot] = greedy
        self._keys[slot] = key
        self._eos_arr[slot] = h._eos

    def _draft_prefill(self, slot: int, h: RequestHandle):
        """Whole-bucket prompt prefill into the DRAFT pool row (the
        draft proposes from its own KV). Runs once at activation —
        deliberately un-chunked and un-cached: the draft is small, and
        keeping its path trivial keeps the compiled set bounded."""
        s = len(h.prompt_tokens)
        bucket = self.pool.bucket_for(s)
        d_params, d_frozen, d_buffers = self._draft_state
        t_pf0 = time.perf_counter()
        with _obs.span('serving.draft_prefill', request_id=h.request_id,
                       bucket=bucket, slot=slot):
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :s] = h.prompt_tokens
            ids_dev = call_with_retry(_to_device, ids, policy=self._retry,
                                      site='serving.h2d')
            self.draft_pool.set_row(slot, self._draft_prefill_jit(
                d_params, d_frozen, d_buffers, ids_dev))
        self._note_prefill(h, t_pf0)

    def _retire(self, slot: int, h: RequestHandle, now: float):
        h._finish(now)
        self._detach_slot(slot, h)
        retained = False
        if self.prefix_cache is not None:
            # retention costs nothing: the slot's rows [0, prompt_len)
            # ARE the prompt's prefill KV (generated-token KV above is
            # stale-by-construction for the next user). Adapter prefill
            # KV carries the adapter's deltas — it retains under the
            # (adapter_id, version) namespace, never the base trie.
            retained = self.prefix_cache.insert(
                h.prompt_tokens, slot, namespace=self._prefix_ns(h))
        if not retained:
            self.pool.free(slot)
        self._counts['completed'] += 1
        if _obs.enabled():
            self._m_requests.labels(status='completed').inc()
            self._m_active.set(len(self._slot_req))
            tpot = h.tpot
            if tpot is not None:
                self._m_tpot.observe(tpot)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Host-side counters + compile-trace counts (the zero-recompile
        assertions read `traces`: after warmup it must stop growing
        across admissions)."""
        out = {
            'submitted': self._counts['submitted'],
            'completed': self._counts['completed'],
            'failed': self._counts['failed'],
            'tokens': self._counts['tokens'],
            'prefills': self._counts['prefills'],
            'prefill_tokens': self._counts['prefill_tokens'],
            'decode_rounds': self._counts['decode_rounds'],
            'decode_steps': self._counts['decode_steps'],
            'chunked_prefills': self._counts['chunked_prefills'],
            'chunk_rounds': self._counts['chunk_rounds'],
            'queue_depth': self.scheduler.queue_depth,
            'active_slots': len(self._slot_req),
            'weight_version': self.weight_version,
            'donate_pool': self._donate_pool,
            'kv_layout': 'paged' if self._paged else 'row',
            'traces': dict(self._trace_counts),
            'pool': self.pool.stats(),
        }
        if self.prefix_cache is not None:
            out['prefix_cache'] = self.prefix_cache.stats()
        if self.adapter_bank is not None:
            out['adapters'] = self.adapter_bank.stats()
        if self.draft_model is not None:
            proposed = self._counts['spec_proposed']
            out['spec'] = {
                'k': self.spec_k,
                'rounds': self._counts['spec_rounds'],
                'proposed': proposed,
                'accepted': self._counts['spec_accepted'],
                'acceptance_rate': (self._counts['spec_accepted']
                                    / proposed if proposed else 0.0),
            }
        return out

    def reset_stats(self):
        """Zero the host-side counters (trace counts survive — they
        track compiles, which persist in the jit caches)."""
        self._counts.clear()
