"""paddle_tpu.serving — continuous-batching inference engine.

The batch-synchronous `generate()` path admits a whole batch together
and every sequence waits for the slowest one. This subsystem serves
heavy mixed-length traffic instead: an `InferenceEngine` owning a
preallocated fixed-slot KV-cache pool (`kv_pool.SlotPool`, N slots x
max_length with length-bucketed prefill), an iteration-level FCFS
scheduler (`scheduler.FCFSScheduler`) that admits and retires requests
BETWEEN decode steps (Orca, OSDI'22; pooled-cache management after
vLLM/PagedAttention, SOSP'23 — fixed slots instead of paged blocks
because TPU programs want static shapes), and ONE compiled decode step
carrying per-slot positions, active mask, and sampling params as
arrays. Greedy outputs are token-for-token identical to `generate()`;
everything reports into the shared observability registry
(`paddle_serving_*`), and host<->device transfers ride the resilience
retry layer with request-level (not engine-level) failure.

    from paddle_tpu.serving import InferenceEngine, SamplingParams

    eng = InferenceEngine(model, num_slots=8, max_length=256)
    h = eng.submit(prompt_ids, SamplingParams(max_new_tokens=32))
    for tok in h.stream():
        ...                       # per-token, as slots advance
    hs = eng.generate_many(prompts)   # continuous-batched batch API

Latency stack (ISSUE 9), all composable and parity-preserving: a radix
prefix cache over the slot pool (`prefix_cache.py` — shared prompt
prefixes prefill once), chunked prefill (`prefill_chunk_tokens=` —
long prompts interleave with decode rounds instead of stalling TTFT),
and per-slot speculative decoding (`draft_model=` — k draft proposals
verified in one target forward, exactly greedy for any draft):

    eng = InferenceEngine(model, num_slots=16, max_length=256,
                          prefix_cache=0.25, prefill_chunk_tokens=32,
                          draft_model=draft)

Paged KV (ISSUE 16): `kv_page_size=` switches the pool to the
page-table layout (`kv_pool.PagedSlotPool`) — fixed-size pages with
per-slot page tables, reservation-based admission, copy-on-write
page sharing through the prefix cache (`PagedPrefixCache`), optional
`kv_quant='int8'` with per-(page, head) scales, and `kv_pages=` to
oversubscribe HBM so short requests admit at page (not slot-row)
granularity. Greedy outputs stay bit-identical to the row pool:

    eng = InferenceEngine(model, num_slots=32, max_length=256,
                          kv_page_size=16, kv_pages=257,
                          prefix_cache=0.25)

Fleet layer (`router.py` + `tenancy.py`): a `Router` over a
`ReplicaSet` of N engines adds health-checked least-loaded placement,
mid-flight failover with per-replica circuit breakers, and per-tenant
QoS (token-bucket rates, concurrency caps, priority classes, typed
fast-fail load shedding):

    from paddle_tpu.serving import ReplicaSet, Router
    router = Router(ReplicaSet(model, 2, num_slots=8, max_length=256),
                    tenants='paid:priority=high;free:priority=low,rate=2',
                    shed_queue_depth=64)
    h = router.submit(prompt_ids, tenant='paid')

Online weight updates (`hotswap.py`, ISSUE 12): a trainer-side
`WeightPublisher` streams versioned, sha256-manifested snapshots into a
`WeightStore`; a `ReplicaUpdater` rolls them across the router's
replicas one at a time (drain → swap → health-gate → rejoin) with zero
dropped requests, zero XLA recompiles, version-tagged responses, and
automatic rollback + quarantine on a failed gate:

    from paddle_tpu.serving import (WeightStore, WeightPublisher,
                                    ReplicaUpdater)
    store = WeightStore('/ckpt/weights')
    publisher = WeightPublisher(train_model, store, interval_steps=50)
    updater = ReplicaUpdater(router, store)
    ...                      # trainer: publisher.maybe_publish(step)
    updater.poll()           # server: swap when a new version lands

Goodput-driven autoscaling (`autoscaler.py`, ISSUE 14): an
`Autoscaler` grows/shrinks the fleet from the router's sliding-window
signals (TTFT p99 vs SLO, queued work per replica, capacity-shed rate)
with hysteresis and cooldown so it never flaps; scale-up provisions
through the shared ProgramStore (the new replica loads, not compiles)
and accounts for the measured provision latency, scale-down reuses the
graceful-drain path so no request drops. `paddle_tpu.loadgen` builds
the deterministic Poisson/diurnal/burst traffic to drive it — the full
loop in ten lines:

    from paddle_tpu import loadgen
    from paddle_tpu.serving import (Autoscaler, AutoscalerConfig,
                                    InferenceEngine, ReplicaSet, Router)
    eng_kw = dict(num_slots=8, max_length=256)
    router = Router(ReplicaSet(model, 1, **eng_kw), shed_queue_depth=64)
    scaler = Autoscaler(router, lambda: InferenceEngine(model, **eng_kw),
                        AutoscalerConfig(max_replicas=4, slo_ttft_s=0.5))
    trace = loadgen.make_trace(
        loadgen.DiurnalSchedule(2.0, 20.0, period_s=120.0), 120.0,
        seed=7, prompt_lengths=loadgen.LognormalLengths(12, 0.6, 4, 64))
    print(loadgen.LoadReplayer(router, trace, autoscaler=scaler)
          .run().report(slo_ttft_s=0.5))

Process fleet runtime (`remote.py` / `replica_main.py` /
`supervisor.py`, ISSUE 18): replicas become supervised OS processes.
A `Supervisor` spawns `python -m paddle_tpu.serving.replica_main`
children that warm-start from the shared ProgramStore (load, never
compile) and pull weights from the `WeightStore`; the parent talks to
each over a checksummed framed RPC socket through a `RemoteReplica` —
the same duck-type surface as an in-process engine, so Router
placement, QoS, breakers, failover, hot-swap rollouts, and the
Autoscaler work unchanged across the process boundary. SIGKILL a
replica mid-decode and the router fails its accepted requests over to
survivors bit-exactly while the supervisor respawns the victim
(backoff + jitter, crash-loop quarantine, hang detection, orphan
reaping):

    from paddle_tpu.serving import (ReplicaSpec, Router, Replica,
                                    Supervisor)
    spec = ReplicaSpec('my_models:tiny_gpt',
                       engine_kwargs=dict(num_slots=8, max_length=256),
                       program_store_dir='/store/programs',
                       weight_store_dir='/store/weights')
    sup = Supervisor('/run/fleet', spec)
    router = Router([Replica(i, sup.spawn()) for i in range(2)])
    scaler = Autoscaler(router, sup.replica_factory(), config)

Multi-tenant adapter serving (`adapters/`, ISSUE 19): an
`AdapterBank` packs up to `capacity` LoRA adapters as device-resident
`[capacity+1, ...]` A/B factor banks per target projection (slot 0 =
the base model's zero delta). Per-slot adapter indices flow through
decode/prefill/spec programs as ARRAY inputs — one compiled decode
block serves any adapter mix, with zero recompiles across mixes and
hot-swaps. Requests pin their adapter version at admission (publish
never disturbs a pinned slot; LRU eviction only claims zero-ref
slots), the radix prefix cache namespaces on (adapter_id, version),
and tenants may carry a default `adapter=` in their spec; a missing
adapter fast-fails typed as
`AdmissionRejected(reason='adapter_unavailable')`:

    from paddle_tpu.serving import AdapterBank, InferenceEngine
    bank = AdapterBank(model, capacity=8, rank=8)
    eng = InferenceEngine(model, num_slots=8, max_length=256,
                          adapter_bank=bank)
    bank.load('tenant-a', factors_a)       # or publish()/store-backed
    h = eng.submit(prompt_ids, adapter_id='tenant-a')

Flags: `FLAGS_autoscale` (gate the poll loop),
`FLAGS_autoscale_min_replicas` / `FLAGS_autoscale_max_replicas`
(fleet bounds), `FLAGS_autoscale_cooldown_s` (decision spacing); all
env-overridable. Every decision emits an `autoscale_*` event, and the
goodput ledger books provisioning/retirement under the `scale_up` /
`scale_down` categories — the bench's proof the machinery costs <3%.
"""
from __future__ import annotations

from .adapters import (AdapterBank, AdapterUnavailable,
                       make_adapter_factors)
from .api import (FAILED, FINISHED, GREEDY, PRIORITY_HIGH, PRIORITY_LOW,
                  PRIORITY_NAMES, PRIORITY_NORMAL, QUEUED, RUNNING,
                  SAMPLING, RequestHandle, SamplingParams)
from .autoscaler import Autoscaler, AutoscalerConfig
from .engine import InferenceEngine, sample_rows
from .hotswap import (CanaryGate, ReplicaUpdater, SwapFailed,
                      WeightLoadError, WeightPublisher, WeightStore,
                      finite_weights_gate)
from .kv_pool import (PageHold, PagePoolExhausted, PagedSlotPool,
                      PromptTooLongError, SlotPool, default_buckets)
from .prefix_cache import PagedPrefixCache, RadixPrefixCache
from .remote import (FrameChecksumError, IncompleteFrameError,
                     RemoteFatalError, RemoteReplica, RemoteTransientError,
                     RpcClient)
from .router import (CircuitBreaker, Replica, ReplicaFailure, ReplicaSet,
                     Router, RouterHandle)
from .scheduler import FCFSScheduler
from .supervisor import ReplicaSpec, Supervisor
from .tenancy import (AdmissionRejected, Tenant, TenantRegistry,
                      TokenBucket, estimate_queue_rounds,
                      parse_tenant_spec, prefill_rounds)

__all__ = [
    'FAILED', 'FINISHED', 'GREEDY', 'QUEUED', 'RUNNING', 'SAMPLING',
    'PRIORITY_HIGH', 'PRIORITY_NORMAL', 'PRIORITY_LOW', 'PRIORITY_NAMES',
    'RequestHandle', 'SamplingParams', 'InferenceEngine', 'sample_rows',
    'SlotPool', 'default_buckets', 'FCFSScheduler', 'RadixPrefixCache',
    'PagedSlotPool', 'PagedPrefixCache', 'PageHold',
    'PagePoolExhausted', 'PromptTooLongError',
    'CircuitBreaker', 'Replica', 'ReplicaFailure', 'ReplicaSet',
    'Router', 'RouterHandle',
    'AdmissionRejected', 'Tenant', 'TenantRegistry', 'TokenBucket',
    'parse_tenant_spec', 'prefill_rounds', 'estimate_queue_rounds',
    'CanaryGate', 'ReplicaUpdater', 'SwapFailed', 'WeightLoadError',
    'WeightPublisher', 'WeightStore', 'finite_weights_gate',
    'Autoscaler', 'AutoscalerConfig',
    'RemoteReplica', 'RpcClient', 'IncompleteFrameError',
    'FrameChecksumError', 'RemoteTransientError', 'RemoteFatalError',
    'ReplicaSpec', 'Supervisor',
    'AdapterBank', 'AdapterUnavailable', 'make_adapter_factors',
]
