"""Goodput-driven autoscaling: grow/shrink the ReplicaSet from the
signals the fleet already publishes.

The replica count has been static since PR 7; every ingredient for
closing the loop exists — the router's windowed TTFT/queue/shed
signals, the ProgramStore warm path that made replica provisioning
8.8x cheaper than a cold compile (PR 8), and the graceful-drain path
that retires an engine without dropping a request (PR 6). The
`Autoscaler` is the policy loop over those parts:

- **Signals, not guesses.** Decisions read `Router.window_signals()`:
  sliding-window TTFT p99 against the SLO, queued-work depth per
  serving replica, and the capacity-shed rate. Windowed — a burst that
  ended a minute ago ages out instead of arguing for more replicas,
  and (the shed-accounting invariant) rejected work never appears as
  demand.
- **Hysteresis + cooldown, so the fleet never flaps.** Scale-up and
  scale-down fire on DIFFERENT thresholds with a dead band between
  them, scale-down additionally requires the quiet signal to have held
  for a full `down_stable_s`, and any action starts a cooldown during
  which the loop only observes. One decision per poll, one replica per
  decision.
- **Provisioning pays — so the decision accounts for it.** Scale-up
  builds the new engine through the shared ProgramStore (identical
  program keys as its siblings: it LOADS, it does not compile), the
  measured provision latency feeds an EMA, and the post-scale-up
  cooldown is extended by that EMA: while a replica is still warming
  into usefulness, its cost must not be misread as "scale-up didn't
  help, add another".
- **Scale-down drains, never drops.** The victim is cordoned via the
  same `begin_drain` path preemption uses (scoped `draining` excludes
  it from placement; router steps keep finishing its accepted work)
  and is only removed once its engine holds zero work.
- **Every decision is attributable.** Actions emit `autoscale_*`
  events; provisioning runs under the `autoscale.provision` span and
  retirement under `autoscale.retire`, which the goodput ledger books
  as the new `scale_up` / `scale_down` categories — so the bench can
  PROVE the added machinery costs <3% of wall time, with the ledger
  still closing within 1%.

Flags (env-overridable like every FLAGS_*): `FLAGS_autoscale` gates
the loop (`poll()` is a no-op when off unless the autoscaler was built
with `force=True`), `FLAGS_autoscale_min_replicas` /
`FLAGS_autoscale_max_replicas` bound the fleet, and
`FLAGS_autoscale_cooldown_s` is the default decision cooldown.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

from .. import flags as _flags
from .. import observability as _obs
from .engine import InferenceEngine
from .router import Replica, Router

_flags.register_flag('FLAGS_autoscale', True)
_flags.register_flag('FLAGS_autoscale_min_replicas', 1)
_flags.register_flag('FLAGS_autoscale_max_replicas', 4)
_flags.register_flag('FLAGS_autoscale_cooldown_s', 10.0)

# decision strings poll() returns (and counts per action)
HOLD = 'hold'
HOLD_COOLDOWN = 'hold_cooldown'
HOLD_AT_MAX = 'hold_at_max'
HOLD_AT_MIN = 'hold_at_min'
SCALE_UP = 'scale_up'
SCALE_DOWN = 'scale_down'
DISABLED = 'disabled'


@dataclasses.dataclass
class AutoscalerConfig:
    """Policy knobs. The defaults encode the hysteresis shape, not any
    particular hardware: tune `slo_ttft_s` and the queue thresholds to
    the deployment, keep up-thresholds strictly above down-thresholds
    (validated) so there is always a dead band."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: the latency objective scale decisions are judged against
    slo_ttft_s: float = 1.0
    #: scale up when windowed TTFT p99 exceeds slo * this
    up_ttft_frac: float = 0.8
    #: scale down only while TTFT p99 is under slo * this
    down_ttft_frac: float = 0.3
    #: scale up when windowed p99 queued requests per serving replica
    #: exceeds this — p99, not median, because flash crowds backlog the
    #: queue for a small fraction of the window and a median would
    #: average them away (the router samples queue depth time-uniformly,
    #: so the quantile is over wall time, not over step count)
    up_queue_per_replica: float = 4.0
    #: scale down only while p99 queued per serving replica is under
    #: this — even the window's worst moment must be quiet
    down_queue_per_replica: float = 0.5
    #: any capacity shedding in the window is a scale-up vote
    up_on_shed: bool = True
    #: seconds between decisions (both directions)
    cooldown_s: float = 10.0
    #: extra post-scale-up cooldown per second of measured provision
    #: latency (the provision-latency accounting: a fleet whose
    #: replicas take 30 s to warm must not re-judge demand after 10)
    provision_cooldown_factor: float = 1.0
    #: the quiet signal must hold this long before a scale-down fires
    down_stable_s: float = 10.0

    @classmethod
    def from_flags(cls, **overrides) -> 'AutoscalerConfig':
        base = dict(
            min_replicas=int(_flags.flag('FLAGS_autoscale_min_replicas')),
            max_replicas=int(_flags.flag('FLAGS_autoscale_max_replicas')),
            cooldown_s=float(_flags.flag('FLAGS_autoscale_cooldown_s')))
        base.update(overrides)
        return cls(**base)

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError('need 1 <= min_replicas <= max_replicas')
        if self.slo_ttft_s <= 0:
            raise ValueError('slo_ttft_s must be positive')
        if self.down_ttft_frac >= self.up_ttft_frac:
            raise ValueError('hysteresis requires down_ttft_frac < '
                             'up_ttft_frac (a dead band)')
        if self.down_queue_per_replica >= self.up_queue_per_replica:
            raise ValueError('hysteresis requires down_queue_per_replica '
                             '< up_queue_per_replica (a dead band)')
        if self.cooldown_s < 0 or self.down_stable_s < 0:
            raise ValueError('cooldown_s/down_stable_s must be >= 0')


class Autoscaler:
    """The policy loop. Drive it by calling `poll()` from the serving
    event loop (the LoadReplayer does; a deployment would call it
    between router steps) — it is cheap when nothing changes: one
    window_signals() read and a few comparisons.

    Args:
        router: the Router whose ReplicaSet is managed.
        replica_factory: zero-arg callable returning a fresh
            `InferenceEngine` over the SAME weights/geometry as the
            existing replicas (so it resolves identical ProgramStore
            keys — the warm provision path). `ReplicaSet`-style
            construction: `lambda: InferenceEngine(model, **kw)`.
        config: AutoscalerConfig (default: from flags).
        clock: injectable monotonic clock (tests).
        force: run even while `FLAGS_autoscale` is off (benches that
            A/B the loop explicitly).
        signal_source: optional zero-arg callable returning a
            `window_signals()`-shaped dict — plug in an
            `observability.FleetSignalSource` so decisions read the
            FLEET view (routers in other processes) instead of the
            local router's registry. None keeps the local read.
    """

    def __init__(self, router: Router,
                 replica_factory: Callable[[], InferenceEngine],
                 config: Optional[AutoscalerConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 force: bool = False,
                 signal_source: Optional[Callable[[], dict]] = None):
        self.router = router
        self.replica_factory = replica_factory
        self.config = config or AutoscalerConfig.from_flags()
        self._clock = clock
        self._force = bool(force)
        self.signal_source = signal_source
        self._cooldown_until: Optional[float] = None
        self._quiet_since: Optional[float] = None
        self._draining: Dict[int, float] = {}    # rid -> drain start
        self._provision_ema_s: Optional[float] = None
        self._decisions: Dict[str, int] = {}
        self._init_metrics()

    # ------------------------------------------------------------------
    # metrics
    # ------------------------------------------------------------------
    def _init_metrics(self):
        reg = _obs.get_registry()
        self._m_replicas = reg.gauge(
            'paddle_autoscaler_replicas',
            'replicas attached to the autoscaled fleet')
        self._m_draining = reg.gauge(
            'paddle_autoscaler_draining_replicas',
            'replicas cordoned and draining toward removal')
        self._m_decisions = reg.counter(
            'paddle_autoscaler_decisions_total',
            'autoscaler poll outcomes by action', ('action',))
        self._m_provision = reg.histogram(
            'paddle_autoscaler_provision_seconds',
            'wall seconds to provision one replica (engine build + '
            'program-store load)')
        self._m_replica_seconds = reg.counter(
            'paddle_autoscaler_replica_seconds_total',
            'integrated replica-seconds of hardware occupancy while '
            'the autoscaler ran')
        if _obs.enabled():
            self._m_replicas.set(len(self.router.replicas))
            self._m_draining.set(0)
        self._last_integrate: Optional[float] = None

    def _count(self, action: str) -> str:
        self._decisions[action] = self._decisions.get(action, 0) + 1
        if _obs.enabled():
            self._m_decisions.labels(action=action).inc()
        return action

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._force or bool(_flags.flag('FLAGS_autoscale'))

    @property
    def provision_ema_s(self) -> Optional[float]:
        """Measured provision-latency EMA (None before the first
        scale-up); feeds the post-scale-up cooldown extension."""
        return self._provision_ema_s

    def active_replicas(self) -> int:
        """Attached and NOT cordoned for removal."""
        return len(self.router.replicas) - len(self._draining)

    def poll(self, now: Optional[float] = None) -> str:
        """One control iteration: finish pending drains, read the
        windowed signals, make at most ONE scaling decision. Returns
        the decision string (metrics count the same names)."""
        if not self.enabled:
            return DISABLED
        now = self._clock() if now is None else now
        self._integrate(now)
        self._advance_drains(now)
        cfg = self.config
        sig = (self.signal_source() if self.signal_source is not None
               else self.router.window_signals())
        want_up, up_why = self._wants_scale_up(sig)
        if self._cooldown_until is not None and now < self._cooldown_until:
            # observe-only window; still note a blocked scale-up WISH so
            # thrash analysis can tell "held by cooldown" from "quiet"
            if want_up:
                return self._count(HOLD_COOLDOWN)
            self._track_quiet(sig, now)
            return self._count(HOLD)
        if want_up:
            self._quiet_since = None
            if self.active_replicas() >= cfg.max_replicas:
                return self._count(HOLD_AT_MAX)
            self._scale_up(now, up_why, sig)
            return self._count(SCALE_UP)
        if self._track_quiet(sig, now) \
                and now - self._quiet_since >= cfg.down_stable_s:
            if self.active_replicas() <= cfg.min_replicas:
                return self._count(HOLD_AT_MIN)
            self._scale_down(now, sig)
            return self._count(SCALE_DOWN)
        return self._count(HOLD)

    # ------------------------------------------------------------------
    # signal interpretation
    # ------------------------------------------------------------------
    def _wants_scale_up(self, sig: dict):
        cfg = self.config
        serving = max(sig['serving_replicas'], 1)
        if cfg.up_on_shed and sig['shed_rate'] > 0:
            return True, f'shedding {sig["shed_rate"]:.2f}/s'
        if sig['ttft_p99'] is not None \
                and sig['ttft_p99'] > cfg.slo_ttft_s * cfg.up_ttft_frac:
            return True, (f'ttft p99 {sig["ttft_p99"]:.3f}s > '
                          f'{cfg.up_ttft_frac:.0%} of SLO')
        if sig['queue_p99'] is not None \
                and sig['queue_p99'] / serving > cfg.up_queue_per_replica:
            return True, (f'queue p99 {sig["queue_p99"]:.1f} over '
                          f'{serving} serving replicas')
        return False, ''

    def _is_quiet(self, sig: dict) -> bool:
        """The scale-down side of the dead band: EVERY signal must sit
        under its (lower) threshold, and the queue signal must actually
        have data — no evidence is not evidence of idleness enough to
        give hardware back on."""
        cfg = self.config
        serving = max(sig['serving_replicas'], 1)
        if sig['shed_rate'] > 0:
            return False
        if sig['ttft_p99'] is not None \
                and sig['ttft_p99'] > cfg.slo_ttft_s * cfg.down_ttft_frac:
            return False
        if sig['queue_p99'] is None:
            return False
        return sig['queue_p99'] / serving <= cfg.down_queue_per_replica

    def _track_quiet(self, sig: dict, now: float) -> bool:
        if self._is_quiet(sig):
            if self._quiet_since is None:
                self._quiet_since = now
            return True
        self._quiet_since = None
        return False

    # ------------------------------------------------------------------
    # actions
    # ------------------------------------------------------------------
    def _scale_up(self, now: float, why: str, sig: dict):
        cfg = self.config
        t0 = self._clock()
        with _obs.span('autoscale.provision'):
            engine = self.replica_factory()
            replica = self.router.add_replica(engine)
        provision_s = self._clock() - t0
        self._provision_ema_s = (
            provision_s if self._provision_ema_s is None
            else 0.5 * self._provision_ema_s + 0.5 * provision_s)
        # provision-latency accounting: demand is not re-judged until
        # the new replica has plausibly warmed into the signal window —
        # anchored at the moment provisioning FINISHED (the provision
        # itself consumed wall time) and extended by the measured
        # provision EMA
        self._cooldown_until = self._clock() + cfg.cooldown_s \
            + cfg.provision_cooldown_factor * self._provision_ema_s
        self._quiet_since = None
        _obs.emit('autoscale_up', replica=replica.id, reason=why,
                  replicas=len(self.router.replicas),
                  provision_s=round(provision_s, 4),
                  ttft_p99=sig['ttft_p99'], queue_p99=sig['queue_p99'],
                  shed_rate=sig['shed_rate'])
        if _obs.enabled():
            self._m_provision.observe(provision_s)
            self._m_replicas.set(len(self.router.replicas))

    def _pick_victim(self) -> Optional[Replica]:
        """Least outstanding work, newest id breaking ties — retiring
        the most recent arrival keeps the longest-warmed replicas."""
        best = None
        for r in self.router.replicas:
            if r.id in self._draining:
                continue
            score = (r.outstanding_tokens(), -r.id)
            if best is None or score < best[0]:
                best = (score, r)
        return best[1] if best else None

    def _scale_down(self, now: float, sig: dict):
        victim = self._pick_victim()
        if victim is None:
            return
        with _obs.span('autoscale.retire'):
            self.router.drain_replica(victim.id)
        self._draining[victim.id] = now
        self._cooldown_until = now + self.config.cooldown_s
        self._quiet_since = None
        _obs.emit('autoscale_down_begin', replica=victim.id,
                  outstanding_tokens=victim.outstanding_tokens(),
                  replicas=len(self.router.replicas),
                  ttft_p99=sig['ttft_p99'], queue_p99=sig['queue_p99'])
        if _obs.enabled():
            self._m_draining.set(len(self._draining))

    def _advance_drains(self, now: float):
        """Remove cordoned replicas whose engines have fully drained.
        Removal is the SIGTERM-graceful-drain contract: zero queued,
        zero in flight — never a dropped request."""
        if not self._draining:
            return
        for rid, t_begin in list(self._draining.items()):
            r = self.router._by_id.get(rid)
            if r is None:                     # failover already evicted it
                self._draining.pop(rid)
                continue
            if r.engine.has_work:
                continue
            with _obs.span('autoscale.retire'):
                self.router.remove_replica(rid)
                # process-backed replicas (RemoteReplica) tear their OS
                # process down through the supervisor here — SIGTERM →
                # graceful drain → reap; in-process engines have no
                # retire() and just get garbage-collected
                retire = getattr(r.engine, 'retire', None)
                if retire is not None:
                    retire()
            self._draining.pop(rid)
            _obs.emit('autoscale_down_complete', replica=rid,
                      drain_s=round(now - t_begin, 4),
                      replicas=len(self.router.replicas))
        if _obs.enabled():
            self._m_draining.set(len(self._draining))
            self._m_replicas.set(len(self.router.replicas))

    def _integrate(self, now: float):
        """Accumulate replica-seconds (hardware occupancy) — the
        denominator of 'SLO attainment per replica-hour'."""
        if self._last_integrate is not None and _obs.enabled():
            dt = max(now - self._last_integrate, 0.0)
            self._m_replica_seconds.inc(dt * len(self.router.replicas))
        self._last_integrate = now

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        return {
            'enabled': self.enabled,
            'replicas': len(self.router.replicas),
            'active_replicas': self.active_replicas(),
            'draining': sorted(self._draining),
            'decisions': dict(self._decisions),
            'provision_ema_s': self._provision_ema_s,
            'cooldown_until': self._cooldown_until,
            'signal_source': ('local' if self.signal_source is None
                              else type(self.signal_source).__name__),
            'config': dataclasses.asdict(self.config),
        }
