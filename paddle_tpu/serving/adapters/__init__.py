"""paddle_tpu.serving.adapters — multi-tenant LoRA adapter serving.

One base model, thousands of per-tenant fine-tuned variants, ONE
compiled decode program (S-LoRA / Punica). The pieces:

- `bank.AdapterBank` — fixed-capacity device-resident packed A/B
  factor banks per target projection, host-side slot table with
  ref-count pinning + LRU eviction, and hot-load/publish through
  versioned sha256-manifested `WeightStore` manifests.
- `apply.adapter_scope` / `apply.linear_hook` — trace-time segmented
  adapter application: per-row bank slots flow as array inputs into
  the engine's decode/prefill/speculative programs and gather their
  factors via `ops.pallas_kernels.adapter_matmul` (fused pallas kernel
  on TPU, pure-lax reference elsewhere).

    from paddle_tpu.serving import AdapterBank, InferenceEngine
    bank = AdapterBank(model, capacity=8, rank=8, store_dir='/adapters')
    bank.publish('tenant-a', factors_a)
    eng = InferenceEngine(model, num_slots=8, adapter_bank=bank)
    h = eng.submit(prompt, params, adapter_id='tenant-a')
"""
from __future__ import annotations

from .apply import adapter_scope, linear_hook
from .bank import (AdapterBank, AdapterUnavailable, DEFAULT_TARGETS,
                   make_adapter_factors)

__all__ = [
    'AdapterBank', 'AdapterUnavailable', 'DEFAULT_TARGETS',
    'adapter_scope', 'linear_hook', 'make_adapter_factors',
]
