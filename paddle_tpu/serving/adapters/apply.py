"""Trace-time segmented adapter application (ISSUE 19).

The engine's decode/prefill/speculative programs are traced ONCE and
replayed for every request mix, so per-row LoRA deltas cannot live in
python control flow — they must be part of the traced graph, driven
entirely by array inputs (the packed bank factors and a per-row slot
index vector). This module is the trace-time glue:

- `adapter_scope(arrays, rows)` — a context manager the engine wraps
  around each program body's forward calls. It publishes the bank's
  device arrays + the per-row adapter slots to a thread-local, visible
  to every `Linear` the trace touches. Outside the scope (training,
  `generate()`, draft models) the hook is inert, so attaching a bank
  never perturbs any other path.
- `linear_hook(linear, x, y)` — installed on target `Linear` instances
  by `AdapterBank.attach`; adds the segmented LoRA delta
  `adapter_matmul(x, A, B, rows, scale)` to the base projection output
  when a scope is active. Rows pointing at bank slot 0 (the reserved
  all-zero base adapter) receive an exactly-zero delta, so adapter-less
  requests stay bit-identical to a bank-less engine.

Everything row-level is an array input — never a static — so one
compiled program serves any heterogeneous adapter mix with zero
recompiles after warmup (the Punica/S-LoRA property).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ...ops.pallas_kernels import adapter_matmul
from ...tensor import Tensor


class _ScopeState(threading.local):
    def __init__(self):
        self.scope: Optional['_Scope'] = None


_state = _ScopeState()


class _Scope:
    """One active adapter application context: the bank's device arrays
    (`factors[site] = {'a': [C,H,R], 'b': [C,R,O]}` + `scale [C]`) and
    the per-row bank slots `rows [B]` for the current program."""

    __slots__ = ('factors', 'scale', 'rows')

    def __init__(self, factors: Dict[str, Dict[str, Any]], scale, rows):
        self.factors = factors
        self.scale = scale
        self.rows = rows


class adapter_scope:
    """`with adapter_scope(arrays, rows): fwd(...)` — arrays is the
    pytree from `AdapterBank.device_arrays()` (or None for an inert
    scope, so call sites need no branching)."""

    __slots__ = ('_arrays', '_rows', '_prev')

    def __init__(self, arrays: Optional[Dict[str, Any]], rows):
        self._arrays = arrays
        self._rows = rows
        self._prev = None

    def __enter__(self):
        self._prev = _state.scope
        if self._arrays is not None:
            _state.scope = _Scope(self._arrays['factors'],
                                  self._arrays['scale'], self._rows)
        return self

    def __exit__(self, *exc):
        _state.scope = self._prev
        return False


def active_scope() -> Optional[_Scope]:
    return _state.scope


def linear_hook(linear, x, y):
    """Adds the per-row LoRA delta to a tagged Linear's output while an
    adapter scope is active; a no-op otherwise. Installed per-instance
    by `AdapterBank.attach` (the Layer stays ignorant of serving)."""
    sc = _state.scope
    if sc is None:
        return y
    fac = sc.factors.get(linear._adapter_site)
    if fac is None:
        return y
    xv = x.value if isinstance(x, Tensor) else x
    squeeze = False
    if xv.ndim == 2:                       # [B, H] -> [B, 1, H]
        xv = xv[:, None, :]
        squeeze = True
    delta = adapter_matmul(xv, fac['a'], fac['b'], sc.rows, sc.scale)
    if squeeze:
        delta = delta[:, 0, :]
    return y + Tensor(delta)
