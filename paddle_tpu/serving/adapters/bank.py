"""Packed device-resident LoRA adapter bank (ISSUE 19).

Serving millions of users is one base model plus thousands of
per-tenant LoRA variants (S-LoRA, Punica). The `AdapterBank` keeps a
fixed number of adapters resident on device as PACKED factors — one
`[capacity+1, in, rank]` A-bank and one `[capacity+1, rank, out]`
B-bank per target projection, plus a `[capacity+1]` scale vector —
so the decode program gathers each row's factors by index and the
program's avals never change:

- statics carry ONLY (capacity, rank, target-set): compiles stay
  bounded no matter how many adapters cycle through the bank;
- bank slot 0 is the reserved all-zero base adapter (scale 0), so
  adapter-less rows get an exactly-zero delta;
- a host-side slot table maps adapter_id -> (slot, version) with
  ref-count pinning while any request decodes under an adapter and
  LRU eviction of zero-ref slots;
- hot-load/publish rides the versioned sha256-manifested
  `WeightStore` (one per adapter id, under `store_dir/<adapter_id>/`):
  publishing v2 while v1 requests decode never touches v1's slot —
  v1 finishes bit-exact, new pins load v2 into a fresh slot; a
  corrupt/truncated manifest is quarantined with an
  `adapter_load_reject` event and the bank keeps serving the version
  it has.

Slot writes are functional `.at[slot].set` updates on the packed
arrays — same shapes, same avals, zero recompiles across any sequence
of loads, evictions, and hot-swaps.
"""
from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ... import observability as _obs
from ..hotswap import WeightLoadError, WeightStore
from . import apply as _apply

#: attribute-name suffixes of the projections that receive adapters by
#: default: attention qkv/out — the classic LoRA target set
DEFAULT_TARGETS = ('qkv_proj', 'out_proj')

_ADAPTER_ID_RE = re.compile(r'^[A-Za-z0-9._\-]+$')


class AdapterUnavailable(KeyError):
    """Typed miss: the bank cannot pin the named adapter (never loaded,
    store empty/corrupt, or bank full of pinned slots). The router maps
    this onto `AdmissionRejected(reason='adapter_unavailable')`."""

    def __init__(self, adapter_id: str, detail: str = '',
                 transient: bool = False):
        super().__init__(adapter_id)
        self.adapter_id = adapter_id
        self.detail = detail
        # transient=True marks back-pressure (bank full of PINNED
        # slots): pins free as requests retire, so the engine requeues
        # instead of failing — queue_wait books as 'adapter_pinned'
        self.transient = transient

    def __str__(self):
        base = f'adapter {self.adapter_id!r} unavailable'
        return f'{base}: {self.detail}' if self.detail else base


class AdapterBank:
    """Fixed-capacity packed LoRA bank over a model's target Linears.

    `capacity` counts loadable adapter slots (the packed arrays carry
    one extra row: the reserved zero base adapter at slot 0). `rank`
    is the shared LoRA rank — factors of any other rank are rejected
    at load (rank is a static; mixing ranks would mean re-tracing).
    """

    def __init__(self, model, capacity: int = 8, rank: int = 8, *,
                 targets: Sequence[str] = DEFAULT_TARGETS,
                 dtype=jnp.float32, store_dir: Optional[str] = None,
                 keep_versions: int = 4):
        if capacity < 1:
            raise ValueError(f'capacity must be >= 1, got {capacity}')
        if rank < 1:
            raise ValueError(f'rank must be >= 1, got {rank}')
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.dtype = dtype
        self.targets = tuple(targets)
        self.store_dir = store_dir
        self.keep_versions = int(keep_versions)
        # site name -> (in_features, out_features), insertion-ordered
        self.sites: Dict[str, Tuple[int, int]] = {}
        self._tagged: List[Any] = []
        self._attach(model)
        if not self.sites:
            raise ValueError(
                f'no target projections matching {self.targets} found '
                f'on {type(model).__name__} — nothing to adapt')
        rows = self.capacity + 1
        self._a = {s: jnp.zeros((rows, i, self.rank), dtype)
                   for s, (i, o) in self.sites.items()}
        self._b = {s: jnp.zeros((rows, self.rank, o), dtype)
                   for s, (i, o) in self.sites.items()}
        self._scale = jnp.zeros((rows,), jnp.float32)
        # host-side slot table (plain python BY DESIGN: consulted on
        # every admission — see the host-sync hot scope)
        self._keys: List[Optional[str]] = [None] * rows   # slot -> id
        self._versions: List[int] = [0] * rows            # slot -> ver
        self._refs: List[int] = [0] * rows
        self._lru: List[int] = [0] * rows
        self._refs[0] = 1          # slot 0 is never evictable
        self._by_key: Dict[str, int] = {}                 # id -> slot
        self._stores: Dict[str, WeightStore] = {}
        self._tick = 0
        reg = _obs.get_registry()
        self._m_loads = reg.counter(
            'paddle_adapter_loads_total',
            'adapters loaded into a bank slot (fresh or hot-swap)')
        self._m_evict = reg.counter(
            'paddle_adapter_evictions_total',
            'zero-ref adapter slots reclaimed by LRU eviction')
        self._m_pinned = reg.gauge(
            'paddle_adapter_pinned',
            'bank slots currently pinned by in-flight requests')
        self._m_requests = reg.counter(
            'paddle_adapter_requests_total',
            'requests admitted per adapter', ('adapter',))

    # -- model tagging ------------------------------------------------------

    def _attach(self, model):
        suffixes = set(self.targets)
        for name, layer in model.named_sublayers():
            attr = name.rsplit('.', 1)[-1]
            if attr not in suffixes:
                continue
            if not hasattr(layer, 'in_features'):
                continue
            self.sites[name] = (int(layer.in_features),
                                int(layer.out_features))
            layer._adapter_site = name
            layer._adapter_hook = _apply.linear_hook
            self._tagged.append(layer)

    def detach(self):
        """Remove the hooks (tests / model reuse); the bank is dead
        after this."""
        for layer in self._tagged:
            layer.__dict__.pop('_adapter_hook', None)
            layer.__dict__.pop('_adapter_site', None)
        self._tagged = []

    # -- statics / traced inputs --------------------------------------------

    def describe_statics(self) -> Dict[str, Any]:
        """The ONLY bank facts that ride program-store keys: packed
        geometry and the target-site set. Slot contents never appear —
        loading/evicting/hot-swapping adapters can't cause a retrace."""
        return {'capacity': self.capacity, 'rank': self.rank,
                'targets': tuple(sorted(self.sites))}

    def device_arrays(self) -> Dict[str, Any]:
        """The traced-input pytree the engine passes into every
        program call: `{'factors': {site: {'a', 'b'}}, 'scale'}`."""
        return {'factors': {s: {'a': self._a[s], 'b': self._b[s]}
                            for s in self.sites},
                'scale': self._scale}

    # -- slot table ----------------------------------------------------------

    def lookup(self, adapter_id: str) -> Optional[Tuple[int, int]]:
        """(slot, version) if the adapter is resident, else None."""
        slot = self._by_key.get(adapter_id)
        if slot is None:
            return None
        return slot, self._versions[slot]

    def available(self, adapter_id: str) -> bool:
        """True if a pin() could succeed right now: resident, or the
        store holds a committed, non-quarantined version."""
        if adapter_id in self._by_key:
            return True
        store = self._store(adapter_id, create=False)
        if store is None:
            return False
        return any(not store.is_quarantined(v) for v in store.versions())

    def pin(self, adapter_id: str) -> Tuple[int, int]:
        """Pin `adapter_id` for one request; returns (slot, version).
        Loads from the store on a miss, and hot-swaps to the store's
        latest version when it is newer than the resident one (the old
        slot keeps serving its pinned requests bit-exact). Raises
        `AdapterUnavailable` when nothing servable exists."""
        slot = self._by_key.get(adapter_id)
        store = self._store(adapter_id, create=False)
        if store is not None:
            latest = self._latest_good(store)
            if latest is not None and (
                    slot is None or latest > self._versions[slot]):
                loaded = self._load_version(adapter_id, store, latest)
                if loaded is not None:
                    slot = loaded
        if slot is None:
            raise AdapterUnavailable(
                adapter_id, 'not loaded and no servable store version')
        self._refs[slot] += 1
        self._tick += 1
        self._lru[slot] = self._tick
        if _obs.enabled():
            self._m_requests.labels(adapter=adapter_id).inc()
            self._m_pinned.set(self._pinned_count())
        return slot, self._versions[slot]

    def unpin(self, slot: int):
        if slot <= 0:
            return
        if self._refs[slot] <= 0:
            raise RuntimeError(f'unpin of unpinned bank slot {slot}')
        self._refs[slot] -= 1
        if _obs.enabled():
            self._m_pinned.set(self._pinned_count())

    def _pinned_count(self) -> int:
        return sum(1 for s in range(1, self.capacity + 1)
                   if self._refs[s] > 0)

    def _alloc_slot(self, adapter_id: str) -> int:
        free = [s for s in range(1, self.capacity + 1)
                if self._keys[s] is None]
        if free:
            return free[0]
        victims = [s for s in range(1, self.capacity + 1)
                   if self._refs[s] == 0]
        if not victims:
            raise AdapterUnavailable(
                adapter_id, f'bank full: all {self.capacity} slots '
                            f'pinned by in-flight requests',
                transient=True)
        victim = min(victims, key=lambda s: self._lru[s])
        old = self._keys[victim]
        _obs.emit('adapter_evict', adapter=old, slot=victim,
                  version=self._versions[victim])
        if _obs.enabled():
            self._m_evict.inc()
        if old is not None and self._by_key.get(old) == victim:
            del self._by_key[old]
        self._keys[victim] = None
        self._versions[victim] = 0
        return victim

    # -- loading -------------------------------------------------------------

    def load(self, adapter_id: str, factors: Dict[str, Tuple[Any, Any]],
             *, alpha: Optional[float] = None, version: int = 0
             ) -> Tuple[int, int]:
        """Directly install host factors (`{site: (A [in,rank],
        B [rank,out])}`) into a bank slot, bypassing the store (tests,
        in-process trainers). Returns (slot, version)."""
        self._check_factors(adapter_id, factors)
        slot = self._by_key.get(adapter_id)
        if slot is None:
            slot = self._alloc_slot(adapter_id)
        self._write_slot(slot, adapter_id, factors, alpha, int(version))
        return slot, int(version)

    def publish(self, adapter_id: str, factors: Dict[str, Tuple[Any, Any]],
                *, alpha: Optional[float] = None,
                meta: Optional[Dict[str, Any]] = None) -> int:
        """Commit a new adapter version through the WeightStore plane
        (sha256 manifests, monotone versions, writer markers). The bank
        does NOT swap eagerly — the next `pin()` picks the version up,
        so live requests are never touched."""
        self._check_factors(adapter_id, factors)
        store = self._store(adapter_id, create=True)
        flat = {}
        for site, (a, b) in factors.items():
            # the publish snapshot is the one sanctioned bulk d2h on
            # this plane (same doctrine as WeightStore.publish)
            flat[f'{site}::a'] = np.asarray(a)  # paddle-lint: disable=host-sync -- publish snapshot: factors must land on the host to be sha256-manifested
            flat[f'{site}::b'] = np.asarray(b)  # paddle-lint: disable=host-sync -- publish snapshot: factors must land on the host to be sha256-manifested
        m = dict(meta or {})
        m['adapter'] = adapter_id
        m['alpha'] = float(self.rank if alpha is None else alpha)
        version = store.publish(flat, meta=m)
        _obs.emit('adapter_publish', adapter=adapter_id, version=version)
        return version

    def _store(self, adapter_id: str,
               create: bool = False) -> Optional[WeightStore]:
        if self.store_dir is None:
            return None
        st = self._stores.get(adapter_id)
        if st is not None:
            return st
        if not _ADAPTER_ID_RE.match(adapter_id):
            raise ValueError(f'bad adapter id {adapter_id!r} (want '
                             f'[A-Za-z0-9._-]+; it names a directory)')
        d = os.path.join(self.store_dir, adapter_id)
        if not create and not os.path.isdir(d):
            return None
        st = WeightStore(d, keep_versions=self.keep_versions)
        self._stores[adapter_id] = st
        return st

    def _latest_good(self, store: WeightStore) -> Optional[int]:
        vs = [v for v in store.versions() if not store.is_quarantined(v)]
        return vs[-1] if vs else None

    def _load_version(self, adapter_id: str, store: WeightStore,
                      version: int) -> Optional[int]:
        """Try to load one store version into a slot. On a corrupt or
        shape-mismatched manifest: quarantine + `adapter_load_reject`
        event, return None — the bank keeps serving whatever it has."""
        try:
            flat = store.load(version)
            meta = store.meta(version)
            factors = self._unflatten(adapter_id, flat)
        except (WeightLoadError, ValueError, KeyError) as e:
            store.quarantine(version, f'adapter load failed: {e}')
            _obs.emit('adapter_load_reject', adapter=adapter_id,
                      version=version, reason=str(e)[:200])
            return None
        slot = self._alloc_slot(adapter_id)
        alpha = meta.get('alpha')
        self._write_slot(slot, adapter_id, factors,
                         None if alpha is None else float(alpha), version)
        return slot

    def _unflatten(self, adapter_id: str, flat: Dict[str, Any]
                   ) -> Dict[str, Tuple[Any, Any]]:
        factors = {}
        for site in self.sites:
            a, b = flat.get(f'{site}::a'), flat.get(f'{site}::b')
            if a is None or b is None:
                raise ValueError(f'manifest missing factors for target '
                                 f'site {site!r}')
            factors[site] = (a, b)
        self._check_factors(adapter_id, factors)
        return factors

    def _check_factors(self, adapter_id: str,
                       factors: Dict[str, Tuple[Any, Any]]):
        for site, (a, b) in factors.items():
            dims = self.sites.get(site)
            if dims is None:
                raise ValueError(f'{adapter_id}: unknown target site '
                                 f'{site!r} (bank targets '
                                 f'{tuple(self.sites)})')
            i, o = dims
            a, b = np.asarray(a), np.asarray(b)  # paddle-lint: disable=host-sync -- load/publish-time shape validation, not a decode-round path
            if a.shape != (i, self.rank) or b.shape != (self.rank, o):
                raise ValueError(
                    f'{adapter_id}: factor shapes for {site!r} are '
                    f'{a.shape}/{b.shape}, bank wants '
                    f'{(i, self.rank)}/{(self.rank, o)} (rank is a '
                    f'static — all adapters share rank={self.rank})')
        missing = set(self.sites) - set(factors)
        if missing:
            raise ValueError(f'{adapter_id}: factors missing for target '
                             f'sites {sorted(missing)}')

    def _write_slot(self, slot: int, adapter_id: str,
                    factors: Dict[str, Tuple[Any, Any]],
                    alpha: Optional[float], version: int):
        # functional .at[slot].set keeps shapes/dtypes — identical
        # avals, so resident programs replay without a retrace
        for site, (a, b) in factors.items():
            self._a[site] = self._a[site].at[slot].set(
                jnp.asarray(a, self.dtype))
            self._b[site] = self._b[site].at[slot].set(
                jnp.asarray(b, self.dtype))
        scaling = float(self.rank if alpha is None else alpha) / self.rank
        self._scale = self._scale.at[slot].set(scaling)
        self._keys[slot] = adapter_id
        self._versions[slot] = int(version)
        self._by_key[adapter_id] = slot
        self._tick += 1
        self._lru[slot] = self._tick
        if _obs.enabled():
            self._m_loads.inc()
        _obs.emit('adapter_load', adapter=adapter_id, slot=slot,
                  version=int(version))

    # -- introspection ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        resident = {self._keys[s]: {'slot': s,
                                    'version': self._versions[s],
                                    'refs': self._refs[s]}
                    for s in range(1, self.capacity + 1)
                    if self._keys[s] is not None}
        return {'capacity': self.capacity, 'rank': self.rank,
                'sites': len(self.sites), 'resident': resident,
                'pinned': self._pinned_count()}


def make_adapter_factors(bank: AdapterBank, seed: int, scale: float = 0.02
                         ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Deterministic random LoRA factors matching `bank`'s sites/rank —
    the test/bench/demo helper. Both factors are non-zero (real LoRA
    inits zero B; here the point is outputs that DIFFER per adapter)."""
    rng = np.random.RandomState(seed)
    out = {}
    for site, (i, o) in bank.sites.items():
        a = rng.standard_normal((i, bank.rank)).astype(np.float32) * scale
        b = rng.standard_normal((bank.rank, o)).astype(np.float32) * scale
        out[site] = (a, b)
    return out
