"""Online weight updates: trainer→serving hot-swap with versioned
rollouts (ISSUE 12).

Training (`resilience/elastic.py`) and serving (`router.py`) are both
production-hardened, but nothing connects them — continuous fine-tuning
and RLHF-style post-training need the weights a trainer just produced
to reach a live fleet WITHOUT a restart, a dropped request, or an XLA
recompile. This module is that link, three pieces composed from
machinery the repo already trusts:

- `WeightStore`: a versioned, sha256-manifested snapshot store reusing
  the PR-6 checkpoint integrity format (atomic-rename commit, per-file
  checksums in the `_COMMITTED` manifest, corrupt payloads rejected
  never restored). Versions are monotone; the last K are retained for
  rollback; a version that fails its health gate or its checksum is
  QUARANTINED (marker file + event) so no later poll re-offers it.
- `WeightPublisher`: the trainer side. Snapshots host-canonical params
  every N steps — from a bare `Layer`, an `ElasticTrainStep`'s
  topology-independent `capture_host_state`, or any callable — and
  publishes them under the next `weight_version`.
- `ReplicaUpdater`: the serving side. Rolls a new version across the
  Router's replicas ONE AT A TIME through the existing health/drain
  machinery: cordon (scoped `weight_swap` degraded state excludes the
  replica from placement while /healthz shows why) → drain (router
  steps keep serving; the victim's accepted requests finish — zero
  drops) → swap (`engine.swap_weights`: aval-checked, so the
  ProgramStore keys cannot move — zero recompiles, verified against
  the store's key set and the compile counters) → health gate (default:
  reject non-finite weights; `CanaryGate` optionally decodes a probe)
  → rejoin. A failed gate auto-reverts the replica to its previous
  weights (a pointer swap — the old device arrays were never dropped),
  quarantines the version, and ABORTS the rollout so no further
  replica ever sees it.

Every phase is a `hotswap.*` span classified as the first-class
`weight_swap` goodput category (decode rounds nested inside the drain
stay `serving_decode`: the fleet kept serving), and every transition
emits `weight_*` events + `paddle_swap_*` / `paddle_weight_*` metrics.
Responses carry the single `weight_version` they were decoded under
(stamped at admission; swaps only land on drained replicas).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as _obs
from .. import serialization
from ..utils.checkpoint import CheckpointManager


class WeightLoadError(RuntimeError):
    """A published version could not be loaded (missing, quarantined,
    or failed its sha256 manifest)."""


class SwapFailed(RuntimeError):
    """A rolling swap could not complete on a replica (drain timeout /
    unexpected engine failure). Gate failures do NOT raise — they roll
    back and quarantine."""

    def __init__(self, version: int, replica_id: int, msg: str):
        self.version = int(version)
        self.replica_id = int(replica_id)
        super().__init__(msg)


def _host_tree(state: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Materialize a {name: Tensor|array} state as host numpy arrays."""
    return {n: np.asarray(getattr(t, 'value', t))  # paddle-lint: disable=host-sync -- the publish/rollback snapshot IS the sanctioned bulk d2h: weights must reach host storage
            for n, t in dict(state).items()}


# ---------------------------------------------------------------------------
# the versioned store
# ---------------------------------------------------------------------------

class WeightStore:
    """Versioned weight snapshots with the PR-6 checkpoint integrity
    format: each version is a committed `step_<v>` directory (atomic
    rename, npz payload, per-file sha256 in `_COMMITTED`) managed by a
    `CheckpointManager`, plus quarantine semantics on top — a version
    that fails a health gate or a checksum gets a `_QUARANTINED`
    marker and stops being offered by `latest_version()`/`load()`,
    while version numbering stays monotone past it.

    Cross-process safety (the PR-12 stretch): `publish` claims a
    `_WRITER.json` marker (pid + start time, atomic rename) for the
    duration of the commit. A publisher KILLED mid-commit leaves the
    marker and possibly a half-written `step_*.tmp` dir behind; because
    commits are atomic-rename the torn version is never offered by
    `latest_version()`/`load()` — readers are safe unconditionally —
    and the NEXT publisher detects the stale marker (dead pid, or
    `stale_writer_s` elapsed for cross-host mounts), sweeps the marker
    plus orphan tmp dirs, emits `weight_writer_stale`, and proceeds. A
    marker whose pid is still alive is a concurrent publisher: a
    loud error, not a silent last-writer-wins.

    Args:
        directory: store root (shared between trainer and servers —
            a filesystem both can reach is the transport).
        keep_versions: retention depth; rollback needs >= 2.
        stale_writer_s: age past which a writer marker is presumed
            dead even when its pid cannot be probed (another host).
    """

    _MARKER = '_QUARANTINED'
    _WRITER = '_WRITER.json'

    def __init__(self, directory: str, keep_versions: int = 4,
                 retry_policy=None, stale_writer_s: float = 300.0):
        if keep_versions < 2:
            raise ValueError('keep_versions must be >= 2 (rollback '
                             'needs the previous version retained)')
        self.mgr = CheckpointManager(
            directory, backend='npz', max_to_keep=int(keep_versions),
            save_interval_steps=1, retry_policy=retry_policy)
        self.directory = self.mgr.directory
        self.stale_writer_s = float(stale_writer_s)
        reg = _obs.get_registry()
        self._m_published = reg.counter(
            'paddle_weight_publish_total', 'weight versions published')
        self._m_publish_bytes = reg.counter(
            'paddle_weight_publish_bytes_total',
            'host payload bytes published to the weight store')
        self._m_published_version = reg.gauge(
            'paddle_weight_published_version',
            'latest committed (non-quarantined) weight version')
        self._m_quarantined = reg.counter(
            'paddle_swap_quarantined_total',
            'weight versions quarantined (failed gate or load)')

    # -- bookkeeping --------------------------------------------------------
    def _dir(self, version: int) -> str:
        return self.mgr._step_dir(int(version))

    def all_versions(self) -> List[int]:
        """Every committed version, quarantined included (numbering)."""
        return self.mgr.all_steps()

    def versions(self) -> List[int]:
        """Committed, servable (non-quarantined) versions, ascending."""
        return [v for v in self.mgr.all_steps()
                if not self.is_quarantined(v)]

    def latest_version(self) -> Optional[int]:
        vs = self.versions()
        return vs[-1] if vs else None

    def next_version(self) -> int:
        vs = self.all_versions()
        return (vs[-1] + 1) if vs else 1

    def is_quarantined(self, version: int) -> bool:
        return os.path.exists(os.path.join(self._dir(version),
                                           self._MARKER))

    def quarantined(self) -> List[int]:
        return [v for v in self.mgr.all_steps() if self.is_quarantined(v)]

    # -- stale-writer detection ---------------------------------------------
    def _writer_path(self) -> str:
        return os.path.join(self.directory, self._WRITER)

    def writer_marker(self) -> Optional[Dict[str, Any]]:
        """The live writer marker, or None. Unreadable/garbage markers
        (a torn marker write) read as stale-shaped: {} with age 0 —
        the claim path sweeps them like any dead writer's."""
        path = self._writer_path()
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:  # paddle-lint: disable=swallowed-exception -- a torn/garbage marker reads as stale-shaped ({}); the claim path sweeps it like any dead writer's
            return {}

    @staticmethod
    def _pid_alive(pid) -> Optional[bool]:
        """True/False when the pid can be probed on THIS host, None when
        it cannot (another host shares the mount) — age decides then."""
        try:
            pid = int(pid)
        except (TypeError, ValueError):
            return False
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:
            return True      # exists, owned by someone else
        except OSError:
            return None

    def _sweep_stale_writer(self, marker: Dict[str, Any]):
        """Remove a dead publisher's droppings: the marker and any
        orphan step tmp dirs. Committed versions are untouched — the
        atomic-rename commit means a killed writer can only ever leave
        UNcommitted state behind."""
        import shutil
        swept = []
        for name in sorted(os.listdir(self.directory)):
            if name.startswith('step_') and name.endswith('.tmp'):
                try:
                    shutil.rmtree(os.path.join(self.directory, name))
                    swept.append(name)
                except OSError:
                    pass
        try:
            os.unlink(self._writer_path())
        except OSError:
            pass
        _obs.emit('weight_writer_stale', pid=marker.get('pid'),
                  started=marker.get('started'), swept_tmp=len(swept))
        if _obs.enabled():
            _obs.get_registry().counter(
                'paddle_weight_writer_stale_total',
                'dead mid-commit publishers detected and swept').inc()

    def _claim_writer(self, version: int):
        """Take the writer marker for this commit. A stale marker (dead
        pid, or older than stale_writer_s where the pid is unprobeable)
        is swept; a LIVE marker is a concurrent publisher and raises."""
        marker = self.writer_marker()
        if marker is not None:
            age = time.time() - float(marker.get('started', 0) or 0)
            alive = self._pid_alive(marker.get('pid'))
            same_host = marker.get('host', '') == os.uname().nodename
            if same_host and alive is not None:
                # pid probe is authoritative on this host
                stale = not alive
            else:
                # another host (pid numbers don't travel) or an
                # unprobeable pid: age decides
                stale = age > self.stale_writer_s
            if not stale:
                raise RuntimeError(
                    f'weight store writer marker {self._writer_path()} '
                    f'belongs to a live publisher (pid '
                    f'{marker.get("pid")}, host '
                    f'{marker.get("host", "?")}, age {age:.0f}s); two '
                    f'live publishers on one store is a deployment bug '
                    f'— or raise stale_writer_s if this is a wedged '
                    f'remote writer')
            self._sweep_stale_writer(marker)
        tmp = f'{self._writer_path()}.{os.getpid()}.tmp'
        with open(tmp, 'w') as f:
            json.dump({'pid': os.getpid(), 'started': time.time(),
                       'host': os.uname().nodename,
                       'version': int(version)}, f)
        os.replace(tmp, self._writer_path())

    def _release_writer(self):
        try:
            os.unlink(self._writer_path())
        except OSError:
            pass

    # -- publish / load -----------------------------------------------------
    def publish(self, state: Dict[str, Any], version: Optional[int] = None,
                meta: Optional[Dict[str, Any]] = None) -> int:
        """Commit `state` ({name: array} model weights) as a new
        version. Versions are strictly monotone: an explicit `version`
        at or below the max ever seen is a caller bug. The commit runs
        under the `_WRITER` marker (see the class docstring): a
        publisher killed anywhere inside leaves only a stale marker and
        an uncommitted tmp dir — never a half-offered version."""
        host = _host_tree(state)
        if version is None:
            version = self.next_version()
        else:
            version = int(version)
            vs = self.all_versions()
            if vs and version <= vs[-1]:
                raise ValueError(
                    f'weight versions are monotone: {version} <= '
                    f'latest committed {vs[-1]}')
        nbytes = sum(int(a.nbytes) for a in host.values()
                     if hasattr(a, 'nbytes'))
        self._claim_writer(version)
        try:
            self.mgr.save(version,
                          {'model': host, 'weight_version': version,
                           'meta': dict(meta or {})}, force=True)
        finally:
            self._release_writer()
        _obs.emit('weight_publish', version=version, bytes=nbytes,
                  **{k: v for k, v in (meta or {}).items()
                     if isinstance(v, (int, float, str))})
        if _obs.enabled():
            self._m_published.inc()
            self._m_publish_bytes.inc(nbytes)
            self._m_published_version.set(version)
        return version

    def load(self, version: int) -> Dict[str, np.ndarray]:
        """Strict read of one exact version's weights: committed, not
        quarantined, and every payload file matching its sha256
        manifest — otherwise `WeightLoadError` (the updater quarantines
        on it). Deliberately NOT `CheckpointManager.restore`: no
        fall-back-to-previous (a swap must never silently apply a
        different version than it announced) and no
        `checkpoint_restore` span (swap time books as `weight_swap`,
        under the caller's `hotswap.load` span)."""
        version = int(version)
        d = self._dir(version)
        if version not in self.mgr.all_steps():
            raise WeightLoadError(f'weight version {version} is not '
                                  f'committed under {self.directory}')
        if self.is_quarantined(version):
            raise WeightLoadError(f'weight version {version} is '
                                  f'quarantined')
        if not self.mgr.verify(version):
            raise WeightLoadError(
                f'weight version {version} failed its sha256 manifest '
                f'(torn write or bit rot)')
        tree = serialization.load(os.path.join(d, 'tree.npz'),
                                  return_numpy=True)
        return dict(tree['model'])

    def meta(self, version: int) -> Dict[str, Any]:
        tree = serialization.load(os.path.join(self._dir(int(version)),
                                               'tree.npz'),
                                  return_numpy=True)
        return dict(tree.get('meta', {}))

    # -- quarantine ---------------------------------------------------------
    def quarantine(self, version: int, reason: str = ''):
        """Mark `version` unservable (failed health gate / bad payload):
        `latest_version()`/`load()` stop offering it, retention still
        ages it out. Idempotent."""
        version = int(version)
        d = self._dir(version)
        already = self.is_quarantined(version)
        if os.path.isdir(d) and not already:
            with open(os.path.join(d, self._MARKER), 'w') as f:
                json.dump({'version': version, 'reason': str(reason),
                           'at': time.time()}, f)
        if not already:
            _obs.emit('weight_version_quarantined', version=version,
                      reason=str(reason))
            if _obs.enabled():
                self._m_quarantined.inc()
                latest = self.latest_version()
                if latest is not None:
                    self._m_published_version.set(latest)

    def stats(self) -> Dict[str, Any]:
        return {
            'directory': self.directory,
            'versions': self.versions(),
            'latest': self.latest_version(),
            'quarantined': self.quarantined(),
            'writer': self.writer_marker(),
        }


# ---------------------------------------------------------------------------
# trainer side
# ---------------------------------------------------------------------------

class WeightPublisher:
    """Streams a live training run's weights into a `WeightStore`
    every `interval_steps` optimizer steps.

    `source` is what to snapshot:
    - a `Layer` (its `state_dict()`, host-materialized),
    - anything with `capture_host_state()` (an `ElasticTrainStep`: the
      topology-independent snapshot — its 'model' tree — so an elastic
      run publishes through a re-mesh unchanged),
    - a zero-arg callable returning `{name: array}`.
    """

    def __init__(self, source, store: WeightStore,
                 interval_steps: int = 1,
                 meta_fn: Optional[Callable[[int], Dict[str, Any]]] = None):
        if interval_steps < 1:
            raise ValueError('interval_steps must be >= 1')
        self.source = source
        self.store = store
        self.interval_steps = int(interval_steps)
        self.meta_fn = meta_fn
        self.last_published_version: Optional[int] = None
        self.last_published_step: Optional[int] = None

    def capture(self) -> Dict[str, np.ndarray]:
        """One host-canonical snapshot of the source's weights. The
        per-leaf `np.asarray` is the publisher's one device→host
        moment; it rides the trainer's cadence, never the decode path."""
        src = self.source
        if callable(src) and not hasattr(src, 'state_dict') \
                and not hasattr(src, 'capture_host_state'):
            return _host_tree(src())
        if hasattr(src, 'capture_host_state'):
            return dict(src.capture_host_state()['model'])
        return _host_tree(src.state_dict())

    def publish(self, step: Optional[int] = None) -> int:
        """Snapshot + commit now; returns the new weight version."""
        meta: Dict[str, Any] = {'step': int(step)} if step is not None \
            else {}
        if self.meta_fn is not None:
            meta.update(self.meta_fn(step))
        version = self.store.publish(self.capture(), meta=meta)
        self.last_published_version = version
        self.last_published_step = step
        return version

    def maybe_publish(self, step: int) -> Optional[int]:
        """Publish when `step` lands on the interval (each step at most
        once); returns the version or None."""
        step = int(step)
        if step % self.interval_steps != 0:
            return None
        if self.last_published_step == step:
            return None
        return self.publish(step)


# ---------------------------------------------------------------------------
# health gates
# ---------------------------------------------------------------------------

def finite_weights_gate(engine, version: int,
                        tree: Dict[str, np.ndarray]) -> Tuple[bool, str]:
    """Default gate: every floating leaf of the published tree is
    finite. Pure host-side numpy on the already-loaded snapshot —
    catches the classic bad checkpoint (NaN/Inf from a diverged or torn
    step) without touching the device, so the swap's zero-compile
    accounting stays exact."""
    for name, leaf in tree.items():
        a = np.asarray(leaf)  # paddle-lint: disable=host-sync -- the gate reads the ALREADY-host npz tree (no device copy); staying on host is what keeps the swap's zero-compile accounting exact
        if np.issubdtype(a.dtype, np.floating) \
                and not bool(np.isfinite(a).all()):
            return False, f'non-finite values in {name!r}'
    return True, ''


class CanaryGate:
    """Opt-in post-swap probe: decode `max_new_tokens` greedily from
    `prompt` ON the freshly swapped (cordoned, drained) engine and
    require it to finish — optionally bit-matching `expect`. The canary
    uses the engine's own compiled programs, so its first run may
    compile a prefill bucket the live traffic never used; pair it with
    traffic-shaped prompts when the zero-compile guarantee matters."""

    def __init__(self, prompt: Sequence[int], max_new_tokens: int = 4,
                 expect: Optional[Sequence[int]] = None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.expect = None if expect is None else [int(t) for t in expect]

    def __call__(self, engine, version: int, tree) -> Tuple[bool, str]:
        from .api import SamplingParams
        h = engine.submit(self.prompt, SamplingParams(
            max_new_tokens=self.max_new_tokens, eos_token_id=-1))
        toks = h.result()
        if self.expect is not None and list(toks) != self.expect:
            return False, (f'canary mismatch: got {list(toks)}, '
                           f'expected {self.expect}')
        if not toks:
            return False, 'canary produced no tokens'
        return True, ''


# ---------------------------------------------------------------------------
# serving side
# ---------------------------------------------------------------------------

class ReplicaUpdater:
    """Rolls published weight versions across a `Router`'s replicas,
    one at a time, with zero dropped requests and zero recompiles.

    Args:
        router: the live `Router` (its replicas are the swap targets;
            its `step()` keeps the WHOLE fleet serving while one
            replica drains).
        store: the `WeightStore` the trainer publishes into.
        gates: health-gate callables `(engine, version, tree) ->
            (ok, detail)` run after the swap, before rejoin; the first
            failure reverts the replica and quarantines the version.
            Default: `[finite_weights_gate]`.
        max_drain_rounds: router rounds to wait for a replica to go
            idle before declaring the swap stuck (`SwapFailed`).
        traffic_pump: optional zero-arg callable invoked once per drain
            round — the hook tests (and request-generating callers) use
            to keep submitting traffic WHILE a swap is in flight.
    """

    def __init__(self, router, store: WeightStore, *,
                 gates: Optional[Sequence[Callable]] = None,
                 max_drain_rounds: int = 100000,
                 traffic_pump: Optional[Callable[[], None]] = None):
        self.router = router
        self.store = store
        self.gates = list(gates) if gates is not None \
            else [finite_weights_gate]
        self.max_drain_rounds = int(max_drain_rounds)
        self.traffic_pump = traffic_pump
        reg = _obs.get_registry()
        self._m_swaps = reg.counter(
            'paddle_swap_total', 'per-replica weight swaps by outcome',
            ('outcome',))
        self._m_rollbacks = reg.counter(
            'paddle_swap_rollbacks_total',
            'replicas reverted to their previous weights after a '
            'failed health gate')
        self._m_seconds = reg.histogram(
            'paddle_swap_seconds',
            'per-replica drain+swap+verify+rejoin wall time')

    # -- introspection ------------------------------------------------------
    def current_versions(self) -> Dict[int, int]:
        return {r.id: r.engine.weight_version
                for r in self.router.replicas}

    @property
    def fleet_version(self) -> Optional[int]:
        """The single version every replica serves, or None while the
        fleet is mixed (mid-rollout)."""
        vs = set(self.current_versions().values())
        return vs.pop() if len(vs) == 1 else None

    # -- the rolling swap ---------------------------------------------------
    def poll(self) -> Optional[Dict[str, Any]]:
        """Swap to the store's latest servable version when any replica
        is behind it; returns the `update_to` result or None."""
        latest = self.store.latest_version()
        if latest is None:
            return None
        if all(r.engine.weight_version >= latest
               for r in self.router.replicas):
            return None
        return self.update_to(latest)

    def update_to(self, version: int) -> Dict[str, Any]:
        """Roll `version` across the fleet. One replica at a time; a
        gate failure quarantines the version and ABORTS the rollout —
        replicas not yet swapped never see a version another replica
        just rejected."""
        version = int(version)
        result: Dict[str, Any] = {'version': version,
                                  'outcome': 'completed', 'replicas': []}
        with _obs.span('hotswap.swap', version=version):
            with _obs.span('hotswap.load', version=version):
                try:
                    tree = self.store.load(version)
                except Exception as exc:
                    # a version that cannot even load is quarantined the
                    # same as one that fails its gate — no replica was
                    # touched, nothing to roll back
                    self.store.quarantine(version,
                                          f'load failed: {exc}')
                    if _obs.enabled():
                        self._m_swaps.labels(outcome='load_failed').inc()
                    result['outcome'] = 'load_failed'
                    result['error'] = f'{type(exc).__name__}: {exc}'
                    return result
            for replica in list(self.router.replicas):
                r = self._swap_replica(replica, version, tree)
                result['replicas'].append(r)
                if r['outcome'] == 'rolled_back':
                    result['outcome'] = 'aborted'
                    break
        return result

    def _drive_drain(self, engine) -> int:
        rounds = 0
        while engine.has_work:
            if self.traffic_pump is not None:
                self.traffic_pump()
            self.router.step()
            rounds += 1
            if rounds > self.max_drain_rounds:
                raise SwapFailed(
                    -1, -1, f'replica did not drain within '
                            f'{self.max_drain_rounds} router rounds')
        return rounds

    def _swap_replica(self, replica, version: int,
                      tree: Dict[str, np.ndarray]) -> Dict[str, Any]:
        from .. import programs as _programs
        eng = replica.engine
        from_version = eng.weight_version
        res: Dict[str, Any] = {
            'replica': replica.id, 'from_version': from_version,
            'to_version': version, 'outcome': 'completed',
            'drain_rounds': 0, 'new_program_keys': 0, 'real_compiles': 0,
        }
        if from_version == version:
            res['outcome'] = 'already_current'
            return res
        _obs.emit('weight_swap_begin', replica=replica.id,
                  from_version=from_version, to_version=version)
        # cordon: the scoped degraded state takes this replica out of
        # placement through the SAME machinery /healthz and the router
        # already share — in-flight and queued work keeps decoding
        _obs.note_degraded('weight_swap',
                           {'from_version': from_version,
                            'to_version': version}, scope=replica.scope)
        t0 = time.perf_counter()
        cleared = False
        try:
            with _obs.span('hotswap.drain', replica=replica.id,
                           version=version):
                try:
                    res['drain_rounds'] = self._drive_drain(eng)
                except SwapFailed as exc:
                    raise SwapFailed(version, replica.id,
                                     str(exc)) from None
            store = _programs.get_store()
            reg = _obs.get_registry()
            keys0 = {e['key'] for e in store.entries()}
            compiles0 = reg.value('paddle_jit_compiles_total')
            hits0 = reg.value('paddle_jit_cache_hits_total')
            with _obs.span('hotswap.load', replica=replica.id,
                           version=version):
                prev = eng.swap_weights(tree, version=version)
            ok, detail = True, ''
            with _obs.span('hotswap.verify', replica=replica.id,
                           version=version):
                for gate in self.gates:
                    try:
                        verdict = gate(eng, version, tree)
                        ok, detail = (verdict if isinstance(verdict,
                                                            tuple)
                                      else (bool(verdict), ''))
                    except Exception as exc:
                        ok = False
                        detail = f'{type(exc).__name__}: {exc}'
                    if not ok:
                        break
                # ProgramStore-verified zero recompiles: same avals and
                # shardings ⇒ same program keys, so the swap (gates
                # included) must not mint keys or real compiles
                new_keys = ({e['key'] for e in store.entries()}
                            - keys0)
                real = ((reg.value('paddle_jit_compiles_total')
                         - compiles0)
                        - (reg.value('paddle_jit_cache_hits_total')
                           - hits0))
                res['new_program_keys'] = len(new_keys)
                res['real_compiles'] = int(real)
            if ok:
                with _obs.span('hotswap.rejoin', replica=replica.id,
                               version=version):
                    _obs.clear_degraded('weight_swap',
                                        scope=replica.scope)
                    cleared = True
                dt = time.perf_counter() - t0
                _obs.emit('weight_swap_complete', replica=replica.id,
                          from_version=from_version, to_version=version,
                          drain_rounds=res['drain_rounds'],
                          seconds=round(dt, 4))
                if _obs.enabled():
                    self._m_swaps.labels(outcome='completed').inc()
                    self._m_seconds.observe(dt)
            else:
                with _obs.span('hotswap.rollback', replica=replica.id,
                               version=version):
                    eng.restore_weights(prev)
                self.store.quarantine(version, detail)
                _obs.emit('weight_swap_failed', replica=replica.id,
                          version=version, reason=detail)
                _obs.emit('weight_rollback', replica=replica.id,
                          to_version=from_version)
                if _obs.enabled():
                    self._m_swaps.labels(outcome='rolled_back').inc()
                    self._m_rollbacks.inc()
                    self._m_seconds.observe(time.perf_counter() - t0)
                res['outcome'] = 'rolled_back'
                res['reason'] = detail
        finally:
            if not cleared:
                _obs.clear_degraded('weight_swap', scope=replica.scope)
        return res
