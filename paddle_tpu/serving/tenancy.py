"""Per-tenant QoS: priorities, token-bucket rate limits, concurrency caps.

A production serving fleet never runs one anonymous traffic stream —
it runs tenants (users, API keys, internal jobs) with different
entitlements, and overload policy is defined in tenant terms: paid
traffic is protected, best-effort traffic is shed FAST (a rejected
request that cost nothing is infinitely better than an accepted one
that times out — the classic load-shedding doctrine). This module is
the data model the router enforces:

- `Tenant` — a name plus its QoS envelope: priority class
  (api.PRIORITY_HIGH/NORMAL/LOW → the scheduler's admission key),
  a request-rate `TokenBucket` (rate/burst; None = unlimited), a
  `max_concurrency` cap on in-flight requests (None = unlimited), and
  an optional default `adapter` — the LoRA adapter id the tenant's
  requests decode under (serving.adapters.AdapterBank; per-request
  adapter_id overrides it). Concurrency caps double as capacity
  reservations: capping best-effort tenants below the slot count keeps
  slots free for latency-sensitive ones, which is what makes
  "high-priority TTFT unaffected by overload" a structural guarantee
  rather than a hope.
- `TenantRegistry` — name -> Tenant with a default template for unknown
  tenants (each still gets its OWN bucket/accounting).
- `AdmissionRejected` — the typed fast-fail: tenant, reason
  ('rate_limited' | 'concurrency' | 'shed' | 'no_healthy_replica' |
  'adapter_unavailable') and a `retry_after_s` hint, raised by the
  router BEFORE any prefill work happens.
- `parse_tenant_spec` — the CLI/env format used by
  `examples/serve_gpt.py --tenants`:
      "paid:priority=high,rate=50,burst=100,adapter=paid-v2;free:priority=low,rate=2,concurrency=2"
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from .api import PRIORITY_NAMES, PRIORITY_NORMAL


# the CLOSED vocabulary of typed rejection reasons. Kept in lockstep
# with the request-ledger's BLOCKED_REASONS (a rejected request never
# gets a ledger record — it cost nothing, which is the point — but
# dashboards join the two vocabularies when explaining tail behavior).
REJECT_REASONS = ('rate_limited', 'concurrency', 'shed',
                  'no_healthy_replica', 'adapter_unavailable')


class AdmissionRejected(RuntimeError):
    """Typed admission rejection (rate limit, concurrency cap, load
    shed, or no healthy replica). Always raised synchronously from
    `Router.submit` — the request never consumed a prefill or a slot.
    `retry_after_s` is the router's hint for client backoff."""

    def __init__(self, tenant: str, reason: str,
                 retry_after_s: Optional[float] = None, detail: str = ''):
        if reason not in REJECT_REASONS:
            raise ValueError(
                f'unknown rejection reason {reason!r}; the vocabulary '
                f'is closed: {REJECT_REASONS}')
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = retry_after_s
        msg = f'tenant {tenant!r} rejected ({reason})'
        if detail:
            msg += f': {detail}'
        if retry_after_s is not None:
            msg += f' [retry after {retry_after_s:.3f}s]'
        super().__init__(msg)


class TokenBucket:
    """Classic token bucket: `rate` tokens/sec refill up to `burst`
    capacity; each admission takes one token. `clock` is injectable so
    tests drive time explicitly."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0:
            raise ValueError('rate must be > 0 tokens/sec')
        self.rate = float(rate)
        self.capacity = float(burst if burst is not None
                              else max(rate, 1.0))
        if self.capacity < 1.0:
            raise ValueError('burst must allow at least one request')
        self._clock = clock
        self._tokens = self.capacity
        self._t = clock()

    def _refill(self):
        now = self._clock()
        self._tokens = min(self.capacity,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def try_acquire(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 if they are)."""
        self._refill()
        if self._tokens >= n:
            return 0.0
        return (n - self._tokens) / self.rate

    @property
    def tokens(self) -> float:
        self._refill()
        return self._tokens


class Tenant:
    """One tenant's QoS envelope + live accounting (in-flight count)."""

    def __init__(self, name: str, priority: int = PRIORITY_NORMAL,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_concurrency: Optional[int] = None,
                 adapter: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        if isinstance(priority, str):
            try:
                priority = PRIORITY_NAMES[priority.lower()]
            except KeyError:
                raise ValueError(
                    f'unknown priority {priority!r}; expected one of '
                    f'{sorted(PRIORITY_NAMES)} or an int class')
        self.priority = int(priority)
        self.bucket = (TokenBucket(rate, burst, clock=clock)
                       if rate is not None else None)
        self.max_concurrency = (int(max_concurrency)
                                if max_concurrency is not None else None)
        # the tenant's default LoRA adapter (None = base model); the
        # router stamps it onto submissions that don't name their own
        self.adapter = adapter
        self.in_flight = 0

    def spec(self) -> dict:
        return {'priority': self.priority,
                'rate': self.bucket.rate if self.bucket else None,
                'burst': self.bucket.capacity if self.bucket else None,
                'max_concurrency': self.max_concurrency,
                'adapter': self.adapter}

    def __repr__(self):
        return f'Tenant({self.name!r}, {self.spec()})'


DEFAULT_TENANT = 'default'


class TenantRegistry:
    """name -> Tenant. Unknown tenants get their own Tenant cloned from
    the default template (separate bucket + in-flight accounting), so a
    brand-new API key is rate-limited like any other default-tier
    tenant instead of sharing one global bucket."""

    def __init__(self, tenants: Optional[Dict[str, dict]] = None,
                 default: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._default_spec = dict(default or {})
        self._tenants: Dict[str, Tenant] = {}
        for name, spec in (tenants or {}).items():
            self.add(name, **spec)

    def add(self, name: str, **spec) -> Tenant:
        t = Tenant(name, clock=self._clock, **spec)
        self._tenants[name] = t
        return t

    def get(self, name: Optional[str]) -> Tenant:
        name = name or DEFAULT_TENANT
        t = self._tenants.get(name)
        if t is None:
            t = Tenant(name, clock=self._clock, **self._default_spec)
            self._tenants[name] = t
        return t

    def tenants(self) -> Dict[str, Tenant]:
        return dict(self._tenants)

    def __contains__(self, name: str) -> bool:
        return name in self._tenants


def prefill_rounds(prompt_len: int, chunk_tokens: Optional[int]) -> int:
    """Scheduler iterations a queued prompt occupies before its slot can
    decode: ONE whole-prompt prefill without chunking, else
    ceil(prompt/chunk) bounded chunk rounds. The shed estimator's unit
    of head-of-line delay."""
    if not chunk_tokens or chunk_tokens <= 0:
        return 1
    return max(1, -(-int(prompt_len) // int(chunk_tokens)))


def estimate_queue_rounds(queued_prompt_lens,
                          chunk_tokens: Optional[int] = None) -> float:
    """Rounds of prefill work ahead of a NEW request: queue depth x
    per-prompt chunk rounds — NOT x whole-prompt prefills. With chunked
    prefill enabled, each round is bounded by the chunk bucket, so the
    observed round time stays small and a queued long prompt is many
    CHEAP rounds instead of one expensive one; an estimator that still
    charged a full-prompt prefill per queued request would over-fire the
    shed budget the moment chunking lands (the old behavior)."""
    return float(sum(prefill_rounds(s, chunk_tokens)
                     for s in queued_prompt_lens))


_SPEC_KEYS = {'priority': str, 'rate': float, 'burst': float,
              'concurrency': int, 'max_concurrency': int,
              'adapter': str}


def parse_tenant_spec(spec: str,
                      clock: Callable[[], float] = time.monotonic
                      ) -> TenantRegistry:
    """Parse the CLI tenant-spec format into a TenantRegistry.

    Format: `name:key=value,key=value;name2:...`, keys from
    priority (high|normal|low or int) / rate (req/s) / burst /
    concurrency / adapter (default LoRA adapter id). A bare `name`
    (no colon) gets all defaults.

        parse_tenant_spec('paid:priority=high,rate=50,adapter=paid-ft;'
                          'free:priority=low,rate=2,concurrency=2')
    """
    reg = TenantRegistry(clock=clock)
    for chunk in (spec or '').split(';'):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, _, body = chunk.partition(':')
        name = name.strip()
        if not name:
            raise ValueError(f'tenant spec chunk {chunk!r} has no name')
        kw: dict = {}
        for item in body.split(','):
            item = item.strip()
            if not item:
                continue
            key, eq, value = item.partition('=')
            key = key.strip()
            if not eq or key not in _SPEC_KEYS:
                raise ValueError(
                    f'bad tenant spec item {item!r} for {name!r}; '
                    f'expected key=value with key in '
                    f'{sorted(_SPEC_KEYS)}')
            cast = _SPEC_KEYS[key]
            if key in ('concurrency', 'max_concurrency'):
                kw['max_concurrency'] = int(value)
            elif key == 'priority':
                v = value.strip()
                kw['priority'] = int(v) if v.lstrip('-').isdigit() else v
            elif key == 'adapter':
                kw['adapter'] = value.strip()
            else:
                kw[key] = cast(value)
        reg.add(name, **kw)
    return reg
