"""Replica process entrypoint: `python -m paddle_tpu.serving.replica_main`.

One invocation = one `InferenceEngine` in its own OS process, serving
the framed RPC protocol from `serving.remote` on an AF_UNIX socket.
The supervisor spawns this module; the parent talks to it through a
`RemoteReplica`. Startup contract (the warm-start guarantee the
fleet_proc tier-1 guard measures):

1. `programs.configure(<store dir>)` BEFORE the engine is built, so
   every serving program loads from the ProgramStore persistent tier
   (StableHLO + the XLA persistent cache) — a new process LOADS, it
   never compiles. Ready-marks of `paddle_jit_compiles_total` /
   `paddle_jit_cache_hits_total` are snapshotted once startup settles
   and shipped in `stats`, so the parent can assert the serving
   window's compile delta equals its cache-hit delta.
2. Weights come from the stale-writer-safe `WeightStore` (sha256
   verified at read) — the factory builds the ARCHITECTURE, the store
   provides the numbers, `swap_weights` stamps the version. No weight
   bytes ever cross the RPC socket.
3. The PR-17 `Shipper` starts last: metrics/events/spans spool to disk
   and the parent's Aggregator stitches them into the fleet view.

SIGTERM honors the existing graceful-drain path (PreemptionHandler →
engine.drain under the deadline → exit 0); the supervisor classifies
exit codes: 0 clean, 2 usage, 3 load failure, anything else a crash.

The model factory is addressed as `module:callable` or
`/path/to/file.py:callable` (tests and bench point at their own tiny
factories without packaging them); it must return a constructed Layer
(eval mode is applied here).
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time
import traceback
from typing import Any, Dict, Optional

EXIT_CLEAN = 0
EXIT_CRASH = 1
EXIT_USAGE = 2
EXIT_LOAD = 3


def _resolve_factory(spec: str):
    """`pkg.mod:fn` or `/path/file.py:fn` -> the callable."""
    target, sep, fn_name = spec.rpartition(':')
    if not sep or not target or not fn_name:
        raise ValueError(
            f'model spec must be "module:callable" or "file.py:callable", '
            f'got {spec!r}')
    if target.endswith('.py') or os.sep in target:
        import importlib.util
        mod_spec = importlib.util.spec_from_file_location(
            'paddle_tpu_replica_factory', target)
        if mod_spec is None or mod_spec.loader is None:
            raise ImportError(f'cannot load factory file {target!r}')
        mod = importlib.util.module_from_spec(mod_spec)
        mod_spec.loader.exec_module(mod)
    else:
        import importlib
        mod = importlib.import_module(target)
    fn = getattr(mod, fn_name, None)
    if fn is None:
        raise ImportError(f'{target!r} has no attribute {fn_name!r}')
    return fn


class _ReplicaServer:
    """Accept loop + per-connection dispatch threads over one engine.

    Engine-touching methods serialize on `_elock`; `healthz` answers
    WITHOUT it, by design — that is what lets the supervisor's
    heartbeat distinguish "busy decoding" (healthz answers) from
    "SIGSTOPped / wedged" (socket times out)."""

    def __init__(self, engine, listener: socket.socket, *,
                 weight_store=None, preempt=None,
                 drain_deadline_s: float = 30.0, uid: str = ''):
        from .. import observability as _obs
        from ..analysis.runtime import concurrency as _concurrency
        self._obs = _obs
        self.engine = engine
        self.listener = listener
        self.weight_store = weight_store
        self.preempt = preempt
        self.drain_deadline_s = drain_deadline_s
        self.uid = uid
        self._elock = _concurrency.RLock('_ReplicaServer._elock')
        self._requests: Dict[int, Any] = {}   # rid -> engine handle
        self._final_sent: set = set()
        self._stop = threading.Event()
        self._drained = False
        # ready-marks: compile counters once startup settled — the
        # warm-start guard's zero point
        reg = _obs.get_registry()
        self.marks = {
            'jit_compiles_at_ready': reg.value('paddle_jit_compiles_total'),
            'jit_cache_hits_at_ready':
                reg.value('paddle_jit_cache_hits_total'),
        }

    # -- request mirror bookkeeping ---------------------------------------
    def _updates(self) -> Dict[str, Any]:
        """Status/token deltas for every tracked request; a request's
        terminal status ships until the frame carrying it is SENT (the
        caller prunes after a successful send, so a torn response frame
        re-ships the final state on the next step)."""
        out = {}
        for rid, h in self._requests.items():
            upd: Dict[str, Any] = {'status': h.status,
                                   'tokens': list(h.tokens)}
            if h.weight_version is not None:
                upd['weight_version'] = h.weight_version
            if getattr(h, 'adapter_version', None) is not None:
                upd['adapter_version'] = h.adapter_version
            if h.error is not None:
                from ..resilience.retry import is_transient
                upd['error'] = {
                    'type': type(h.error).__name__,
                    'message': str(h.error),
                    'transient': is_transient(h.error),
                }
            out[str(rid)] = upd
        return out

    def _prune_done(self):
        for rid in [r for r, h in self._requests.items() if h.done]:
            if rid in self._final_sent:
                del self._requests[rid]
                self._final_sent.discard(rid)
            else:
                self._final_sent.add(rid)

    # -- RPC methods -------------------------------------------------------
    def rpc_hello(self, **_):
        eng = self.engine
        return {
            'pid': os.getpid(), 'uid': self.uid,
            'weight_version': eng.weight_version,
            'prefill_chunk_tokens': eng.prefill_chunk_tokens,
            'num_slots': eng.pool.num_slots,
            'max_length': eng.pool.max_length,
        }

    def rpc_submit(self, prompt_tokens=None, params=None, priority=None,
                   adapter_id=None, **_):
        from .remote import params_from_wire
        with self._elock:
            h = self.engine.submit(prompt_tokens,
                                   params=params_from_wire(params or {}),
                                   priority=priority,
                                   adapter_id=adapter_id)
            self._requests[h.request_id] = h
            return {'rid': h.request_id, 'status': h.status}

    def rpc_step(self, **_):
        with self._elock:
            t0 = time.perf_counter()
            progressed = self.engine.step() if self.engine.has_work else 0
            # reported so the parent's mirror ledger can split this
            # round into decode (child wall) vs rpc_transport (framing
            # + socket surplus measured around the call)
            step_wall = time.perf_counter() - t0
            out = {'progressed': progressed, 'updates': self._updates(),
                   'step_wall_s': step_wall}
            self._prune_done()
            return out

    def rpc_evict_all(self, **_):
        with self._elock:
            orphans = self.engine.evict_all()
            rids = [h.request_id for h in orphans]
            for rid in rids:
                self._requests.pop(rid, None)
                self._final_sent.discard(rid)
            return {'rids': rids}

    def rpc_begin_drain(self, **_):
        with self._elock:
            self.engine.begin_drain()
            return {'draining': True}

    def rpc_drain(self, deadline_s=None, **_):
        with self._elock:
            ok = self.engine.drain(deadline_s=deadline_s)
            out = {'ok': ok, 'updates': self._updates()}
            self._prune_done()
            return out

    def rpc_swap_weights(self, version=None, strict=True, **_):
        if self.weight_store is None:
            raise RuntimeError('replica process has no --weight-store; '
                               'cannot swap by version')
        with self._elock:
            prev_version = self.engine.weight_version
            state = self.weight_store.load(int(version))
            self.engine.swap_weights(state, version=int(version),
                                     strict=bool(strict))
            return {'weight_version': self.engine.weight_version,
                    'prev_version': prev_version}

    def rpc_healthz(self, **_):
        # NO engine lock: must answer while a decode block runs
        return {'ok': True, 'pid': os.getpid(), 'uid': self.uid,
                'draining': self.engine.draining,
                'weight_version': self.engine.weight_version,
                'states': sorted(self._obs.degraded_states().keys())}

    def rpc_stats(self, **_):
        reg = self._obs.get_registry()
        with self._elock:
            out = self.engine.stats()
        out['jit_compiles_total'] = reg.value('paddle_jit_compiles_total')
        out['jit_cache_hits_total'] = reg.value(
            'paddle_jit_cache_hits_total')
        out.update(self.marks)
        out['pid'] = os.getpid()
        out['uid'] = self.uid
        return out

    def rpc_set_obs_scope(self, scope=None, **_):
        self.engine.obs_scope = scope
        return {'scope': scope}

    def rpc_shutdown(self, **_):
        self._stop.set()
        return {'stopping': True}

    # -- serve loop --------------------------------------------------------
    def _dispatch(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        from ..resilience.retry import is_transient
        method = msg.get('method', '')
        fn = getattr(self, f'rpc_{method}', None)
        if fn is None:
            return {'error': {'type': 'KeyError',
                              'message': f'unknown RPC method {method!r}',
                              'transient': False}}
        try:
            return {'result': fn(**(msg.get('args') or {}))}
        except BaseException as exc:   # ships to the caller, typed
            return {'error': {'type': type(exc).__name__,
                              'message': str(exc),
                              'transient': is_transient(exc)}}

    def _serve_conn(self, conn: socket.socket):
        from .remote import recv_msg, send_msg
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn)
                except (ConnectionError, OSError, TimeoutError):
                    return   # peer gone; its mirrors survive parent-side
                send_msg(conn, self._dispatch(msg))
        finally:
            try:
                conn.close()
            except OSError:
                self._obs.count_suppressed('replica_conn_close')

    def serve_forever(self):
        """Accept until shutdown RPC or SIGTERM; then drain and return.
        Returns True when the drain (if any) beat its deadline."""
        self.listener.settimeout(0.2)
        threads = []
        while not self._stop.is_set():
            if self.preempt is not None and self.preempt.requested:
                break
            try:
                conn, _ = self.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True,
                                 name='replica-rpc-conn')
            t.start()
            threads.append(t)
        # graceful exit: finish every accepted request under the deadline
        with self._elock:
            ok = True
            if self.engine.has_work or not self.engine.draining:
                ok = self.engine.drain(deadline_s=self.drain_deadline_s)
            self._drained = True
        self._stop.set()
        return ok


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog='python -m paddle_tpu.serving.replica_main',
        description='one supervised InferenceEngine replica process')
    p.add_argument('--socket', required=True,
                   help='AF_UNIX socket path to serve the replica RPC on')
    p.add_argument('--model-spec', required=True,
                   help='model factory: "module:callable" or '
                        '"/path/file.py:callable"')
    p.add_argument('--model-kwargs', default='{}',
                   help='JSON kwargs for the model factory')
    p.add_argument('--engine-kwargs', default='{}',
                   help='JSON kwargs for InferenceEngine')
    p.add_argument('--program-store', default=None,
                   help='ProgramStore directory (warm-start tier)')
    p.add_argument('--weight-store', default=None,
                   help='WeightStore directory (the weight plane)')
    p.add_argument('--weight-version', type=int, default=None,
                   help='version to load at boot (default: latest)')
    p.add_argument('--spool', default=None,
                   help='observability spool dir: starts a Shipper')
    p.add_argument('--uid', default='',
                   help='process uid for spool segments / pidfiles')
    p.add_argument('--obs-scope', default=None)
    p.add_argument('--drain-deadline-s', type=float, default=30.0)
    p.add_argument('--heartbeat-file', default=None,
                   help=argparse.SUPPRESS)   # reserved
    return p


def main(argv=None) -> int:
    try:
        opts = _build_parser().parse_args(argv)
        model_kwargs = json.loads(opts.model_kwargs)
        engine_kwargs = json.loads(opts.engine_kwargs)
        factory = _resolve_factory(opts.model_spec)
    except SystemExit:
        return EXIT_USAGE
    except Exception:
        traceback.print_exc()
        return EXIT_USAGE

    # program store FIRST: the engine's build-time preload must hit the
    # persistent tier, not the compiler
    try:
        if opts.program_store:
            from .. import programs
            programs.configure(opts.program_store)
        model = factory(**model_kwargs)
        model.eval()
        from .engine import InferenceEngine
        engine = InferenceEngine(model, **engine_kwargs)
        weight_store = None
        if opts.weight_store:
            from .hotswap import WeightStore
            weight_store = WeightStore(opts.weight_store)
            version = (opts.weight_version
                       if opts.weight_version is not None
                       else weight_store.latest_version())
            if version is not None:
                state = weight_store.load(int(version))
                engine.swap_weights(state, version=int(version))
    except Exception:
        traceback.print_exc()
        return EXIT_LOAD

    if opts.obs_scope:
        engine.obs_scope = opts.obs_scope

    # warm the incidental non-store programs (host<->device converts)
    # before the ready-marks snapshot, mirroring bench coldstart: the
    # serving-window compile delta must isolate store-owned executables
    import jax.numpy as jnp
    import numpy as np
    _ = np.asarray(jnp.asarray([1, 2, 3], jnp.int32))
    _ = float(np.asarray(jnp.asarray(0.0, jnp.float32)))

    preempt = engine.enable_graceful_drain(
        deadline_s=opts.drain_deadline_s)

    shipper = None
    if opts.spool:
        from ..observability.shipper import Shipper
        shipper = Shipper(opts.spool, interval_s=0.5,
                          uid=opts.uid or None)
        shipper.start()

    # bind LAST: a connectable socket is the readiness signal the
    # supervisor polls for, so it must imply "warm and serviceable"
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        if os.path.exists(opts.socket):
            os.unlink(opts.socket)   # stale tenant of our own path
        listener.bind(opts.socket)
        listener.listen(8)
    except OSError:
        traceback.print_exc()
        return EXIT_LOAD

    server = _ReplicaServer(engine, listener,
                            weight_store=weight_store, preempt=preempt,
                            drain_deadline_s=opts.drain_deadline_s,
                            uid=opts.uid)
    try:
        server.serve_forever()
    finally:
        try:
            listener.close()
            if os.path.exists(opts.socket):
                os.unlink(opts.socket)
        except OSError:
            pass
        if shipper is not None:
            shipper.stop(flush=True)
    return EXIT_CLEAN


if __name__ == '__main__':
    sys.exit(main())
