"""Slot-pooled KV cache: N fixed slots x max_length, allocated ONCE —
held as PER-SLOT sub-buffers so single-slot writes never round-trip the
whole pool.

vLLM's PagedAttention (Kwon et al. SOSP'23) pools KV memory in small
blocks behind an address-translation step; on TPU the same "requests
share one preallocated cache" idea wants STATIC shapes, so the pool here
is the coarser fixed-slot variant: one [1, max_length, H_kv, D] row per
slot per layer (exactly the model's own `init_cache` layout with the
batch dim reinterpreted as slots). A slot is the unit of admission:
alloc on prefill, free on retirement, and the decode step runs over ALL
slots every iteration with per-slot positions — freed slots are simply
masked until a new request overwrites them, so admission never
recompiles anything.

Representation (the ISSUE-13 copy-surface shrink): the cache is a
LIST of per-slot row pytrees, not one stacked [N, ...] buffer. Under
the PR-8 jaxlib constraint store-served programs run undonated, so any
program that takes the stacked pool and returns it materializes a full
pool copy — an ~18ms/program floor that bounded chunked prefill's win.
With per-slot rows:

- prefill / chunk-prefill programs take and return ONE row — the
  undonated copy surface shrinks from O(pool) to O(row) = pool/N;
- `write_slot` / `copy_slot` are host-side row replacements (a pointer
  assignment and a one-row device copy respectively) — the jitted
  full-pool writer and copier are GONE, along with their compiles;
- the decode block stacks the rows inside the program
  (`stack_rows`) and splits its output back (`split_rows`) —
  bit-identical math, and when the donation gauntlet enables donation
  the row inputs alias the outputs so even that round trip vanishes.

Prefill shapes are length-bucketed: a prompt of length s runs at the
smallest bucket >= s (right-padded; pad KV lands above the live
position, where the slot-causal decode mask hides it until the slot's
own decode overwrites it — the same stale-slot argument as speculative
decoding). Buckets bound the number of prefill compilations to
O(len(buckets)), not O(distinct prompt lengths).
"""
from __future__ import annotations

import bisect
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

_tree = jax.tree_util


def default_buckets(max_length: int, smallest: int = 8) -> Tuple[int, ...]:
    """Powers of two from `smallest` up to max_length (max_length always
    included so every admissible prompt has a bucket)."""
    out: List[int] = []
    b = smallest
    while b < max_length:
        out.append(b)
        b *= 2
    out.append(max_length)
    return tuple(out)


def stack_rows(rows):
    """Stack a sequence of per-slot row pytrees (leaves [1, ...]) into
    the decode-facing pool pytree (leaves [N, ...]). Traced inside the
    decode program — the math downstream is bit-identical to the old
    stacked representation."""
    return _tree.tree_map(lambda *ls: jnp.concatenate(ls, axis=0), *rows)


def split_rows(stacked, n: int):
    """Inverse of `stack_rows`: the decode program's output pool back
    into n per-slot rows (the host-side list representation)."""
    return tuple(
        _tree.tree_map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, i, 1, axis=0),
            stacked)
        for i in range(n))


def _leaf_bytes(tree) -> int:
    return sum(int(getattr(leaf, 'nbytes', 0) or
                   leaf.size * leaf.dtype.itemsize)
               for leaf in _tree.tree_leaves(tree))


class SlotPool:
    """Owns the per-slot KV rows + the slot free list.

    Each row is whatever `model.init_cache(1, max_length)` returns
    (per-layer (K, V) pairs for every causal-LM family here), so the
    pool works for any model honoring the init_cache contract.
    """

    def __init__(self, model, num_slots: int, max_length: int,
                 dtype=None, buckets: Optional[Sequence[int]] = None):
        if num_slots < 1:
            raise ValueError('num_slots must be >= 1')
        if max_length < 2:
            raise ValueError('max_length must be >= 2')
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        base = model.init_cache(self.num_slots, self.max_length, dtype)
        # split ONCE into per-slot rows (a one-time device slice); the
        # base stacked buffer is dropped
        self.rows: List[Any] = [
            _tree.tree_map(lambda c: c[i:i + 1], base)
            for i in range(self.num_slots)]
        self.row_spec = _tree.tree_map(
            lambda c: jax.ShapeDtypeStruct((1,) + tuple(c.shape[1:]),
                                           c.dtype), base)
        self.row_bytes = _leaf_bytes(self.rows[0])
        self.pool_bytes = self.row_bytes * self.num_slots
        self.buckets = tuple(sorted(set(
            int(b) for b in (buckets or default_buckets(self.max_length))
            if int(b) <= self.max_length)))
        if not self.buckets:
            raise ValueError('no prefill bucket <= max_length')
        self._free = sorted(range(self.num_slots), reverse=True)
        # chunked-prefill config rides the pool so stats()/debuggers see
        # the full prefill geometry in one place (the engine sets it)
        self.prefill_chunk_tokens: Optional[int] = None
        # copy-surface accounting (the bench donation phase reports the
        # bytes delta vs the old full-pool round trips)
        self._row_writes = 0
        self._row_copies = 0
        self._copied_bytes = 0

    # -- slot lifecycle ----------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_count / self.num_slots

    def alloc(self) -> int:
        """Claim the lowest free slot index; raises when full (the
        scheduler checks free_count before admitting)."""
        if not self._free:
            raise RuntimeError('slot pool exhausted')
        return self._free.pop()

    def free(self, slot: int):
        if not 0 <= slot < self.num_slots:
            raise ValueError(f'slot {slot} out of range')
        if slot in self._free:
            raise ValueError(f'slot {slot} is already free')
        self._free.append(slot)
        self._free.sort(reverse=True)

    # -- prefill bucketing -------------------------------------------------
    def bucket_for(self, length: int) -> int:
        """Smallest bucket >= length; ValueError past the largest.
        `bisect` over the sorted bucket tuple — this runs once per
        submit AND once per scheduler admission pass, so it must not be
        a linear scan of a long custom bucket list."""
        i = bisect.bisect_left(self.buckets, length)
        if i == len(self.buckets):
            raise ValueError(
                f'prompt length {length} exceeds the largest prefill '
                f'bucket {self.buckets[-1]} (max_length '
                f'{self.max_length})')
        return self.buckets[i]

    # -- the cache pytree (decode-facing view) -----------------------------
    @property
    def cache(self):
        """The decode program's pool argument: a tuple of per-slot row
        pytrees (jax flattens it as one input tree)."""
        return tuple(self.rows)

    @cache.setter
    def cache(self, new_rows):
        """Accepts the decode program's output (a sequence of N row
        pytrees) — a host-side pointer swap per slot, no device work."""
        new_rows = list(new_rows)
        if len(new_rows) != self.num_slots:
            raise ValueError(
                f'pool update has {len(new_rows)} rows, expected '
                f'{self.num_slots}')
        self.rows = new_rows

    def row(self, slot: int):
        return self.rows[slot]

    def set_row(self, slot: int, row):
        """Replace one slot's row (dtype-cast against the row spec so a
        float32 slab lands in a bf16 pool without moving any OTHER
        slot). THE single-slot write surface: O(row), never O(pool)."""
        self.rows[slot] = _tree.tree_map(
            lambda spec, leaf: leaf if leaf.dtype == spec.dtype
            else leaf.astype(spec.dtype),
            self.row_spec, row)
        self._row_writes += 1

    def write_slot(self, slot: int, slab):
        """Store a batch-1 prefill cache (leaves [1, max_length, ...])
        as the pool's row `slot` — the hand-off from prefill to the
        pooled decode step. A host-side row replacement (plus an astype
        when the dtypes differ): the old jitted full-pool scatter — and
        its full-pool output copy — is gone."""
        self.set_row(slot, slab)

    def copy_slot(self, src: int, dst: int):
        """Copy row `src` into row `dst` (the prefix-cache hit path: a
        retained prefix row becomes the new request's KV floor; stale
        positions above the prefix are masked until the request's own
        prefill/decode overwrites them). A ONE-row device copy — a real
        copy, not an alias, so a donated decode round can never see the
        same buffer twice."""
        self.rows[dst] = _tree.tree_map(jnp.array, self.rows[src])
        self._row_copies += 1
        self._copied_bytes += self.row_bytes

    def reset_rows(self):
        """Re-zero every row (fresh buffers). The donation-failure
        recovery path: if a DONATED decode program dies mid-call its
        input rows may already be invalidated, so the engine rebuilds
        the pool rather than risk stacking dead buffers."""
        self.rows = [
            _tree.tree_map(lambda s: jnp.zeros(s.shape, s.dtype),
                           self.row_spec)
            for _ in range(self.num_slots)]

    def stats(self) -> dict:
        return {'num_slots': self.num_slots, 'max_length': self.max_length,
                'used': self.used_count, 'free': self.free_count,
                'buckets': list(self.buckets),
                'prefill_chunk_tokens': self.prefill_chunk_tokens,
                'row_bytes': self.row_bytes,
                'pool_bytes': self.pool_bytes,
                'row_writes': self._row_writes,
                'row_copies': self._row_copies,
                'copied_bytes': self._copied_bytes}
