"""Slot-pooled KV cache: N fixed slots x max_length, allocated ONCE.

vLLM's PagedAttention (Kwon et al. SOSP'23) pools KV memory in small
blocks behind an address-translation step; on TPU the same "requests
share one preallocated cache" idea wants STATIC shapes, so the pool here
is the coarser fixed-slot variant: one [num_slots, max_length, H_kv, D]
cache per layer (exactly the model's own `init_cache` layout with the
batch dim reinterpreted as slots). A slot is the unit of admission:
alloc on prefill, free on retirement, and the decode step runs over ALL
slots every iteration with per-slot positions — freed slots are simply
masked until a new request overwrites them, so admission never
recompiles anything.

Prefill shapes are length-bucketed: a prompt of length s runs at the
smallest bucket >= s (right-padded; pad KV lands above the live
position, where the slot-causal decode mask hides it until the slot's
own decode overwrites it — the same stale-slot argument as speculative
decoding). Buckets bound the number of prefill compilations to
O(len(buckets)), not O(distinct prompt lengths).
"""
from __future__ import annotations

import bisect
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def default_buckets(max_length: int, smallest: int = 8) -> Tuple[int, ...]:
    """Powers of two from `smallest` up to max_length (max_length always
    included so every admissible prompt has a bucket)."""
    out: List[int] = []
    b = smallest
    while b < max_length:
        out.append(b)
        b *= 2
    out.append(max_length)
    return tuple(out)


class SlotPool:
    """Owns the pooled cache pytree + the slot free list.

    The cache is whatever `model.init_cache(num_slots, max_length)`
    returns (per-layer (K, V) pairs for every causal-LM family here), so
    the pool works for any model honoring the init_cache contract.
    """

    def __init__(self, model, num_slots: int, max_length: int,
                 dtype=None, buckets: Optional[Sequence[int]] = None):
        if num_slots < 1:
            raise ValueError('num_slots must be >= 1')
        if max_length < 2:
            raise ValueError('max_length must be >= 2')
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        self.cache = model.init_cache(self.num_slots, self.max_length,
                                      dtype)
        self.buckets = tuple(sorted(set(
            int(b) for b in (buckets or default_buckets(self.max_length))
            if int(b) <= self.max_length)))
        if not self.buckets:
            raise ValueError('no prefill bucket <= max_length')
        self._free = sorted(range(self.num_slots), reverse=True)
        # chunked-prefill config rides the pool so stats()/debuggers see
        # the full prefill geometry in one place (the engine sets it)
        self.prefill_chunk_tokens: Optional[int] = None
        self._write_traces = 0
        self._copy_traces = 0
        self._write_jit = jax.jit(self._write_fn)
        self._copy_jit = jax.jit(self._copy_fn)

    # -- slot lifecycle ----------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_count / self.num_slots

    def alloc(self) -> int:
        """Claim the lowest free slot index; raises when full (the
        scheduler checks free_count before admitting)."""
        if not self._free:
            raise RuntimeError('slot pool exhausted')
        return self._free.pop()

    def free(self, slot: int):
        if not 0 <= slot < self.num_slots:
            raise ValueError(f'slot {slot} out of range')
        if slot in self._free:
            raise ValueError(f'slot {slot} is already free')
        self._free.append(slot)
        self._free.sort(reverse=True)

    # -- prefill bucketing -------------------------------------------------
    def bucket_for(self, length: int) -> int:
        """Smallest bucket >= length; ValueError past the largest.
        `bisect` over the sorted bucket tuple — this runs once per
        submit AND once per scheduler admission pass, so it must not be
        a linear scan of a long custom bucket list."""
        i = bisect.bisect_left(self.buckets, length)
        if i == len(self.buckets):
            raise ValueError(
                f'prompt length {length} exceeds the largest prefill '
                f'bucket {self.buckets[-1]} (max_length '
                f'{self.max_length})')
        return self.buckets[i]

    # -- pooled-cache writes -----------------------------------------------
    def _write_fn(self, pool, slab, slot):
        # one compile total: `slot` is traced, shapes are static
        self._write_traces += 1
        return jax.tree_util.tree_map(
            lambda c, s: jax.lax.dynamic_update_slice(
                c, s.astype(c.dtype),
                (slot,) + (0,) * (c.ndim - 1)),
            pool, slab)

    def write_slot(self, slot: int, slab):
        """Scatter a batch-1 prefill cache (leaves [1, max_length, ...])
        into the pool's row `slot` — the hand-off from prefill to the
        pooled decode step."""
        self.cache = self._write_jit(self.cache, slab,
                                     jnp.int32(slot))

    def _copy_fn(self, pool, src, dst):
        # one compile total: src/dst are traced, shapes are static
        self._copy_traces += 1
        return jax.tree_util.tree_map(
            lambda c: jax.lax.dynamic_update_slice(
                c,
                jax.lax.dynamic_slice(
                    c, (src,) + (0,) * (c.ndim - 1),
                    (1,) + c.shape[1:]),
                (dst,) + (0,) * (c.ndim - 1)),
            pool)

    def copy_slot(self, src: int, dst: int):
        """Copy row `src` into row `dst` across the whole cache pytree
        (the prefix-cache hit path: a retained prefix row becomes the
        new request's KV floor; stale positions above the prefix are
        masked until the request's own prefill/decode overwrites them).
        One compiled program regardless of src/dst."""
        self.cache = self._copy_jit(self.cache, jnp.int32(src),
                                    jnp.int32(dst))

    def stats(self) -> dict:
        return {'num_slots': self.num_slots, 'max_length': self.max_length,
                'used': self.used_count, 'free': self.free_count,
                'buckets': list(self.buckets),
                'prefill_chunk_tokens': self.prefill_chunk_tokens,
                'write_traces': self._write_traces,
                'copy_traces': self._copy_traces}
