"""Slot-pooled KV cache: N fixed slots x max_length, allocated ONCE —
held as PER-SLOT sub-buffers so single-slot writes never round-trip the
whole pool.

vLLM's PagedAttention (Kwon et al. SOSP'23) pools KV memory in small
blocks behind an address-translation step; on TPU the same "requests
share one preallocated cache" idea wants STATIC shapes, so the pool here
is the coarser fixed-slot variant: one [1, max_length, H_kv, D] row per
slot per layer (exactly the model's own `init_cache` layout with the
batch dim reinterpreted as slots). A slot is the unit of admission:
alloc on prefill, free on retirement, and the decode step runs over ALL
slots every iteration with per-slot positions — freed slots are simply
masked until a new request overwrites them, so admission never
recompiles anything.

Representation (the ISSUE-13 copy-surface shrink): the cache is a
LIST of per-slot row pytrees, not one stacked [N, ...] buffer. Under
the PR-8 jaxlib constraint store-served programs run undonated, so any
program that takes the stacked pool and returns it materializes a full
pool copy — an ~18ms/program floor that bounded chunked prefill's win.
With per-slot rows:

- prefill / chunk-prefill programs take and return ONE row — the
  undonated copy surface shrinks from O(pool) to O(row) = pool/N;
- `write_slot` / `copy_slot` are host-side row replacements (a pointer
  assignment and a one-row device copy respectively) — the jitted
  full-pool writer and copier are GONE, along with their compiles;
- the decode block stacks the rows inside the program
  (`stack_rows`) and splits its output back (`split_rows`) —
  bit-identical math, and when the donation gauntlet enables donation
  the row inputs alias the outputs so even that round trip vanishes.

Prefill shapes are length-bucketed: a prompt of length s runs at the
smallest bucket >= s (right-padded; pad KV lands above the live
position, where the slot-causal decode mask hides it until the slot's
own decode overwrites it — the same stale-slot argument as speculative
decoding). Buckets bound the number of prefill compilations to
O(len(buckets)), not O(distinct prompt lengths).

Paged mode (ISSUE 16): `PagedSlotPool` is the finer-grained variant —
the true PagedAttention layout under the same static-shape discipline.
KV storage is ONE [num_pages, page_size, H_kv, D] buffer per layer
leaf; a slot owns a page LIST (a row of the [num_slots,
pages_per_slot] page table, host-side), pages come from a free list,
and page id 0 is a reserved NULL page: unreserved table entries point
at it, so out-of-range program writes land in junk that no mask ever
attends. Sharing is per-PAGE with refcounts: the prefix cache pins a
prefix's pages once (`hold_pages`) and every live request that hits it
attaches the same page ids read-only (`attach_prefix`); the only
write-into-shared-page case (a full-prompt hit re-forwarding its last
token) is copy-on-write split via `ensure_exclusive`. The compiled
programs see (pages, scales, table) and translate addresses with
`gather_pages` / `scatter_pages` — gather reconstructs the SAME
[N, max_length, H, D] contiguous view the row pool stacks, so the
decode math (and greedy output) is bit-identical; scatter writes back
only the pages overlapping the written span, so settled int8 pages are
never requantized. Optional int8 storage keeps per-(page, head) absmax
scales (quantization.kv_page_scales semantics) alongside the pages.
"""
from __future__ import annotations

import bisect
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_tree = jax.tree_util


class PromptTooLongError(ValueError):
    """A prompt is longer than the largest prefill bucket (and therefore
    than max_length). Subclasses ValueError so pre-ISSUE-16 callers that
    caught ValueError keep working; typed so admission layers can
    distinguish 'request can never fit' from other validation errors."""


class PagePoolExhausted(RuntimeError):
    """No free KV pages for a reservation. Subclasses RuntimeError so it
    rides the engine's existing requeue-on-exhaustion path: the request
    is NOT failed — it goes back to the queue front and admission waits
    for retirements (or prefix-cache evictions) to return pages."""


def default_buckets(max_length: int, smallest: int = 8) -> Tuple[int, ...]:
    """Powers of two from `smallest` up to max_length (max_length always
    included so every admissible prompt has a bucket)."""
    out: List[int] = []
    b = smallest
    while b < max_length:
        out.append(b)
        b *= 2
    out.append(max_length)
    return tuple(out)


def stack_rows(rows):
    """Stack a sequence of per-slot row pytrees (leaves [1, ...]) into
    the decode-facing pool pytree (leaves [N, ...]). Traced inside the
    decode program — the math downstream is bit-identical to the old
    stacked representation."""
    return _tree.tree_map(lambda *ls: jnp.concatenate(ls, axis=0), *rows)


def split_rows(stacked, n: int):
    """Inverse of `stack_rows`: the decode program's output pool back
    into n per-slot rows (the host-side list representation)."""
    return tuple(
        _tree.tree_map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, i, 1, axis=0),
            stacked)
        for i in range(n))


def _leaf_bytes(tree) -> int:
    return sum(int(getattr(leaf, 'nbytes', 0) or
                   leaf.size * leaf.dtype.itemsize)
               for leaf in _tree.tree_leaves(tree))


def _normalize_buckets(buckets, max_length: int) -> Tuple[int, ...]:
    out = tuple(sorted(set(
        int(b) for b in (buckets or default_buckets(max_length))
        if int(b) <= max_length)))
    if not out:
        raise ValueError('no prefill bucket <= max_length')
    return out


def _bucket_for(buckets: Tuple[int, ...], length: int,
                max_length: int) -> int:
    """Smallest bucket >= length; PromptTooLongError past the largest.
    `bisect` over the sorted bucket tuple — this runs once per submit
    AND once per scheduler admission pass, so it must not be a linear
    scan of a long custom bucket list."""
    i = bisect.bisect_left(buckets, length)
    if i == len(buckets):
        raise PromptTooLongError(
            f'prompt length {length} exceeds the largest prefill '
            f'bucket {buckets[-1]} (max_length {max_length})')
    return buckets[i]


class SlotPool:
    """Owns the per-slot KV rows + the slot free list.

    Each row is whatever `model.init_cache(1, max_length)` returns
    (per-layer (K, V) pairs for every causal-LM family here), so the
    pool works for any model honoring the init_cache contract.
    """

    def __init__(self, model, num_slots: int, max_length: int,
                 dtype=None, buckets: Optional[Sequence[int]] = None):
        if num_slots < 1:
            raise ValueError('num_slots must be >= 1')
        if max_length < 2:
            raise ValueError('max_length must be >= 2')
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        base = model.init_cache(self.num_slots, self.max_length, dtype)
        # split ONCE into per-slot rows (a one-time device slice); the
        # base stacked buffer is dropped
        self.rows: List[Any] = [
            _tree.tree_map(lambda c: c[i:i + 1], base)
            for i in range(self.num_slots)]
        self.row_spec = _tree.tree_map(
            lambda c: jax.ShapeDtypeStruct((1,) + tuple(c.shape[1:]),
                                           c.dtype), base)
        self.row_bytes = _leaf_bytes(self.rows[0])
        self.pool_bytes = self.row_bytes * self.num_slots
        self.buckets = _normalize_buckets(buckets, self.max_length)
        self._free = sorted(range(self.num_slots), reverse=True)
        # per-slot high-water mark of WRITTEN rows (vs the max_length
        # rows a slot always allocates) — the stranded-capacity figure
        # the paged A/B reports utilization against
        self._written = [0] * self.num_slots
        # chunked-prefill config rides the pool so stats()/debuggers see
        # the full prefill geometry in one place (the engine sets it)
        self.prefill_chunk_tokens: Optional[int] = None
        # copy-surface accounting (the bench donation phase reports the
        # bytes delta vs the old full-pool round trips)
        self._row_writes = 0
        self._row_copies = 0
        self._copied_bytes = 0

    # -- slot lifecycle ----------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_count / self.num_slots

    def alloc(self) -> int:
        """Claim the lowest free slot index; raises when full (the
        scheduler checks free_count before admitting)."""
        if not self._free:
            raise RuntimeError('slot pool exhausted')
        return self._free.pop()

    def free(self, slot: int):
        if not 0 <= slot < self.num_slots:
            raise ValueError(f'slot {slot} out of range')
        if slot in self._free:
            raise ValueError(f'slot {slot} is already free')
        self._free.append(slot)
        self._free.sort(reverse=True)
        self._written[slot] = 0

    def note_written(self, slot: int, rows) -> None:
        """Record that `slot` holds live KV through row `rows` (the
        engine calls this at prefill and after each decode round); the
        high-water mark feeds the stranded-capacity stats."""
        r = min(int(rows), self.max_length)
        if r > self._written[slot]:
            self._written[slot] = r

    # -- prefill bucketing -------------------------------------------------
    def bucket_for(self, length: int) -> int:
        """Smallest bucket >= length; `PromptTooLongError` (a ValueError)
        past the largest bucket."""
        return _bucket_for(self.buckets, length, self.max_length)

    # -- the cache pytree (decode-facing view) -----------------------------
    @property
    def cache(self):
        """The decode program's pool argument: a tuple of per-slot row
        pytrees (jax flattens it as one input tree)."""
        return tuple(self.rows)

    @cache.setter
    def cache(self, new_rows):
        """Accepts the decode program's output (a sequence of N row
        pytrees) — a host-side pointer swap per slot, no device work."""
        new_rows = list(new_rows)
        if len(new_rows) != self.num_slots:
            raise ValueError(
                f'pool update has {len(new_rows)} rows, expected '
                f'{self.num_slots}')
        self.rows = new_rows

    def row(self, slot: int):
        return self.rows[slot]

    def set_row(self, slot: int, row):
        """Replace one slot's row (dtype-cast against the row spec so a
        float32 slab lands in a bf16 pool without moving any OTHER
        slot). THE single-slot write surface: O(row), never O(pool)."""
        self.rows[slot] = _tree.tree_map(
            lambda spec, leaf: leaf if leaf.dtype == spec.dtype
            else leaf.astype(spec.dtype),
            self.row_spec, row)
        self._row_writes += 1

    def write_slot(self, slot: int, slab):
        """Store a batch-1 prefill cache (leaves [1, max_length, ...])
        as the pool's row `slot` — the hand-off from prefill to the
        pooled decode step. A host-side row replacement (plus an astype
        when the dtypes differ): the old jitted full-pool scatter — and
        its full-pool output copy — is gone."""
        self.set_row(slot, slab)

    def copy_slot(self, src: int, dst: int):
        """Copy row `src` into row `dst` (the prefix-cache hit path: a
        retained prefix row becomes the new request's KV floor; stale
        positions above the prefix are masked until the request's own
        prefill/decode overwrites them). A ONE-row device copy — a real
        copy, not an alias, so a donated decode round can never see the
        same buffer twice."""
        self.rows[dst] = _tree.tree_map(jnp.array, self.rows[src])
        self._row_copies += 1
        self._copied_bytes += self.row_bytes

    def reset_rows(self):
        """Re-zero every row (fresh buffers). The donation-failure
        recovery path: if a DONATED decode program dies mid-call its
        input rows may already be invalidated, so the engine rebuilds
        the pool rather than risk stacking dead buffers."""
        self.rows = [
            _tree.tree_map(lambda s: jnp.zeros(s.shape, s.dtype),
                           self.row_spec)
            for _ in range(self.num_slots)]

    def _capacity_stats(self) -> dict:
        """Allocated vs written rows over USED slots: the row pool
        allocates max_length rows per seated request no matter how few
        it writes, and `stranded_rows` is exactly that waste (the paged
        A/B's honesty metric; ~0 for the paged pool by construction)."""
        used = [s for s in range(self.num_slots) if s not in self._free]
        allocated = sum(self.allocated_rows(s) for s in used)
        written = sum(self._written[s] for s in used)
        return {
            'allocated_rows': allocated,
            'written_rows': written,
            'stranded_rows': allocated - written,
            'row_utilization': written / allocated if allocated else 1.0,
            'slot_written_rows': {s: self._written[s] for s in used},
        }

    def allocated_rows(self, slot: int) -> int:
        """KV rows reserved for `slot` while seated (a whole row here;
        the paged pool overrides with its page-granular figure)."""
        return self.max_length

    def stats(self) -> dict:
        return {'num_slots': self.num_slots, 'max_length': self.max_length,
                'used': self.used_count, 'free': self.free_count,
                'buckets': list(self.buckets),
                'prefill_chunk_tokens': self.prefill_chunk_tokens,
                'row_bytes': self.row_bytes,
                'pool_bytes': self.pool_bytes,
                'row_writes': self._row_writes,
                'row_copies': self._row_copies,
                'copied_bytes': self._copied_bytes,
                **self._capacity_stats()}


# ---------------------------------------------------------------------------
# paged pool (ISSUE 16)
# ---------------------------------------------------------------------------

def gather_pages(pages, table, scales=None, out_dtype=None):
    """Address-translate the page pool into the decode-facing contiguous
    view: leaves [num_pages, ps, H, D] indexed by `table` [N, P] become
    [N, P*ps, H, D] = [N, max_length, H, D] — the SAME shape (and, for
    the unquantized path, the same bits) the row pool's `stack_rows`
    feeds the decode scan, so the attention math downstream is
    bit-identical. With `scales` (per-(page, head) int8 scales, leaves
    [num_pages, H]) the gather dequantizes in the same expression.
    Traced inside every paged program."""
    from ..quantization import kv_dequantize_page
    n, p = table.shape

    def g(leaf, s=None):
        out = leaf[table]                       # [N, P, ps, H, D]
        if s is not None:
            out = kv_dequantize_page(out, s[table],
                                     out_dtype or jnp.float32)
        out = out.reshape(n, p * leaf.shape[1], *leaf.shape[2:])
        return out if out_dtype is None else out.astype(out_dtype)

    if scales is None:
        return _tree.tree_map(g, pages)
    return _tree.tree_map(g, pages, scales)


def scatter_pages(pages, table, contig, start, length: int,
                  page_size: int, scales=None, floor=None):
    """Write the span [start, start+length) of the contiguous view back
    into the page pool — ONLY the pages overlapping the span. `start` is
    per-slot traced [N]; `length` is static, so the window count is
    static: a length-L span can straddle at most (L+ps-2)//ps + 1 pages
    at any alignment. Windows outside a slot's actual span are redirected
    to the NULL page (id 0) so untouched pages are never rewritten —
    which is what keeps settled int8 pages from requantization drift,
    and makes the unquantized path an exact-value (bit-identical)
    writeback. With `scales`, each touched page is (re)quantized at its
    fresh per-(page, head) absmax scale. `floor` (traced [N], rows)
    additionally redirects pages that end at or below it — the chunk
    programs pass the prefix-attach boundary so a tail-shifted window
    that re-forwards already-settled rows can never rewrite a SHARED
    page. Returns (pages, scales)."""
    from ..quantization import kv_page_scales, kv_quantize_page
    n, p = table.shape
    first = start // page_size                  # [N]
    nwin = (length + page_size - 2) // page_size + 1

    def upd(leaf, s_leaf, cont):
        for w in range(nwin):
            idx = jnp.clip(first + w, 0, p - 1)             # [N]
            pid = jnp.take_along_axis(table, idx[:, None], 1)[:, 0]
            touched = ((idx * page_size < start + length)
                       & ((idx + 1) * page_size > start))
            if floor is not None:
                touched &= (idx + 1) * page_size > floor
            pid = jnp.where(touched, pid, 0)    # junk -> null page
            sl = jax.vmap(
                lambda c, i: jax.lax.dynamic_slice_in_dim(
                    c, i * page_size, page_size, axis=0))(
                        cont, idx)              # [N, ps, H, D]
            if s_leaf is not None:
                sc = kv_page_scales(sl)
                leaf = leaf.at[pid].set(kv_quantize_page(sl, sc))
                s_leaf = s_leaf.at[pid].set(sc)
            else:
                leaf = leaf.at[pid].set(sl.astype(leaf.dtype))
        return leaf, s_leaf

    if scales is None:
        out = _tree.tree_map(lambda lf, ct: upd(lf, None, ct)[0],
                             pages, contig)
        return out, None
    flat_p, treedef = _tree.tree_flatten(pages)
    flat_s = treedef.flatten_up_to(scales)
    flat_c = treedef.flatten_up_to(contig)
    new_p, new_s = [], []
    for lf, s, ct in zip(flat_p, flat_s, flat_c):
        a, b = upd(lf, s, ct)
        new_p.append(a)
        new_s.append(b)
    return (_tree.tree_unflatten(treedef, new_p),
            _tree.tree_unflatten(treedef, new_s))


class PageHold:
    """A reference-counted pin on a set of pages (the prefix cache's
    retained resource in paged mode): the first `kv_len` rows across
    `pages` are a prompt prefix's prefill KV. Created by
    `PagedSlotPool.hold_pages`, released by `release_hold` — the pages
    survive the originating slot's free for as long as the hold lives."""

    __slots__ = ('pages', 'kv_len', 'released')

    def __init__(self, pages: Tuple[int, ...], kv_len: int):
        self.pages = tuple(int(p) for p in pages)
        self.kv_len = int(kv_len)
        self.released = False

    def __len__(self):
        return len(self.pages)

    def __repr__(self):
        return (f'PageHold(pages={len(self.pages)}, kv_len={self.kv_len}'
                f'{", released" if self.released else ""})')


class PagedSlotPool:
    """Page-table KV pool: fixed-size pages, per-slot page lists,
    free-list allocation, copy-on-write refcounts (vLLM's PagedAttention
    memory manager under TPU static shapes).

    Storage is `model.init_cache(num_pages, page_size)` — per-layer
    (K, V) leaves [num_pages, page_size, H_kv, D] — so any model
    honoring the init_cache contract pools unchanged. Page id 0 is the
    reserved NULL page (junk sink for out-of-span program writes; never
    allocated, never attended unmasked). With `quant='int8'` the pages
    are int8 with per-(page, head) float32 absmax scales; gather
    dequantizes, scatter requantizes touched pages only.

    Admission is reservation-based: `reserve(slot, total_len)` claims
    every page the request can touch (prompt + new tokens + speculation
    headroom) up front, so a seated request can never die of page
    exhaustion mid-decode — exhaustion surfaces at admission as
    `PagePoolExhausted` and the engine requeues.
    """

    def __init__(self, model, num_slots: int, max_length: int,
                 dtype=None, buckets: Optional[Sequence[int]] = None,
                 *, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 quant: Optional[str] = None):
        if num_slots < 1:
            raise ValueError('num_slots must be >= 1')
        if max_length < 2:
            raise ValueError('max_length must be >= 2')
        if page_size < 1:
            raise ValueError('page_size must be >= 1')
        if max_length % page_size != 0:
            raise ValueError(
                f'max_length {max_length} must be a multiple of '
                f'page_size {page_size} (the page table is dense)')
        if quant not in (None, 'int8'):
            raise ValueError(f"kv quant mode {quant!r} not supported "
                             f"(None or 'int8')")
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        self.page_size = int(page_size)
        self.pages_per_slot = self.max_length // self.page_size
        # +1: page 0 is the null page — a full-capacity default budget
        # still seats num_slots max-length requests
        self.num_pages = int(num_pages) if num_pages is not None else \
            self.num_slots * self.pages_per_slot + 1
        if self.num_pages < self.pages_per_slot + 1:
            raise ValueError(
                f'num_pages {self.num_pages} cannot seat even one '
                f'max-length request ({self.pages_per_slot} pages + '
                f'the null page)')
        self.quant = quant
        base = model.init_cache(self.num_pages, self.page_size, dtype)
        for leaf in _tree.tree_leaves(base):
            if leaf.ndim != 4:
                raise ValueError(
                    'PagedSlotPool requires [B, L, H, D] KV leaves, got '
                    f'shape {tuple(leaf.shape)}')
        self.compute_dtype = _tree.tree_leaves(base)[0].dtype
        if quant == 'int8':
            self.pages = _tree.tree_map(
                lambda c: jnp.zeros(c.shape, jnp.int8), base)
            self.scales = _tree.tree_map(
                lambda c: jnp.ones((c.shape[0], c.shape[2]),
                                   jnp.float32), base)
        else:
            self.pages = base
            self.scales = None
        # the row-shaped spec the (reused) whole-prefill program fills
        self.row_spec = _tree.tree_map(
            lambda c: jax.ShapeDtypeStruct(
                (1, self.max_length) + tuple(c.shape[2:]),
                self.compute_dtype), base)
        self.page_bytes = _leaf_bytes(
            _tree.tree_map(lambda c: c[:1], self.pages))
        self.row_bytes = self.page_bytes * self.pages_per_slot
        self.pool_bytes = _leaf_bytes(self.pages) + \
            (_leaf_bytes(self.scales) if self.scales is not None else 0)
        self.buckets = _normalize_buckets(buckets, self.max_length)
        self.prefill_chunk_tokens: Optional[int] = None
        # host-side address map + refcounts: entry 0 = unreserved/null
        self.page_table = np.zeros(
            (self.num_slots, self.pages_per_slot), np.int32)
        self._page_refs = np.zeros(self.num_pages, np.int64)
        self._page_refs[0] = 1                  # null page: never freed
        self._free_pages: List[int] = list(
            range(self.num_pages - 1, 0, -1))
        self._free = sorted(range(self.num_slots), reverse=True)
        self._written = [0] * self.num_slots
        self._cow_splits = 0
        self._holds_live = 0

    # -- slot lifecycle ----------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.used_count / self.num_slots

    @property
    def free_page_count(self) -> int:
        return len(self._free_pages)

    @property
    def used_page_count(self) -> int:
        return self.num_pages - 1 - len(self._free_pages)

    def pages_for(self, length: int) -> int:
        """Pages covering `length` KV rows (ceil division)."""
        return -(-int(length) // self.page_size)

    def alloc(self) -> int:
        """Claim the lowest free slot index; raises when full. Pages are
        reserved SEPARATELY (`reserve`) — a slot is just the decode-row
        index, which is host bookkeeping, not HBM."""
        if not self._free:
            raise RuntimeError('slot pool exhausted')
        return self._free.pop()

    def free(self, slot: int):
        """Release the slot AND its page references: exclusive pages
        return to the free list immediately; shared pages (a live
        PageHold or a sibling request's attach) survive at refs >= 1."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f'slot {slot} out of range')
        if slot in self._free:
            raise ValueError(f'slot {slot} is already free')
        for pid in self.page_table[slot]:
            self._decref(int(pid))
        self.page_table[slot] = 0
        self._free.append(slot)
        self._free.sort(reverse=True)
        self._written[slot] = 0

    def _decref(self, pid: int):
        if pid == 0:
            return
        self._page_refs[pid] -= 1
        if self._page_refs[pid] < 0:
            raise RuntimeError(f'page {pid} freed more than referenced')
        if self._page_refs[pid] == 0:
            self._free_pages.append(pid)

    def _incref(self, pid: int):
        if pid != 0:
            self._page_refs[pid] += 1

    # -- page lifecycle ----------------------------------------------------
    def reserve(self, slot: int, total_len: int):
        """Ensure `slot`'s table covers [0, total_len): allocate a fresh
        exclusive page for every still-null entry in range. All-or-
        nothing: raises PagePoolExhausted (allocating nothing) when the
        free list cannot cover the need, so admission can requeue
        without partial-state cleanup."""
        if total_len > self.max_length:
            raise ValueError(
                f'reservation {total_len} exceeds max_length '
                f'{self.max_length}')
        npages = self.pages_for(total_len)
        missing = [i for i in range(npages)
                   if self.page_table[slot, i] == 0]
        if len(missing) > len(self._free_pages):
            raise PagePoolExhausted(
                f'need {len(missing)} KV pages, {len(self._free_pages)} '
                f'free (of {self.num_pages - 1})')
        for i in missing:
            pid = self._free_pages.pop()
            self._page_refs[pid] = 1
            self.page_table[slot, i] = pid

    def attach_prefix(self, slot: int, hold: PageHold, npages: int):
        """Map the first `npages` of a retained prefix hold into
        `slot`'s table READ-ONLY (refcount shared). The engine only
        attaches whole pages and prefills/decodes strictly above them —
        except the full-hit pending re-forward, which must
        `ensure_exclusive` first."""
        if hold.released:
            raise RuntimeError('attach_prefix on a released PageHold')
        if npages > len(hold.pages):
            raise ValueError(
                f'attach of {npages} pages exceeds the hold '
                f'({len(hold.pages)})')
        for i in range(npages):
            if self.page_table[slot, i] != 0:
                raise RuntimeError(
                    f'slot {slot} table entry {i} already mapped')
            pid = hold.pages[i]
            self._incref(pid)
            self.page_table[slot, i] = pid

    def ensure_exclusive(self, slot: int, pos: int) -> bool:
        """Copy-on-write split: if the page holding row `pos` of `slot`
        is shared (refs > 1), copy it to a fresh page and repoint the
        table — writes at `pos` then never touch the shared original.
        Returns True when a split happened. Raises PagePoolExhausted
        when no page is free for the copy."""
        i = int(pos) // self.page_size
        pid = int(self.page_table[slot, i])  # paddle-lint: disable=host-sync -- page_table is host numpy (the address map never leaves the host)
        if pid == 0:
            raise RuntimeError(
                f'ensure_exclusive on unreserved page {i} of slot {slot}')
        if self._page_refs[pid] <= 1:
            return False
        if not self._free_pages:
            raise PagePoolExhausted(
                'no free page for a copy-on-write split')
        npid = self._free_pages.pop()
        # one-page device copy (the entire COW surface)
        self.pages = _tree.tree_map(
            lambda c: c.at[npid].set(c[pid]), self.pages)
        if self.scales is not None:
            self.scales = _tree.tree_map(
                lambda s: s.at[npid].set(s[pid]), self.scales)
        self._page_refs[npid] = 1
        self.page_table[slot, i] = npid
        self._decref(pid)
        self._cow_splits += 1
        return True

    def hold_pages(self, slot: int, kv_len: int) -> Optional[PageHold]:
        """Pin the FULL pages covering `slot`'s first `kv_len` rows as a
        PageHold (the prefix cache's retention primitive). Only whole
        pages are held — a trailing partial page is left to the slot
        (its rows above the last full page re-prefill on a hit, which
        is what keeps suffix writes out of shared pages). None when no
        full page is covered."""
        npages = int(kv_len) // self.page_size
        if npages < 1:
            return None
        pids = [int(p) for p in self.page_table[slot, :npages]]
        if any(p == 0 for p in pids):
            raise RuntimeError(
                f'hold_pages: slot {slot} has unreserved pages below '
                f'kv_len {kv_len}')
        for pid in pids:
            self._incref(pid)
        self._holds_live += 1
        return PageHold(tuple(pids), npages * self.page_size)

    def release_hold(self, hold: PageHold):
        if hold.released:
            raise RuntimeError('PageHold released twice')
        hold.released = True
        for pid in hold.pages:
            self._decref(pid)
        self._holds_live -= 1

    def note_written(self, slot: int, rows) -> None:
        r = min(int(rows), self.max_length)
        if r > self._written[slot]:
            self._written[slot] = r

    def allocated_rows(self, slot: int) -> int:
        """Rows actually reserved for `slot` = mapped pages * page_size
        (the page-granular figure the row pool cannot offer)."""
        # paddle-lint: disable-next=host-sync -- page_table is host numpy, no device read
        return int(np.count_nonzero(self.page_table[slot])) \
            * self.page_size

    def bucket_for(self, length: int) -> int:
        """Smallest bucket >= length; `PromptTooLongError` (a ValueError)
        past the largest bucket."""
        return _bucket_for(self.buckets, length, self.max_length)

    # -- device state ------------------------------------------------------
    def device_state(self) -> Tuple[Any, Any]:
        """(pages, scales) as the compiled programs take them — scales
        is an EMPTY pytree when unquantized so every program signature
        is mode-stable."""
        return self.pages, (self.scales if self.scales is not None
                            else ())

    def set_device_state(self, pages, scales):
        self.pages = pages
        if self.scales is not None:
            self.scales = scales

    def reset_pages(self):
        """Re-zero the page storage (fresh buffers) WITHOUT touching the
        table/refcount bookkeeping: the donation-failure recovery path —
        a donated paged program dying mid-call may have invalidated the
        page buffers, and the in-flight requests are about to fail
        through the normal error path, which frees their mappings."""
        self.pages = _tree.tree_map(
            lambda c: jnp.zeros(c.shape, c.dtype), self.pages)
        if self.scales is not None:
            self.scales = _tree.tree_map(
                lambda s: jnp.ones(s.shape, s.dtype), self.scales)

    def stats(self) -> dict:
        return {'num_slots': self.num_slots,
                'max_length': self.max_length,
                'used': self.used_count, 'free': self.free_count,
                'page_size': self.page_size,
                'num_pages': self.num_pages,
                'pages_per_slot': self.pages_per_slot,
                'free_pages': len(self._free_pages),
                'used_pages': self.used_page_count,
                'shared_pages': int(np.sum(self._page_refs[1:] > 1)),  # paddle-lint: disable=host-sync -- _page_refs is host numpy bookkeeping
                'holds_live': self._holds_live,
                'cow_splits': self._cow_splits,
                'kv_quant': self.quant,
                'buckets': list(self.buckets),
                'prefill_chunk_tokens': self.prefill_chunk_tokens,
                'page_bytes': self.page_bytes,
                'row_bytes': self.row_bytes,
                'pool_bytes': self.pool_bytes,
                **SlotPool._capacity_stats(self)}
