"""Replica process supervisor: spawn, heartbeat, heal, quarantine.

The fleet-runtime half of "break the one-process wall": `remote.py`
gives the Router a process-shaped replica, this module keeps those
processes ALIVE. One `Supervisor` owns a set of children running
`replica_main`, each described by one shared `ReplicaSpec` (same model
factory, same ProgramStore/WeightStore/spool planes — a fleet is N
copies of one recipe, differing only in name/socket/uid).

Failure policy, mirroring the in-process breaker philosophy (failures
are the steady state, so the machinery must be boring and bounded):

- exit-code classification: 0 → clean exit; anything else (including a
  death-by-signal negative rc) → crash; a live process whose healthz
  socket stops answering past the heartbeat deadline → hang, and a
  hang is escalated to SIGKILL — a wedged child holding its socket is
  worse than a dead one.
- restart with EXPONENTIAL BACKOFF + JITTER (deterministic RNG, so the
  fault tests can assert the spacing envelope from event timestamps).
- crash-loop circuit breaking: more than `max_restarts` crashes inside
  `restart_window_s` quarantines the replica — `replica_quarantined`
  event, pidfile/socket swept, NO further respawns. A flapping child
  burning the warm-start path is a capacity bug to page on, not to
  paper over.
- orphan reaping: on boot (and before every spawn) stale pidfiles from
  a previous supervisor incarnation are checked against /proc — a live
  orphan whose cmdline really is a replica_main gets SIGKILLed, and
  its socket/pidfile/spool remnants are swept, so a crashed supervisor
  never leaks replica processes or lets a zombie serve stale weights.

The Autoscaler plugs in unchanged: `supervisor.replica_factory()` is
its `replica_factory` (scale-up spawns a real process and joins it via
`router.add_replica`), and scale-down's `remove_replica` is followed
by `RemoteReplica.retire()` which lands back here as SIGTERM → drain →
reap. Every transition emits a declared event; the PR-17 spool/
aggregator plane makes them fleet-visible.

All timing flows through an injectable `clock` and all process control
through injectable `popen_fn`/`connect_fn`, so the state-machine fault
tests run on synthetic children with zero real spawns.
"""
from __future__ import annotations

import json
import os
import random
import shutil
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .. import observability as _obs
from ..analysis.runtime import concurrency as _concurrency

# child lifecycle states
SPAWNING = 'spawning'
READY = 'ready'
BACKOFF = 'backoff'
QUARANTINED = 'quarantined'
RETIRING = 'retiring'
STOPPED = 'stopped'


@dataclass
class ReplicaSpec:
    """One recipe for a replica process (shared across the fleet)."""
    model_spec: str
    model_kwargs: Dict[str, Any] = field(default_factory=dict)
    engine_kwargs: Dict[str, Any] = field(default_factory=dict)
    program_store_dir: Optional[str] = None
    weight_store_dir: Optional[str] = None
    weight_version: Optional[int] = None
    spool_dir: Optional[str] = None
    drain_deadline_s: float = 30.0
    env: Dict[str, str] = field(default_factory=dict)

    def argv(self, python: str, socket_path: str, uid: str,
             obs_scope: Optional[str] = None) -> List[str]:
        cmd = [python, '-m', 'paddle_tpu.serving.replica_main',
               '--socket', socket_path,
               '--model-spec', self.model_spec,
               '--model-kwargs', json.dumps(self.model_kwargs),
               '--engine-kwargs', json.dumps(self.engine_kwargs),
               '--uid', uid,
               '--drain-deadline-s', str(self.drain_deadline_s)]
        if self.program_store_dir:
            cmd += ['--program-store', self.program_store_dir]
        if self.weight_store_dir:
            cmd += ['--weight-store', self.weight_store_dir]
        if self.weight_version is not None:
            cmd += ['--weight-version', str(self.weight_version)]
        if self.spool_dir:
            cmd += ['--spool', self.spool_dir]
        if obs_scope:
            cmd += ['--obs-scope', obs_scope]
        return cmd


class _Child:
    """Supervisor-side record of one replica process."""

    __slots__ = ('name', 'proc', 'socket_path', 'uid', 'replica', 'state',
                 'attempts', 'crash_times', 'not_before', 'ready_since',
                 'last_hb_ok', 'hb_due', 'exit_reason')

    def __init__(self, name: str, socket_path: str, uid: str):
        self.name = name
        self.socket_path = socket_path
        self.uid = uid
        self.proc = None
        self.replica = None
        self.state = SPAWNING
        self.attempts = 0            # consecutive restarts
        self.crash_times: List[float] = []   # window for the breaker
        self.not_before = 0.0        # backoff gate for the next respawn
        self.ready_since = 0.0
        self.last_hb_ok = 0.0
        self.hb_due = 0.0
        self.exit_reason = None


class Supervisor:
    """Spawn/monitor/heal a fleet of replica_main processes."""

    def __init__(self, run_dir: str, spec: ReplicaSpec, *,
                 heartbeat_interval_s: float = 1.0,
                 heartbeat_timeout_s: float = 5.0,
                 spawn_timeout_s: float = 180.0,
                 backoff_base_s: float = 0.5,
                 backoff_mult: float = 2.0,
                 backoff_cap_s: float = 30.0,
                 backoff_jitter: float = 0.25,
                 max_restarts: int = 3,
                 restart_window_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 popen_fn=None, connect_fn=None,
                 on_restart: Optional[Callable] = None,
                 python: str = sys.executable):
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self.spec = spec
        self.heartbeat_interval_s = heartbeat_interval_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self.backoff_base_s = backoff_base_s
        self.backoff_mult = backoff_mult
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.clock = clock
        self.sleep = sleep
        self.popen_fn = popen_fn or self._default_popen
        self.connect_fn = connect_fn or self._default_connect
        self.on_restart = on_restart
        self.python = python
        self._lock = _concurrency.RLock('Supervisor._lock')
        self._children: Dict[str, _Child] = {}
        self._seq = 0
        # deterministic jitter: reproducible spacing for the fault tests
        self._rng = random.Random(0x5EED)
        self._m_replicas = _obs.get_registry().gauge(
            'paddle_supervisor_replicas',
            'supervised replica processes by state', ('state',))
        self.reap_orphans()

    # -- metrics helpers ---------------------------------------------------
    def _count(self, name: str, help_: str, **labels):
        if _obs.enabled():
            reg = _obs.get_registry()
            reg.counter(name, help_, tuple(labels)).labels(**labels).inc() \
                if labels else reg.counter(name, help_).inc()

    def _refresh_gauge(self):
        if not _obs.enabled():
            return
        counts: Dict[str, int] = {}
        for c in self._children.values():
            counts[c.state] = counts.get(c.state, 0) + 1
        for state in (SPAWNING, READY, BACKOFF, QUARANTINED, RETIRING,
                      STOPPED):
            self._m_replicas.labels(state=state).set(counts.get(state, 0))

    # -- default process plumbing -----------------------------------------
    def _default_popen(self, argv: List[str], env: Dict[str, str],
                       log_path: str):
        log = open(log_path, 'ab')
        try:
            return subprocess.Popen(argv, env=env, stdout=log, stderr=log,
                                    start_new_session=True)
        finally:
            log.close()   # the child holds its own fd now

    def _default_connect(self, child: _Child):
        """Poll-connect until the child binds its socket (readiness =
        warm and serviceable) or the spawn deadline passes."""
        from .remote import RemoteReplica
        deadline = self.clock() + self.spawn_timeout_s
        last: Optional[BaseException] = None
        while self.clock() < deadline:
            rc = child.proc.poll()
            if rc is not None:
                raise RuntimeError(
                    f'replica {child.name} exited rc={rc} during spawn '
                    f'(see {self._log_path(child.name)})')
            rr = RemoteReplica(child.socket_path, name=child.name,
                              supervisor=self)
            try:
                rr.connect(deadline_s=2.0)
                return rr
            except (ConnectionError, OSError, TimeoutError) as exc:
                last = exc
                rr.close()
                self.sleep(0.1)
        raise TimeoutError(
            f'replica {child.name} not connectable within '
            f'{self.spawn_timeout_s}s') from last

    # -- paths -------------------------------------------------------------
    def _socket_path(self, name: str) -> str:
        return os.path.join(self.run_dir, f'{name}.sock')

    def _pidfile_path(self, name: str) -> str:
        return os.path.join(self.run_dir, f'{name}.json')

    def _log_path(self, name: str) -> str:
        return os.path.join(self.run_dir, f'{name}.log')

    # -- orphan / stale-state sweep ---------------------------------------
    def reap_orphans(self) -> int:
        """Sweep pidfiles/sockets left by a previous supervisor
        incarnation. A pidfile's process is killed ONLY when /proc
        confirms it still is a replica_main (pids recycle; a recycled
        pid must never catch a stray SIGKILL). Returns processes
        killed."""
        killed = 0
        with self._lock:
            owned = {c.name for c in self._children.values()}
            for fname in sorted(os.listdir(self.run_dir)):
                base, ext = os.path.splitext(fname)
                if ext not in ('.json', '.sock') or base in owned:
                    continue
                path = os.path.join(self.run_dir, fname)
                if ext == '.json':
                    pid, uid = None, None
                    try:
                        with open(path) as f:
                            rec = json.load(f)
                        pid, uid = rec.get('pid'), rec.get('uid')
                    except (OSError, ValueError):
                        _obs.count_suppressed('supervisor_pidfile')
                    if pid is not None and self._is_replica_proc(pid):
                        try:
                            os.kill(int(pid), signal.SIGKILL)
                            killed += 1
                            _obs.emit('replica_orphan_reaped',
                                      pid=int(pid), pidfile=fname)
                            self._count(
                                'paddle_supervisor_orphans_reaped_total',
                                'orphaned replica processes SIGKILLed '
                                'at supervisor boot')
                        except OSError:
                            _obs.count_suppressed('supervisor_orphan_kill')
                    if uid and self.spec.spool_dir:
                        stale_spool = os.path.join(self.spec.spool_dir,
                                                   str(uid))
                        if os.path.isdir(stale_spool):
                            shutil.rmtree(stale_spool, ignore_errors=True)
                try:
                    os.unlink(path)
                    self._count(
                        'paddle_supervisor_stale_cleaned_total',
                        'stale pidfiles/sockets swept by the supervisor')
                except OSError:
                    _obs.count_suppressed('supervisor_stale_unlink')
        return killed

    @staticmethod
    def _is_replica_proc(pid) -> bool:
        try:
            with open(f'/proc/{int(pid)}/cmdline', 'rb') as f:
                return b'replica_main' in f.read()
        except (OSError, ValueError):
            return False

    # -- spawn / respawn ---------------------------------------------------
    def spawn(self, name: Optional[str] = None):
        """Start one replica process and block until it answers hello
        (warm-started and serviceable). Returns its RemoteReplica —
        exactly what `Router.add_replica` / the Autoscaler's
        replica_factory expect."""
        with self._lock:
            if name is None:
                name = f'r{self._seq}'
            self._seq += 1
            if name in self._children and \
                    self._children[name].state not in (STOPPED,):
                raise ValueError(f'replica {name!r} already supervised')
            self.reap_orphans()
            child = _Child(name, self._socket_path(name),
                           uid=f'{name}-{self._seq}')
            self._children[name] = child
        return self._start(child)

    def _start(self, child: _Child):
        now = self.clock()
        child.state = SPAWNING
        argv = self.spec.argv(self.python, child.socket_path, child.uid,
                              obs_scope=f'proc:{child.name}')
        env = dict(os.environ)
        env.update(self.spec.env)
        _obs.emit('replica_spawn', replica=child.name, attempt=child.attempts)
        self._count('paddle_supervisor_spawns_total',
                    'replica processes launched')
        child.proc = self.popen_fn(argv, env, self._log_path(child.name))
        with open(self._pidfile_path(child.name), 'w') as f:
            json.dump({'pid': child.proc.pid, 'name': child.name,
                       'socket': child.socket_path, 'uid': child.uid}, f)
        try:
            child.replica = self.connect_fn(child)
        except BaseException:
            # a child that never became ready counts as a crash: kill
            # whatever half-started, record it, re-raise to the caller
            self._kill_proc(child)
            self._cleanup_files(child)
            child.state = STOPPED
            self._refresh_gauge()
            raise
        child.state = READY
        child.ready_since = now
        child.last_hb_ok = self.clock()
        child.hb_due = child.last_hb_ok + self.heartbeat_interval_s
        _obs.emit('replica_ready', replica=child.name,
                  pid=child.proc.pid, attempt=child.attempts)
        self._refresh_gauge()
        return child.replica

    # -- teardown helpers --------------------------------------------------
    def _kill_proc(self, child: _Child):
        if child.proc is not None and child.proc.poll() is None:
            try:
                child.proc.kill()
                child.proc.wait()
            except OSError:
                _obs.count_suppressed('supervisor_kill')

    def _cleanup_files(self, child: _Child):
        for path in (self._pidfile_path(child.name), child.socket_path):
            try:
                if os.path.exists(path):
                    os.unlink(path)
            except OSError:
                _obs.count_suppressed('supervisor_cleanup')
        if child.replica is not None:
            child.replica.close()

    # -- the state machine -------------------------------------------------
    def poll(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One monitoring pass: reap exits, heartbeat the living,
        respawn the backed-off, quarantine the flapping. Drive this from
        any loop (the fleet tests call it directly with a fake clock)."""
        if now is None:
            now = self.clock()
        with self._lock:
            for child in list(self._children.values()):
                if child.state == READY:
                    self._poll_ready(child, now)
                elif child.state == BACKOFF:
                    self._poll_backoff(child, now)
            self._refresh_gauge()
            return self.stats()

    def _poll_ready(self, child: _Child, now: float):
        rc = child.proc.poll()
        if rc is not None:
            reason = 'clean_exit' if rc == 0 else 'crash'
            _obs.emit('replica_exit', replica=child.name, rc=rc,
                      reason=reason)
            self._on_death(child, now, reason=reason, rc=rc)
            return
        # a stretch of sustained health forgives past crashes: the
        # consecutive-attempt counter (backoff exponent) resets
        if child.attempts and \
                now - child.ready_since > self.restart_window_s:
            child.attempts = 0
        if now >= child.hb_due:
            child.hb_due = now + self.heartbeat_interval_s
            try:
                child.replica.healthz(
                    deadline_s=self.heartbeat_timeout_s)
                child.last_hb_ok = now
            except (ConnectionError, OSError, TimeoutError):
                self._count('paddle_supervisor_heartbeat_misses_total',
                            'replica heartbeat probes that failed')
                if now - child.last_hb_ok >= self.heartbeat_timeout_s:
                    # live pid, dead socket: wedged. Escalate to SIGKILL
                    # and restart — hang is the third exit class.
                    _obs.emit('replica_hang', replica=child.name,
                              pid=child.proc.pid,
                              silent_s=round(now - child.last_hb_ok, 3))
                    self._kill_proc(child)
                    self._on_death(child, now, reason='hang',
                                   rc=child.proc.poll())

    def _on_death(self, child: _Child, now: float, *, reason: str, rc):
        self._cleanup_files(child)
        child.replica = None
        if child.state == RETIRING:
            child.state = STOPPED
            _obs.emit('replica_retired', replica=child.name, rc=rc)
            return
        if reason != 'clean_exit':
            _obs.emit('replica_crash', replica=child.name, rc=rc,
                      reason=reason)
        child.attempts += 1
        child.crash_times.append(now)
        child.crash_times = [t for t in child.crash_times
                             if now - t <= self.restart_window_s]
        if len(child.crash_times) > self.max_restarts:
            child.state = QUARANTINED
            child.exit_reason = reason
            _obs.emit('replica_quarantined', replica=child.name,
                      crashes_in_window=len(child.crash_times),
                      window_s=self.restart_window_s, reason=reason)
            self._count('paddle_supervisor_quarantined_total',
                        'replicas circuit-broken out of the respawn loop')
            return
        backoff = self._backoff_s(child.attempts)
        child.state = BACKOFF
        child.not_before = now + backoff
        _obs.emit('replica_restart', replica=child.name,
                  attempt=child.attempts, backoff_s=round(backoff, 3),
                  reason=reason)
        self._count('paddle_supervisor_restarts_total',
                    'replica respawns scheduled', reason=reason)

    def _poll_backoff(self, child: _Child, now: float):
        if now < child.not_before:
            return
        try:
            replica = self._start(child)
        except BaseException:
            # a failed respawn is one more crash against the window
            _obs.count_suppressed('supervisor_respawn')
            self._on_death(child, self.clock(), reason='crash', rc=None)
            return
        if self.on_restart is not None:
            self.on_restart(child.name, replica)

    def _backoff_s(self, attempt: int) -> float:
        base = self.backoff_base_s * (
            self.backoff_mult ** max(0, attempt - 1))
        base = min(base, self.backoff_cap_s)
        return base * (1.0 + self._rng.uniform(-self.backoff_jitter,
                                               self.backoff_jitter))

    # -- explicit control --------------------------------------------------
    def retire(self, name: str, deadline_s: float = 30.0):
        """Graceful teardown: SIGTERM (the child drains under its own
        deadline and exits 0), bounded wait, SIGKILL past the bound.
        The scale-down path: Autoscaler -> remove_replica ->
        RemoteReplica.retire -> here."""
        with self._lock:
            child = self._children.get(name)
            if child is None or child.proc is None:
                return
            child.state = RETIRING
            try:
                child.proc.send_signal(signal.SIGTERM)
            except OSError:
                _obs.count_suppressed('supervisor_sigterm')
        try:
            child.proc.wait(timeout=deadline_s)
        except Exception:
            # drain deadline blown (or a fake proc without timeouts):
            # escalate to SIGKILL — retire must always converge
            _obs.count_suppressed('supervisor_retire_wait')
            self._kill_proc(child)
        with self._lock:
            self._on_death(child, self.clock(), reason='retired',
                           rc=child.proc.poll())
            self._refresh_gauge()

    def kill(self, name: str):
        """SIGKILL a child (chaos injection / hang escalation). The next
        poll() classifies the death and schedules the respawn."""
        with self._lock:
            child = self._children.get(name)
        if child is not None and child.proc is not None:
            try:
                child.proc.send_signal(signal.SIGKILL)
            except OSError:
                _obs.count_suppressed('supervisor_sigkill')

    def stop_all(self, deadline_s: float = 10.0):
        for name, child in list(self._children.items()):
            if child.state in (READY, SPAWNING, BACKOFF):
                self.retire(name, deadline_s=deadline_s)
        self._refresh_gauge()

    # -- integration -------------------------------------------------------
    def replica_factory(self) -> Callable[[], Any]:
        """Zero-arg factory for `Autoscaler(replica_factory=...)`: each
        call provisions a fresh supervised PROCESS and returns its
        RemoteReplica (already warm: spawn blocks on readiness)."""
        return lambda: self.spawn()

    def replicas(self) -> Dict[str, Any]:
        with self._lock:
            return {name: c.replica for name, c in self._children.items()
                    if c.state == READY}

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, c in self._children.items():
            out[name] = {
                'state': c.state,
                'pid': c.proc.pid if c.proc is not None else None,
                'attempts': c.attempts,
                'crashes_in_window': len(c.crash_times),
                'uid': c.uid,
            }
        return out
