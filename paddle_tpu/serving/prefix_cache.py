"""Radix prefix cache: shared prompt prefixes prefill ONCE.

Production traffic shares prefixes — a system prompt in front of every
request, few-shot templates, multi-turn histories. RadixAttention
(SGLang, Zheng et al. 2023) showed that keeping prefill KV keyed by the
token-id prefix tree and reusing the longest cached prefix is the
single biggest serving win at such traffic shapes. This is that idea
over the slot pool's STATIC shapes: instead of paged blocks, a cached
prefix retains a whole pool slot (its KV rows [0, kv_len) are the
prefix KV; rows above are stale and never attended — the same
overwrite-before-attend argument the engine's decode already relies
on), and a hit copies the retained row into the new request's slot with
one jitted row copy, so only the prompt SUFFIX is prefilled.

Structure: a compressed radix trie over token ids. Only nodes created
by an insertion own a slot; edge splits create structural nodes. A
lookup walks the prompt and returns the deepest slot-owning node whose
full root path is a prompt prefix.

Lifecycle:
- `insert(tokens, slot)` at request retirement ADOPTS the slot (the
  prompt KV is already in it — retention costs zero extra compute). The
  caller keeps the slot when the prefix is already covered or no budget
  can be freed (insert returns False).
- `acquire`/`release` pin a node for the lifetime of a request admitted
  off it: pinned nodes are never evicted, so a hot shared prefix
  survives pool pressure (the ref-count guarantee the tests gauntlet).
- Eviction is LRU over ZERO-REF owning nodes, under `budget_slots` =
  `fraction * num_slots` (retention must never starve decode capacity:
  the engine reclaims LRU entries on demand when the pool runs dry).

Sampling-params independence is by construction: the key is the token
prefix alone — prefill KV does not depend on temperature/top-k/top-p,
so greedy and sampled requests share entries.

Weight versioning (ISSUE 12): prefill KV is a function of the WEIGHTS,
so a hot weight swap invalidates every retained prefix. Entries are
tagged with the `weight_version` that produced them; `set_version`
moves the cache forward WITHOUT flushing — stale entries simply stop
matching lookups and are evicted lazily (on the lookup path that walks
past them, and preferentially under eviction pressure), never
wholesale mid-traffic. A rollback to the previous version re-validates
its surviving entries for free.

Namespaces (ISSUE 19, multi-tenant adapters): prefill KV is ALSO a
function of the LoRA adapter it was computed under, so `lookup` and
`insert` take a hashable `namespace` key — the engine passes
`(adapter_id, adapter_version)` for adapter requests, None for base
requests. Each namespace is its own radix trie root: two tenants with
identical prompts but different adapters can never share a cached
prefix, while base requests keep deduping against each other. Budget,
LRU eviction, and pinning stay GLOBAL across namespaces (retention is
a pool-capacity question, not a per-tenant one).
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Tuple

from .. import observability as _obs

# live caches, for flight-recorder bundles (prefix_cache.json)
_live_caches: 'weakref.WeakSet' = weakref.WeakSet()


def snapshot_all() -> List[dict]:
    """State of every live prefix cache (flight-recorder hook)."""
    return [c.snapshot() for c in list(_live_caches)]


class _Node:
    """One radix-trie node. `edge` is the token run from the parent;
    `slot`/`kv_len`/`version` are set only on owning nodes
    (kv_len == depth; version is the weight version whose prefill KV
    the retained slot holds)."""

    __slots__ = ('edge', 'children', 'parent', 'slot', 'kv_len', 'refs',
                 'last_use', 'version')

    def __init__(self, edge: Tuple[int, ...], parent: Optional['_Node']):
        self.edge = edge
        self.children: Dict[int, '_Node'] = {}
        self.parent = parent
        self.slot: Optional[int] = None
        self.kv_len = 0
        self.refs = 0
        self.last_use = 0
        self.version = 0


def _common(a: Tuple[int, ...], b: List[int], off: int) -> int:
    """Length of the common prefix of `a` and `b[off:]`."""
    n = min(len(a), len(b) - off)
    i = 0
    while i < n and a[i] == b[off + i]:
        i += 1
    return i


class RadixPrefixCache:
    """Token-prefix -> retained KV slot, LRU under a pool-fraction
    budget, with per-node ref-count pinning.

    Args:
        pool: the `SlotPool` whose slots are retained (evictions free
            straight back into it).
        fraction: max share of the pool the cache may pin as retained
            prefixes (budget_slots = int(fraction * num_slots); at
            least one slot is always left to the pool).
        min_tokens: don't retain prompts shorter than this (a 2-token
            prefix is cheaper to recompute than a slot is worth).
    """

    def __init__(self, pool, fraction: float = 0.5, min_tokens: int = 1):
        if not 0.0 < fraction <= 1.0:
            raise ValueError('fraction must be in (0, 1]')
        self.pool = pool
        self.budget_slots = min(int(fraction * pool.num_slots),
                                pool.num_slots - 1)
        self.min_tokens = max(int(min_tokens), 1)
        self._root = _Node((), None)
        # namespace key -> that namespace's own trie root (the default
        # None namespace is self._root); owners/budget/LRU stay global
        self._ns_roots: Dict = {}
        self._owners: set = set()
        self._tick = 0
        # the weight version CURRENT entries belong to; owners tagged
        # with any other version are stale (never served, lazily freed)
        self.version = 0
        self._counts = {'hits': 0, 'misses': 0, 'inserts': 0,
                        'evictions': 0, 'tokens_reused': 0,
                        'stale_evictions': 0}
        self._init_metrics()
        _live_caches.add(self)

    def _init_metrics(self):
        reg = _obs.get_registry()
        self._m_hits = reg.counter(
            'paddle_serving_prefix_hits_total',
            'submissions served a cached prefix')
        self._m_misses = reg.counter(
            'paddle_serving_prefix_misses_total',
            'submissions with no usable cached prefix')
        self._m_reused = reg.counter(
            'paddle_serving_prefix_tokens_reused_total',
            'prompt tokens whose prefill was skipped via the cache')
        self._m_inserts = reg.counter(
            'paddle_serving_prefix_inserts_total',
            'prefixes retained at retirement')
        self._m_evictions = reg.counter(
            'paddle_serving_prefix_evictions_total',
            'retained prefixes evicted (LRU / pool pressure)')
        self._m_stale_evictions = reg.counter(
            'paddle_serving_prefix_stale_evictions_total',
            'old-weight-version prefixes lazily reclaimed after a swap')
        self._m_retained = reg.gauge(
            'paddle_serving_prefix_retained_slots',
            'pool slots currently pinned by cached prefixes')
        if _obs.enabled():
            self._m_retained.set(0)

    # -- bookkeeping --------------------------------------------------------
    @property
    def retained_count(self) -> int:
        return len(self._owners)

    @property
    def reclaimable_count(self) -> int:
        """Owning nodes evictable right now (zero refs)."""
        return sum(1 for n in self._owners if n.refs == 0)

    def _touch(self, node: _Node):
        self._tick += 1
        node.last_use = self._tick

    def _ns_root(self, namespace) -> _Node:
        """The trie root serving `namespace` (created on first use; an
        empty namespace root is a few-hundred-byte dict entry, so stale
        (adapter, version) namespaces cost nothing once their owners
        are evicted)."""
        if namespace is None:
            return self._root
        root = self._ns_roots.get(namespace)
        if root is None:
            root = self._ns_roots[namespace] = _Node((), None)
        return root

    # -- weight versioning --------------------------------------------------
    def set_version(self, version: int):
        """Move the cache to a new weight version (the engine calls this
        from `swap_weights`). NO flush happens here: entries tagged with
        other versions become stale — unmatchable by lookups — and are
        reclaimed lazily (lookup walks, eviction pressure), so a swap
        never stalls live traffic behind a wholesale invalidation. A
        later `set_version` back to a previous version (rollback)
        re-validates that version's surviving entries."""
        self.version = int(version)

    @property
    def stale_count(self) -> int:
        """Retained entries whose version is not current (pending lazy
        reclamation; they never serve lookups)."""
        return sum(1 for n in self._owners if n.version != self.version)

    # -- lookup -------------------------------------------------------------
    def _subtree_owner(self, node: _Node,
                       reclaim_stale: bool = False) -> Optional[_Node]:
        """Most-recently-used CURRENT-version slot-owning node at/under
        `node`. Any such node works: its retained KV rows cover its
        whole root path, so the first `matched` of them are exactly the
        querying prompt's prefix KV. With `reclaim_stale`, unpinned
        stale (old-weight-version) owners found on the walk are freed —
        the lazy swap-invalidation path: no wholesale flush, the trie
        sheds old-version KV as traffic actually touches its subtrees
        (a full-miss lookup sweeps nothing, so a later rollback still
        finds its survivors)."""
        best, stack, stale = None, [node], []
        while stack:
            n = stack.pop()
            if n.slot is not None:
                if n.version != self.version:
                    if reclaim_stale and n.refs == 0:
                        stale.append(n)
                elif best is None or n.last_use > best.last_use:
                    best = n
            stack.extend(n.children.values())
        for n in stale:
            self._evict_node(n, stale=True)
        return best

    def lookup(self, tokens,
               namespace=None) -> Tuple[Optional[_Node], int]:
        """Longest common prefix between `tokens` and ANY cached entry
        IN `namespace`: (node, matched_len), or (None, 0). The matched
        length is the common-prefix length — it may be shorter than the
        owning node's own kv_len (a cached "system prompt + suffix A"
        serves a "system prompt + suffix B" request for the shared
        prefix; the stale A-rows above are overwritten/masked). A hit
        refreshes the node's LRU position."""
        tokens = list(tokens)
        root = self._ns_root(namespace)
        node, depth = root, 0
        deepest, deepest_len = root, 0   # divergence point
        best_exact: Tuple[Optional[_Node], int] = (None, 0)
        while depth < len(tokens):
            child = node.children.get(tokens[depth])
            if child is None:
                break
            m = _common(child.edge, tokens, depth)
            if m < len(child.edge):
                if m > 0:          # diverges mid-edge: the child's
                    deepest, deepest_len = child, depth + m
                break              # subtree still shares depth+m tokens
            depth += m
            node = child
            deepest, deepest_len = node, depth
            if node.slot is not None and node.version == self.version:
                best_exact = (node, depth)
        hit = self._subtree_owner(deepest,
                                  reclaim_stale=deepest_len > 0)
        if hit is not None and deepest_len > best_exact[1]:
            best = (hit, deepest_len)
        else:
            best = best_exact
        if best[0] is not None and best[1] > 0:
            self._touch(best[0])
            self._counts['hits'] += 1
            self._counts['tokens_reused'] += best[1]
            if _obs.enabled():
                self._m_hits.inc()
                self._m_reused.inc(best[1])
            return best
        self._counts['misses'] += 1
        if _obs.enabled():
            self._m_misses.inc()
        return (None, 0)

    # -- pinning ------------------------------------------------------------
    def acquire(self, node: _Node):
        """Pin `node` for the lifetime of a request admitted off it
        (pinned nodes survive every eviction path)."""
        node.refs += 1

    def release(self, node: _Node):
        if node.refs <= 0:
            raise RuntimeError('prefix node released more than acquired')
        node.refs -= 1

    # -- resource hooks (row-slot vs paged-hold retention) ------------------
    def _release_entry(self, resource) -> None:
        """Return a retained resource to the pool. Base: the resource IS
        a slot index. PagedPrefixCache overrides with release_hold."""
        self.pool.free(resource)

    def _entry_repr(self, resource) -> int:
        """JSON-safe scalar for events/snapshots (`slot` fields). Base:
        the slot index itself; paged entries report -1 (they retain
        pages, not a slot)."""
        return int(resource)

    def _entry_pages(self, resource) -> int:
        """Pages pinned by a retained resource (0 in row mode — budget
        accounting there is per-slot)."""
        return 0

    def _needs_eviction(self, incoming) -> bool:
        """True while adopting `incoming` would leave retention over
        budget. Base budget: retained SLOTS."""
        return len(self._owners) >= self.budget_slots

    # -- insertion ----------------------------------------------------------
    def insert(self, tokens, slot: int, namespace=None) -> bool:
        """Retain `slot` (whose rows [0, len(tokens)) hold the prefill KV
        of `tokens`) as a cached prefix under `namespace`. Returns True
        when the cache ADOPTED the slot — the caller must NOT free it —
        and False when the caller keeps it (already covered / under
        min_tokens / budget exhausted by pinned entries)."""
        if self.budget_slots < 1:
            return False
        return self._insert_resource(tokens, int(slot), namespace)

    def _insert_resource(self, tokens, resource, namespace=None) -> bool:
        """The trie half of insert: walk/split to the prompt's node and
        adopt `resource` as its retained entry. Shared by row mode
        (resource = slot index) and paged mode (resource = PageHold)."""
        tokens = list(tokens)
        if len(tokens) < self.min_tokens:
            return False
        node, depth = self._ns_root(namespace), 0
        while depth < len(tokens):
            child = node.children.get(tokens[depth])
            if child is None:
                new = _Node(tuple(tokens[depth:]), node)
                node.children[tokens[depth]] = new
                node, depth = new, len(tokens)
                break
            m = _common(child.edge, tokens, depth)
            if m == len(child.edge):
                node, depth = child, depth + m
                continue
            # split the edge at m: structural midpoint node
            mid = _Node(child.edge[:m], node)
            mid.children[child.edge[m]] = child
            node.children[tokens[depth]] = mid
            child.edge = child.edge[m:]
            child.parent = mid
            node, depth = mid, depth + m
        if node.slot is not None and node.version != self.version:
            # this exact prefix is retained under an OLD weight version:
            # the fresh KV supersedes it (a pinned stale owner — a
            # pre-swap request still decoding off it — keeps its slot;
            # the caller keeps the new one)
            if node.refs > 0:
                return False
            self._evict_node(node, stale=True, prune=False)
        covering = self._subtree_owner(node)
        if covering is not None:
            # some retained CURRENT-version entry already extends (or
            # equals) this prompt, so its rows already serve this
            # prefix: refresh it rather than spending a second slot
            self._touch(covering)
            return False
        while self._needs_eviction(resource):
            if not self.evict_lru():
                return False        # everything is pinned
        node.slot = resource
        node.kv_len = len(tokens)
        node.version = self.version
        self._owners.add(node)
        self._touch(node)
        self._counts['inserts'] += 1
        if _obs.enabled():
            self._m_inserts.inc()
            self._m_retained.set(len(self._owners))
        return True

    # -- eviction -----------------------------------------------------------
    def _evict_node(self, victim: _Node, stale: bool = False,
                    prune: bool = True) -> None:
        """Free `victim`'s retained slot back into the pool and drop it
        from the owner set. `prune=False` keeps the (now structural)
        node in the trie — the insert path re-owns it in place."""
        slot, kv_len = self._entry_repr(victim.slot), victim.kv_len
        self._release_entry(victim.slot)
        victim.slot = None
        victim.kv_len = 0
        self._owners.discard(victim)
        if prune:
            # prune now-empty leaves upward (structural nodes with
            # children stay: they still route longer retained paths)
            n = victim
            while (n.parent is not None and n.slot is None
                   and not n.children):
                del n.parent.children[n.edge[0]]
                n = n.parent
        self._counts['evictions'] += 1
        if stale:
            self._counts['stale_evictions'] += 1
        if _obs.enabled():
            self._m_evictions.inc()
            if stale:
                self._m_stale_evictions.inc()
            self._m_retained.set(len(self._owners))
        _obs.emit('prefix_evict', slot=slot, kv_len=kv_len, stale=stale,
                  retained=len(self._owners))

    def evict_lru(self) -> bool:
        """Free one ZERO-REF retained prefix back into the pool: stale
        (old-weight-version) entries go first — they can never serve a
        lookup again — then least-recently-used current entries. False
        when every entry is pinned (or empty)."""
        cands = [n for n in self._owners if n.refs == 0]
        if not cands:
            return False
        victim = min(cands, key=lambda n: (n.version == self.version,
                                           n.last_use))
        self._evict_node(victim, stale=victim.version != self.version)
        return True

    def clear(self, force: bool = False):
        """Evict every unpinned entry (tests / manual reset).
        `force=True` drops PINNED entries too — the pool-recovery path
        after a donated decode program failed mid-call, where every
        retained row is already invalid. Pins are left intact: the
        failing requests release them during their own teardown, and a
        released node that was force-evicted is simply unowned."""
        if force:
            for n in list(self._owners):
                self._evict_node(n)
            return
        while self.evict_lru():
            pass

    # -- introspection ------------------------------------------------------
    def _node_count(self) -> int:
        roots = [self._root, *self._ns_roots.values()]
        n, stack = 0, list(roots)
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n - len(roots)       # roots are structural

    def stats(self) -> dict:
        return {
            'budget_slots': self.budget_slots,
            'retained_slots': len(self._owners),
            'pinned': sum(1 for n in self._owners if n.refs > 0),
            'nodes': self._node_count(),
            'namespaces': len(self._ns_roots),
            'weight_version': self.version,
            'stale_slots': self.stale_count,
            **self._counts,
        }

    def snapshot(self) -> dict:
        """Flight-recorder view: stats + the retained prefix inventory
        (lengths + pin state, NOT token contents — prompts are user
        data and postmortem bundles travel)."""
        return {
            **self.stats(),
            'entries': sorted(
                ({'kv_len': n.kv_len, 'slot': self._entry_repr(n.slot),
                  'refs': n.refs, 'pages': self._entry_pages(n.slot),
                  'last_use': n.last_use, 'version': n.version}
                 for n in self._owners),
                key=lambda e: -e['last_use']),
        }


class PagedPrefixCache(RadixPrefixCache):
    """Radix prefix cache over a `PagedSlotPool`: retention pins PAGES,
    not slots — the tentpole difference. At retirement the cache takes a
    `PageHold` over the prompt's full pages and the SLOT always goes
    back to the pool (insert never adopts it); on a hit the engine
    attaches the held page ids into the new request's page table
    read-only, so a shared system prompt costs its pages ONCE across
    every live request plus the cache — vs once per retained slot in row
    mode. Budget is counted in PAGES (`fraction * num_pages`); eviction
    stays LRU-over-zero-ref with stale-version preference, and releasing
    a hold returns its pages straight to the pool free list."""

    def __init__(self, pool, fraction: float = 0.5, min_tokens: int = 1):
        super().__init__(pool, fraction, min_tokens)
        # pages, not slots: leave at least one slot's worth for decode
        self.budget_pages = min(
            int(fraction * (pool.num_pages - 1)),
            pool.num_pages - 1 - pool.pages_per_slot)
        self._held_pages = 0

    # -- resource hooks ----------------------------------------------------
    def _release_entry(self, resource) -> None:
        self._held_pages -= len(resource.pages)
        self.pool.release_hold(resource)

    def _entry_repr(self, resource) -> int:
        return -1                      # pages retained, no slot

    def _entry_pages(self, resource) -> int:
        return len(resource.pages)

    def _needs_eviction(self, incoming) -> bool:
        return (self._held_pages + len(incoming.pages)
                > self.budget_pages)

    @property
    def held_pages(self) -> int:
        return self._held_pages

    @property
    def reclaimable_pages(self) -> int:
        """Pages in zero-ref holds (releasable on pool pressure)."""
        return sum(len(n.slot.pages) for n in self._owners
                   if n.refs == 0)

    def insert(self, tokens, slot: int, namespace=None) -> bool:
        """Pin the prompt's full pages as a PageHold and retain that.
        ALWAYS returns False: the slot itself is never adopted — the
        engine frees it, and the held pages survive the free at
        refs >= 1."""
        tokens = list(tokens)
        if len(tokens) < self.min_tokens or self.budget_pages < 1:
            return False
        hold = self.pool.hold_pages(slot, len(tokens))
        if hold is None:               # no full page covered
            return False
        adopted = self._insert_resource(tokens, hold, namespace)
        if adopted:
            self._held_pages += len(hold.pages)
        else:
            self.pool.release_hold(hold)
        return False

    def stats(self) -> dict:
        out = super().stats()
        out.update(budget_pages=self.budget_pages,
                   held_pages=self._held_pages,
                   reclaimable_pages=self.reclaimable_pages)
        return out
