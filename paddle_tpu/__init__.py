"""paddle_tpu: a TPU-native deep-learning framework with the reference's
(92lqllearning/Paddle) capability surface.

Compute path: jax/XLA (+ Pallas kernels); eager DyGraph autograd on a vjp
tape; jitted functional training steps for performance; distribution via
jax.sharding Mesh + XLA collectives over ICI.
"""
from __future__ import annotations

from . import autograd, dtype as _dtype_module, framework
from .autograd import enable_grad, no_grad, set_grad_enabled, grad
from .dtype import (bfloat16, bool_, complex64, complex128, finfo, float16,
                    float32, float64, iinfo, int8, int16, int32, int64, uint8)
from .framework import (CPUPlace, CUDAPlace, Generator, Place, TPUPlace,
                        XLAPlace, device_guard, get_default_dtype, get_device,
                        seed, set_default_dtype, set_device)
from .tensor import Parameter, Tensor, set_printoptions

# full op surface (also attaches Tensor methods/operators)
from .ops import *  # noqa: F401,F403
from .ops import linalg

from . import device
from . import jit
from . import nn
from . import optimizer
from . import distributed
from . import nlp
from . import vision
from . import amp
from . import utils
from . import io
from . import observability
from . import profiler
from . import debug
from . import resilience
from . import serving
from . import metric
from . import hapi
from .hapi import Model
from .hapi import callbacks_mod as callbacks
from .serialization import load, save
from .nn.layer import LazyGuard, ParamAttr
from .optimizer import L1Decay, L2Decay

from . import hub
from . import sysconfig
from . import regularizer
from . import audio
from . import geometric
from . import incubate
from . import onnx
from . import text
from . import static
from . import sparse
from . import quantization
from . import fft
from . import signal
from . import distribution
from . import version
from .utils.flops import flops, summary

bool = bool_  # paddle.bool

__version__ = '0.1.0'

disable_static = static.disable_static
enable_static = static.enable_static


# single source for the CUDA-compat shims: framework.py
from .framework import is_compiled_with_cuda  # noqa: E402


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str = '') -> bool:
    return False


def get_cudnn_version():
    return None  # no CUDA in this build


def in_dynamic_mode() -> bool:
    return not static.in_static_mode()


def is_grad_enabled():
    return autograd.is_grad_enabled()


def get_flags(flags=None):
    from . import flags as _flags
    return _flags.get_flags(flags)


def set_flags(flags):
    from . import flags as _flags
    return _flags.set_flags(flags)
