"""paddle.text (upstream: python/paddle/text/) — ViterbiDecoder plus the
dataset surface (offline build: synthetic deterministic stand-ins, same
pattern as vision.datasets).

TPU-native note: viterbi_decode is a `lax.scan` over the sequence — the
per-step [B, T, T] max-reduction vectorizes on the VPU, and the argmax
backtrace is a second scan, so the whole decode stays on device.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .io import Dataset
from .nn.layer import Layer
from .ops._helpers import defop

__all__ = ['viterbi_decode', 'ViterbiDecoder', 'Imdb', 'UCIHousing',
           'Conll05st']


def viterbi_decode(potentials, transition, lengths, include_bos_eos_tag=True,
                   name=None):
    """Hard Viterbi decode (upstream: paddle.text.viterbi_decode).

    potentials: [B, L, T] unary emissions; transition: [T, T] (row = from);
    lengths: [B] int. With include_bos_eos_tag=True the last two tag rows
    are treated as BOS/EOS like upstream. Returns (scores [B], paths
    [B, L] int64, right-padded with 0 past each length).
    """
    def f(pot, trans, lens):
        b, seq_len, n_tags = pot.shape
        if include_bos_eos_tag:
            bos, eos = n_tags - 2, n_tags - 1
            start = pot[:, 0] + trans[bos][None, :]
        else:
            start = pot[:, 0]

        def step(carry, xs):
            alpha, t_idx = carry
            emit = xs  # [B, T]
            # [B, Tfrom, Tto]
            scores = alpha[:, :, None] + trans[None, :, :] + emit[:, None, :]
            best = jnp.max(scores, axis=1)
            back = jnp.argmax(scores, axis=1)
            # positions past a sequence's length keep their alpha frozen
            active = (t_idx < lens)[:, None]
            new_alpha = jnp.where(active, best, alpha)
            back = jnp.where(active, back,
                             jnp.broadcast_to(jnp.arange(n_tags)[None, :],
                                              back.shape))
            return (new_alpha, t_idx + 1), back

        (alpha, _), backs = jax.lax.scan(step, (start, jnp.ones((), jnp.int32)),
                                         jnp.swapaxes(pot[:, 1:], 0, 1))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos][None, :]
        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1)

        def back_step(tag, back_t):
            prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # ys = [tag_{L-1}, ..., tag_1]; final carry = tag_0
        tag0, path_rev = jax.lax.scan(back_step, last_tag, backs[::-1])
        paths = jnp.concatenate(
            [tag0[:, None], path_rev[::-1].T], axis=1)  # [B, L]
        # mask past-length positions to 0 (upstream pads with 0)
        pos = jnp.arange(seq_len)[None, :]
        paths = jnp.where(pos < lens[:, None], paths, 0)
        return scores, paths.astype(jnp.int64)
    return defop(f, name='viterbi_decode')(potentials, transition, lengths)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# -- datasets (synthetic offline stand-ins) ---------------------------------

class Imdb(Dataset):
    """Binary sentiment surface: token-id sequences + 0/1 labels."""

    def __init__(self, data_file=None, mode='train', cutoff=150, seed=None):
        if data_file is not None:
            raise RuntimeError('offline build: archives unavailable; '
                               'the synthetic stand-in is used instead')
        rng = np.random.RandomState(
            (0 if mode == 'train' else 1) if seed is None else seed)
        n, vocab, length = (256 if mode == 'train' else 64), 5000, 64
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        # class-dependent token distribution so models can fit
        shift = self.labels[:, None] * (vocab // 2)
        self.docs = ((rng.randint(0, vocab // 2, (n, length)) + shift)
                     .astype(np.int64))
        self.word_idx = {f'tok{i}': i for i in range(vocab)}

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    """13-feature regression surface with a fixed linear ground truth."""

    def __init__(self, data_file=None, mode='train'):
        if data_file is not None:
            raise RuntimeError('offline build: archives unavailable; '
                               'the synthetic stand-in is used instead')
        rng = np.random.RandomState(0 if mode == 'train' else 1)
        n = 404 if mode == 'train' else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = np.linspace(-1, 1, 13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(np.float32)[:, None]

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class Conll05st(Dataset):
    """SRL-shaped surface: (tokens, predicate-mark, labels) triples."""

    N_TAGS = 9

    def __init__(self, data_file=None, mode='train'):
        if data_file is not None:
            raise RuntimeError('offline build: archives unavailable; '
                               'the synthetic stand-in is used instead')
        rng = np.random.RandomState(0 if mode == 'train' else 1)
        n, vocab, length = (128 if mode == 'train' else 32), 2000, 32
        self.tokens = rng.randint(0, vocab, (n, length)).astype(np.int64)
        self.marks = (rng.rand(n, length) < 0.1).astype(np.int64)
        self.labels = ((self.tokens + self.marks * 3) % self.N_TAGS) \
            .astype(np.int64)

    def __getitem__(self, i):
        return self.tokens[i], self.marks[i], self.labels[i]

    def __len__(self):
        return len(self.tokens)
