"""falsy-guard: `x or default` on framework types that are falsy when
empty.

The PR 10 bug class: `Span(_log=log)` and `to_chrome_trace(log)` used
`log or _default_log` — but `EventLog.__len__` makes an *empty* log
falsy, so spans recorded into a fresh log were silently rerouted to the
default one. The fix (and the contract this pass enforces) is
`x if x is not None else default` for every framework type that bears
`__len__` or may grow it: EventLog, MetricsRegistry, SlotPool,
ProgramCatalog, GoodputLedger, ReplicaSet.

Two triggers:

- the guarded name's type is inferred as one of the protected types
  (parameter annotation, or a visible `x = EventLog(...)` assignment);
- the `or`-default is a protected constructor/factory call
  (`registry or get_registry()`): whatever the left side is, the intent
  is "registry-typed", so truthiness is the wrong check.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

#: framework types where `or` on an instance is a latent empty-object bug
FALSY_TYPES = frozenset((
    'EventLog', 'MetricsRegistry', 'SlotPool', 'ProgramCatalog',
    'GoodputLedger', 'ReplicaSet',
))

#: factory -> type it returns (module-level singletons)
FACTORIES = {
    'get_event_log': 'EventLog',
    'get_registry': 'MetricsRegistry',
    'get_catalog': 'ProgramCatalog',
    'program_catalog': 'ProgramCatalog',
    'get_ledger': 'GoodputLedger',
}


def _annotation_type(ann: Optional[ast.AST]) -> Optional[str]:
    if ann is None:
        return None
    try:
        text = ast.unparse(ann)
    except (ValueError, TypeError, AttributeError):
        return None
    for t in FALSY_TYPES:
        if t in text:
            return t
    return None


def _producer_type(expr: ast.AST) -> Optional[str]:
    """Type of a constructor/factory call expression, if protected."""
    if not isinstance(expr, ast.Call):
        return None
    seg = _util.last_segment(_util.call_name(expr))
    if seg in FALSY_TYPES:
        return seg
    return FACTORIES.get(seg or '')


@register_pass
class FalsyGuardPass(AnalysisPass):
    name = 'falsy-guard'
    description = ('`x or default` where x is a __len__-bearing framework '
                   'type (EventLog/MetricsRegistry/SlotPool/...): an empty '
                   'instance is falsy and gets silently replaced; use '
                   '`is None`')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        types = self._infer_types(sf.tree)
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.BoolOp) or \
                    not isinstance(node.op, ast.Or):
                continue
            guarded = node.values[0]
            gname = _util.dotted_name(guarded)
            gtype = types.get(gname) if gname else None
            default_type = None
            for v in node.values[1:]:
                default_type = _producer_type(v)
                if default_type:
                    break
            t = gtype or default_type
            if t is None:
                continue
            label = gname or '<expr>'
            findings.append(self.finding(
                sf, node,
                f'`{label} or ...` guards a {t} — an EMPTY {t} is falsy '
                f'(`__len__`) and `or` silently replaces it (the PR 10 '
                f'EventLog rerouting bug); use '
                f'`{label} if {label} is not None else ...`'))
        return findings

    def _infer_types(self, tree: ast.AST) -> Dict[str, str]:
        """name / 'self.attr' -> protected type, from annotations and
        visible constructor/factory assignments."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                for p in a.posonlyargs + a.args + a.kwonlyargs:
                    t = _annotation_type(p.annotation)
                    if t:
                        out[p.arg] = t
            elif isinstance(node, ast.AnnAssign):
                t = _annotation_type(node.annotation)
                name = _util.dotted_name(node.target)
                if t and name:
                    out[name] = t
            elif isinstance(node, ast.Assign) and node.value is not None:
                t = _producer_type(node.value)
                if not t:
                    continue
                for tgt in node.targets:
                    name = _util.dotted_name(tgt)
                    if name:
                        out[name] = t
        return out
