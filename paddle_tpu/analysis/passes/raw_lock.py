"""raw-lock: every lock allocation must go through the sanitized
wrappers in `paddle_tpu.analysis.runtime.concurrency`.

The runtime concurrency sanitizer only sees locks allocated through its
`Lock`/`RLock`/`Condition` wrappers — a raw `threading.Lock()` is a
blind spot in the acquisition graph AND in every `guarded_by` lockset.
This pass flags raw allocations of the three wrapped primitives:

- `threading.Lock()` / `threading.RLock()` / `threading.Condition()`
  (any alias the module was imported under), and
- bare `Lock()` / `RLock()` / `Condition()` when the file does
  `from threading import ...` them.

`threading.Event` / `Semaphore` / `Barrier` are signaling primitives,
not mutual exclusion — the sanitizer has nothing to say about them, so
they stay raw. Deliberate exceptions (the sanitizer's own internals
wrap raw primitives) carry inline annotations::

    _state_lock = threading.Lock()  # paddle-lint: disable=raw-lock -- <why>
"""
from __future__ import annotations

import ast
from typing import List, Set

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

_PRIMITIVES = frozenset(('Lock', 'RLock', 'Condition'))


@register_pass
class RawLockPass(AnalysisPass):
    name = 'raw-lock'
    description = ('threading.Lock/RLock/Condition allocated raw instead '
                   'of through the sanitized analysis.runtime.concurrency '
                   'wrappers (annotated exceptions allowed)')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        threading_aliases: Set[str] = set()
        from_imports = {}   # local name -> real primitive name
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == 'threading':
                        threading_aliases.add(alias.asname or 'threading')
            elif isinstance(node, ast.ImportFrom):
                if node.module == 'threading':
                    for alias in node.names:
                        if alias.name in _PRIMITIVES:
                            from_imports[alias.asname or alias.name] = \
                                alias.name
        if not threading_aliases and not from_imports:
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _util.call_name(node)
            if name is None:
                continue
            hit = None
            if '.' in name:
                root, seg = name.split('.', 1)
                if root in threading_aliases and seg in _PRIMITIVES:
                    hit = seg
            elif name in from_imports:
                hit = from_imports[name]
            if hit is not None:
                findings.append(self.finding(
                    sf, node,
                    f'raw threading.{hit}() allocation — the runtime '
                    f'concurrency sanitizer cannot see this lock; '
                    f'allocate it via analysis.runtime.concurrency.'
                    f'{hit}("Class.attr") (or annotate why it must '
                    f'stay raw)'))
        return findings
