"""donation-path: raw `donate_argnums` outside the gauntlet-gated store.

PR 8 established that re-applying donation to store-served (exported →
deserialized) executables intermittently heap-corrupts on jaxlib
0.4.36; ISSUE 13's donation gauntlet therefore made the ProgramStore
the single donation owner: callers declare `donate_argnums` to
`wrap_jit`, the DIRECT compile path donates as declared (the safe
case), and the export path re-applies donation only on a probe-safe
verdict, sentinel-guarded, quarantinable.

A raw `donate_argnums=`/`donate_argnames=` keyword on `jax.jit` (or any
other call) bypasses all of that: the donation is baked into the jitted
object where the gauntlet can neither withhold it on a corrupting
runtime nor quarantine it after a sentinel trip. This pass flags every
such keyword outside the store's own modules. The two legitimate
direct-only sites that predate the store (the offload update kernels,
the fleet DistTrainStep) carry inline suppressions with their reasons —
new sites must route through `wrap_jit(..., donate_argnums=...)`.
"""
from __future__ import annotations

import ast
from typing import List

from ..core import AnalysisPass, Finding, SourceFile, register_pass
from . import _util

#: the donation owner itself: applying/recording donate_argnums here IS
#: the gated path
ALLOWED_FILES = frozenset((
    'paddle_tpu/programs/store.py',
    'paddle_tpu/programs/donation.py',
))

#: calls where the keyword is the DECLARATION to the gauntlet, not a
#: bypass of it
GATED_CALLS = frozenset(('wrap_jit',))

DONATE_KEYWORDS = ('donate_argnums', 'donate_argnames')


@register_pass
class DonationPathPass(AnalysisPass):
    name = 'donation-path'
    description = ('raw donate_argnums/donate_argnames outside the '
                   'gauntlet-gated ProgramStore path: donation baked '
                   'into a jit bypasses the probe verdict, the '
                   'corruption sentinels, and quarantine')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        if sf.rel in ALLOWED_FILES:
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            kw = next((k for k in node.keywords
                       if k.arg in DONATE_KEYWORDS), None)
            if kw is None:
                continue
            # gated spelling: the keyword on a wrap_jit(...) call is the
            # declaration to the store, however the receiver is spelled
            # (`store.wrap_jit`, `get_store().wrap_jit`, bare wrap_jit)
            if isinstance(node.func, ast.Attribute):
                seg = node.func.attr
            else:
                seg = _util.last_segment(_util.call_name(node))
            if seg in GATED_CALLS:
                continue
            findings.append(self.finding(
                sf, node,
                f'raw `{kw.arg}` on `{seg or "<call>"}` bypasses the '
                f'donation gauntlet — route it through '
                f'`ProgramStore.wrap_jit(..., donate_argnums=...)` so '
                f'the probe verdict, corruption sentinels, and '
                f'quarantine govern it (store-served donated '
                f'executables heap-corrupt on jaxlib 0.4.36)'))
        return findings
