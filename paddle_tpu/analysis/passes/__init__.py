"""Built-in passes. Importing this package registers them with
`core.REGISTRY`; a new pass is one module with a `@register_pass` class
(see README "Static analysis" for the recipe).
"""
from . import trace_hazard    # noqa: F401
from . import host_sync       # noqa: F401
from . import falsy_guard     # noqa: F401
from . import lock_order      # noqa: F401
from . import raw_lock        # noqa: F401
from . import swallowed_exception  # noqa: F401
from . import obs_schema      # noqa: F401
from . import donation_path   # noqa: F401
