"""trace-hazard: Python-level control flow / concretization on traced
values, and vjp rules that close over tracers.

The PR 1 bug class: `_fused_softmax_ce_xla`'s custom_vjp originally
closed over `labels`/`valid` from the enclosing scope instead of passing
them through residuals — fine under plain tracing, broken the moment the
fwd/bwd split runs in separate traces. Same family: `if x:` /
`while x:` / `bool(x)` / `int(x)` / `.item()` on a traced value raises
`TracerBoolConversionError` at best, silently bakes in a constant at
worst, and `np.asarray(tracer)` is a concretization error.

What counts as a traced function here:

- decorated ``@jax.jit`` / ``@partial(jax.jit, ...)`` (minus
  static_argnums/static_argnames), ``@to_static``,
  ``@jax.custom_vjp`` / ``@jax.custom_jvp`` (minus nondiff_argnums);
- decorated ``@defop`` — the repo's op convention: params without
  defaults are the array args, trailing defaulted params are statics;
- registered via ``f.defvjp(fwd, bwd)`` (both rules, all params);
- wrapped via ``jax.jit(fn)`` or ``store.wrap_jit(fn)`` /
  ``wrap_jit(self._method)`` — the ProgramStore path every production
  program compiles through (no statics: wrap_jit traces every arg).

Shape/dtype reads (`x.shape`, `x.ndim`, `x.dtype`), `len(x)`,
`isinstance(...)` and `is None` checks are static under tracing and
never flagged.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, Finding, SourceFile, enclosing_function, \
    register_pass
from . import _util

_JIT_NAMES = frozenset(('jax.jit', 'jit'))
_NP_ROOTS = frozenset(('np', 'numpy', 'onp'))
_CONCRETIZE_BUILTINS = frozenset(('bool', 'int', 'float', 'complex'))
_CONCRETIZE_METHODS = frozenset(('item', 'tolist'))


def _statics_from_call(call: Optional[ast.Call],
                       params: List[str]) -> Set[str]:
    """static_argnums / static_argnames / nondiff_argnums -> param names."""
    out: Set[str] = set()
    if call is None:
        return out
    for kw in call.keywords:
        if kw.arg in ('static_argnums', 'nondiff_argnums'):
            v = _util.const_value(kw.value)
            idxs = v if isinstance(v, (tuple, list)) else [v]
            for i in idxs:
                if isinstance(i, int) and 0 <= i < len(params):
                    out.add(params[i])
        elif kw.arg == 'static_argnames':
            v = _util.const_value(kw.value)
            names = v if isinstance(v, (tuple, list)) else [v]
            out.update(n for n in names if isinstance(n, str))
    return out


class _TracedFn:
    __slots__ = ('node', 'kind', 'traced', 'is_vjp_rule')

    def __init__(self, node, kind: str, traced: Set[str],
                 is_vjp_rule: bool = False):
        self.node = node
        self.kind = kind
        self.traced = traced
        self.is_vjp_rule = is_vjp_rule


@register_pass
class TraceHazardPass(AnalysisPass):
    name = 'trace-hazard'
    description = ('Python control flow / bool()/int()/.item() on traced '
                   'values, and custom_vjp rules closing over tracers, '
                   'inside @jit/@defop/wrap_jit/defvjp functions')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        traced = self._collect_traced(sf.tree)
        findings: List[Finding] = []
        seen: Set[Tuple[int, int, str]] = set()
        traced_nodes = {id(t.node) for t in traced}
        for t in traced:
            for f in self._check(sf, t, traced_nodes):
                sig = (f.line, f.col, f.message)
                if sig not in seen:
                    seen.add(sig)
                    findings.append(f)
        return findings

    # -- discovery ----------------------------------------------------------

    def _collect_traced(self, tree: ast.AST) -> List[_TracedFn]:
        by_name: Dict[str, List[ast.AST]] = {}
        fns = [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            by_name.setdefault(fn.name, []).append(fn)

        out: List[_TracedFn] = []
        marked: Set[int] = set()

        def mark(fn, kind, statics: Set[str], is_vjp_rule=False):
            if id(fn) in marked:
                return
            marked.add(id(fn))
            params = _util.param_names(fn)
            out.append(_TracedFn(fn, kind,
                                 set(params) - statics, is_vjp_rule))

        for fn in fns:
            decos = _util.decorator_names(fn)
            segs = {_util.last_segment(d) for d in decos}
            params = _util.param_names(fn)
            if any(d in _JIT_NAMES for d in decos):
                mark(fn, 'jit',
                     _statics_from_call(_util.decorator_call(fn, 'jit'),
                                        params))
            elif 'to_static' in segs:
                mark(fn, 'to_static', set())
            elif 'custom_vjp' in segs or 'custom_jvp' in segs:
                seg = 'custom_vjp' if 'custom_vjp' in segs else 'custom_jvp'
                mark(fn, seg,
                     _statics_from_call(_util.decorator_call(fn, seg),
                                        params))
            elif 'defop' in segs:
                # repo convention: defaulted trailing params are statics
                mark(fn, 'defop',
                     set(params) - set(_util.params_without_defaults(fn)))

        for call in (n for n in ast.walk(tree) if isinstance(n, ast.Call)):
            seg = _util.last_segment(_util.call_name(call))
            if seg == 'defvjp':
                for arg in call.args[:2]:
                    name = None
                    if isinstance(arg, ast.Name):
                        name = arg.id
                    for fn in by_name.get(name, ()):
                        mark(fn, 'defvjp', set(), is_vjp_rule=True)
            elif seg == 'wrap_jit' or _util.call_name(call) in _JIT_NAMES:
                if not call.args:
                    continue
                arg0 = call.args[0]
                target = None
                if isinstance(arg0, ast.Name):
                    target = arg0.id
                elif isinstance(arg0, ast.Attribute) and \
                        isinstance(arg0.value, ast.Name) and \
                        arg0.value.id == 'self':
                    target = arg0.attr
                if target is None:
                    continue
                params_of = by_name.get(target, ())
                statics_call = call if seg != 'wrap_jit' else None
                for fn in params_of:
                    mark(fn, 'wrap_jit' if seg == 'wrap_jit' else 'jit',
                         _statics_from_call(statics_call,
                                            _util.param_names(fn)))
        return out

    # -- checks -------------------------------------------------------------

    def _check(self, sf: SourceFile, t: _TracedFn,
               traced_nodes: Set[int]) -> List[Finding]:
        findings: List[Finding] = []
        traced = set(t.traced)

        # nested defs run in the same trace (scan/cond bodies): their
        # non-defaulted params are traced values too — but a nested def
        # that is itself a registered traced fn is checked separately.
        def walk(node, traced: Set[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    if id(child) in traced_nodes and child is not t.node:
                        continue
                    inner = traced | set(_util.params_without_defaults(child))
                    walk(child, inner)
                    continue
                self._check_node(sf, t, child, traced, findings)
                walk(child, traced)

        walk(t.node, traced)

        if t.is_vjp_rule:
            findings.extend(self._check_vjp_closure(sf, t))
        return findings

    def _check_node(self, sf: SourceFile, t: _TracedFn, node: ast.AST,
                    traced: Set[str], findings: List[Finding]):
        if isinstance(node, (ast.If, ast.While)):
            hot = self._truthiness_names(node.test) & traced
            if hot:
                kw = 'while' if isinstance(node, ast.While) else 'if'
                findings.append(self.finding(
                    sf, node,
                    f'`{kw}` on traced value(s) {sorted(hot)} inside '
                    f'{t.kind}-traced `{t.node.name}` — data-dependent '
                    f'Python control flow fails or bakes in a constant '
                    f'under tracing; use lax.cond/jnp.where or hoist to '
                    f'a static'))
        elif isinstance(node, ast.Call):
            seg = _util.last_segment(_util.call_name(node))
            full = _util.call_name(node) or ''
            root = full.split('.', 1)[0]
            is_concretize = (
                (seg in _CONCRETIZE_BUILTINS and full == seg) or
                (seg in ('asarray', 'array') and root in _NP_ROOTS) or
                full == 'jax.device_get')
            if is_concretize and node.args:
                hot = set()
                for a in node.args:
                    hot |= _util.value_names(a) & traced
                if hot:
                    findings.append(self.finding(
                        sf, node,
                        f'`{seg}()` concretizes traced value(s) '
                        f'{sorted(hot)} inside {t.kind}-traced '
                        f'`{t.node.name}` — host round-trip breaks under '
                        f'tracing; keep it a jnp array or make the arg '
                        f'static'))
            elif seg in _CONCRETIZE_METHODS and \
                    isinstance(node.func, ast.Attribute):
                hot = _util.value_names(node.func.value) & traced
                if hot:
                    findings.append(self.finding(
                        sf, node,
                        f'`.{seg}()` on traced value(s) {sorted(hot)} '
                        f'inside {t.kind}-traced `{t.node.name}` — '
                        f'device sync cannot run under tracing'))

    def _truthiness_names(self, test: ast.AST) -> Set[str]:
        """Names whose runtime truthiness/comparison the test depends on;
        `is`/`is not` comparisons and static-attr reads excluded."""
        if isinstance(test, ast.BoolOp):
            out: Set[str] = set()
            for v in test.values:
                out |= self._truthiness_names(v)
            return out
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._truthiness_names(test.operand)
        if isinstance(test, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
                return set()
            out = _util.value_names(test.left)
            for c in test.comparators:
                out |= _util.value_names(c)
            return out
        return _util.value_names(test)

    def _check_vjp_closure(self, sf: SourceFile,
                           t: _TracedFn) -> List[Finding]:
        """A defvjp-registered rule nested in another function must not
        read that function's (likely-tracer) arguments — the rule runs in
        its own trace; tracers must flow through residuals (PR 1)."""
        enclosing = enclosing_function(t.node)
        if enclosing is None:
            return []
        outer_traced: Set[str] = set()
        cur = enclosing
        while cur is not None:
            outer_traced |= set(_util.params_without_defaults(cur))
            cur = enclosing_function(cur)
        bound = set(_util.param_names(t.node, skip_self=False))
        for n in ast.walk(t.node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                bound.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not t.node:
                bound.add(n.name)
                bound.update(_util.param_names(n, skip_self=False))
        free_hot = set()
        for n in ast.walk(t.node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in outer_traced and n.id not in bound:
                free_hot.add(n.id)
        if not free_hot:
            return []
        return [self.finding(
            sf, t.node,
            f'custom_vjp rule `{t.node.name}` closes over '
            f'{sorted(free_hot)} from the enclosing scope — a tracer '
            f'captured at registration time breaks the fwd/bwd split; '
            f'pass it through residuals (the PR 1 bug class)')]
