"""host-sync: implicit device->host transfers on serving/training hot
paths must be explicit.

A `.item()`, `np.asarray(...)`, `float(arr[i])` or `block_until_ready`
in the decode loop stalls the dispatch pipeline: under async dispatch a
step call returns in ~2ms while the device works 120ms (measured in
PR 10), so one stray sync per round can halve tokens/sec and never
shows up in a profile as anything but "python".

This pass does NOT ban syncs — emitting a token IS a d2h read. It bans
*unannotated* syncs inside the configured hot scopes: every site must
carry `# paddle-lint: disable=host-sync -- <why this sync is required>`
so the set of pipeline stalls on the hot path is reviewable in one grep.

Hot scopes (path -> enclosing-qualname prefixes): the serving engine
step/decode/prefill/admission loop and jit.TrainStep.__call__.
"""
from __future__ import annotations

import ast
from typing import List, Set

from ..core import AnalysisPass, Finding, SourceFile, enclosing_scope, \
    register_pass
from . import _util

#: path suffix -> qualname prefixes that form the hot set
HOT_SCOPES = {
    'paddle_tpu/serving/engine.py': (
        'InferenceEngine.step', 'InferenceEngine.run',
        'InferenceEngine._decode_round', 'InferenceEngine._spec_round',
        'InferenceEngine._admit', 'InferenceEngine._begin_request',
        'InferenceEngine._whole_prefill', 'InferenceEngine._advance_prefills',
        'InferenceEngine._prefill_chunk', 'InferenceEngine._activate',
        'InferenceEngine._draft_prefill', 'InferenceEngine._retire',
    ),
    'paddle_tpu/jit/__init__.py': ('TrainStep.__call__',),
    # the hot-swap path runs INTERLEAVED with live decode rounds (the
    # drain keeps the fleet serving), so a stray sync here stalls the
    # same pipeline the engine scopes protect; the publisher's snapshot
    # is the one sanctioned bulk d2h and must say so
    'paddle_tpu/serving/hotswap.py': (
        'WeightStore.publish', 'WeightPublisher.', 'ReplicaUpdater.',
        'CanaryGate.__call__', 'finite_weights_gate', '_host_tree',
    ),
    'paddle_tpu/loop/rollout.py': (
        'RolloutLoop.', 'RolloutBatch.', 'Rollout.',
    ),
    # the autoscaler's poll loop and the loadgen replayer both run
    # INTERLEAVED with decode rounds (one poll/submit pass per router
    # step) — a stray sync in either stalls the same pipeline the
    # engine scopes protect
    'paddle_tpu/serving/autoscaler.py': ('Autoscaler.',),
    'paddle_tpu/loadgen/replay.py': ('LoadReplayer.',),
    # the page manager (ISSUE 16) runs INSIDE the admission/decode loop:
    # reserve/attach/COW on every seating, note_written every round. Its
    # bookkeeping is host-side numpy BY DESIGN — any device read that
    # creeps in (e.g. materializing a page to inspect it) stalls every
    # decode round, so the whole class is a hot scope
    'paddle_tpu/serving/kv_pool.py': ('PagedSlotPool.',),
    # the cross-process RPC client (ISSUE 18) runs INSIDE the router
    # step loop: every placement reads the mirror scheduler and every
    # step applies mirror updates. The mirrors are plain-python BY
    # DESIGN (tokens are ints off the wire) — a device read creeping in
    # here (e.g. materializing arrays while building a frame) stalls
    # the routing of every replica, remote or not
    'paddle_tpu/serving/remote.py': ('RemoteReplica.', '_MirrorScheduler.',
                                     'RpcClient.'),
    # the supervisor's monitoring pass interleaves with router steps in
    # the serving loop; its state machine is pidfiles + clocks only —
    # any device sync in poll/heartbeat stalls serving fleet-wide
    'paddle_tpu/serving/supervisor.py': ('Supervisor.poll',
                                         'Supervisor._poll',
                                         'Supervisor._on_death',
                                         'Supervisor._backoff_s'),
    # the adapter bank (ISSUE 19) is consulted INSIDE the admission/
    # decode loop: pin/unpin on every request boundary, device_arrays()
    # per jit call. Its slot table is host-side python BY DESIGN — the
    # one sanctioned device op is the `.at[slot].set` hot-load in
    # _write_slot (a device-side scatter, not a sync); anything reading
    # factors back (np.asarray on a bank, .item on a scale) stalls
    # every decode round, so the whole class is a hot scope. The
    # trace-time apply hook runs inside the COMPILED program where a
    # sync is a tracer error, but np.asarray there would silently
    # constant-fold a weight into the executable — equally banned
    'paddle_tpu/serving/adapters/bank.py': ('AdapterBank.',),
    'paddle_tpu/serving/adapters/apply.py': ('linear_hook',
                                             'adapter_scope.'),
    # the request ledger (ISSUE 20) is written from INSIDE the engine
    # step / router failover loops: queue transitions at every
    # scheduler pass, per-round fair-share attribution after every
    # decode round, finalize on every retire. Its books are host-side
    # floats BY DESIGN — any device read creeping into add()/
    # note_round()/finalize_record() stalls every decode round of
    # every request, which is exactly the tail it exists to explain
    'paddle_tpu/observability/reqledger.py': ('RequestRecord.',
                                              'RequestLedger.'),
}

_NP_ROOTS = frozenset(('np', 'numpy', 'onp'))
_SYNC_METHODS = frozenset(('item', 'tolist', 'block_until_ready'))


@register_pass
class HostSyncPass(AnalysisPass):
    name = 'host-sync'
    description = ('implicit device->host transfers (np.asarray, .item, '
                   '.tolist, block_until_ready, int()/float() on array '
                   'reads) on serving/train-step hot paths without an '
                   'explicit justification annotation')

    def visit_file(self, sf: SourceFile) -> List[Finding]:
        prefixes = None
        for suffix, pref in HOT_SCOPES.items():
            if sf.rel.endswith(suffix):
                prefixes = pref
                break
        if prefixes is None:
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            scope = enclosing_scope(node)
            if not scope.startswith(tuple(prefixes)):
                continue
            msg = self._sync_kind(node)
            if msg:
                findings.append(self.finding(
                    sf, node,
                    f'{msg} in hot scope `{scope}` — a device sync here '
                    f'stalls the dispatch pipeline; hoist it off the hot '
                    f'path or annotate the site with '
                    f'`# paddle-lint: disable=host-sync -- <why>`'))
        return findings

    def _sync_kind(self, node: ast.Call) -> str:
        full = _util.call_name(node) or ''
        seg = _util.last_segment(full)
        root = full.split('.', 1)[0]
        if seg in ('asarray', 'array') and root in _NP_ROOTS:
            return f'`{full}()` forces a device->host copy'
        if full == 'jax.device_get':
            return '`jax.device_get()` forces a device->host copy'
        if seg in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
            return f'`.{seg}()` blocks on the device'
        if full in ('int', 'float', 'bool') and node.args and \
                self._reads_array(node.args[0]):
            return (f'`{full}(...)` on an array element forces a '
                    f'device->host read')
        return ''

    @staticmethod
    def _reads_array(expr: ast.AST) -> bool:
        """int(x[i]) / float(row[j]) style: a subscript read is the usual
        shape of pulling one element off the device."""
        return any(isinstance(n, ast.Subscript) for n in ast.walk(expr))
