"""Shared AST helpers for the built-in passes."""
from __future__ import annotations

import ast
from typing import List, Optional, Set

#: attribute accesses that are compile-time constants under jax tracing —
#: `x.shape[0] == 2` is a static check, not a trace hazard
STATIC_ATTRS = frozenset(('shape', 'ndim', 'dtype', 'size', 'sharding',
                          'aval', 'weak_type'))


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); 'jit' for Name('jit');
    None for anything not a plain dotted path."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def last_segment(name: Optional[str]) -> Optional[str]:
    return name.rsplit('.', 1)[-1] if name else None


def const_value(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return None


def param_names(fn: ast.AST, skip_self: bool = True) -> List[str]:
    """Positional + kwonly parameter names (no *args/**kwargs)."""
    a = fn.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if skip_self and names and names[0] in ('self', 'cls'):
        names = names[1:]
    return names


def params_without_defaults(fn: ast.AST, skip_self: bool = True) -> List[str]:
    """Positional params that have no default — for op-style signatures
    (`def mean(x, axis=None, keepdim=False)`) these are the array args;
    defaulted trailing params are Python-level statics."""
    a = fn.args
    pos = a.posonlyargs + a.args
    n_defaults = len(a.defaults)
    no_default = pos[:len(pos) - n_defaults] if n_defaults else pos
    names = [p.arg for p in no_default]
    if skip_self and names and names[0] in ('self', 'cls'):
        names = names[1:]
    return names


def value_names(expr: ast.AST) -> Set[str]:
    """Root names used *as values* in `expr`, excluding names that only
    appear under a static attribute (`x.shape`, `x.ndim`, ...), inside
    `len(...)`, or as `isinstance`/`hasattr`/`callable` subjects."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if not isinstance(node, ast.Name) or not isinstance(node.ctx, ast.Load):
            continue
        if _in_static_context(node, stop=expr):
            continue
        out.add(node.id)
    return out


def _in_static_context(name: ast.Name, stop: ast.AST) -> bool:
    cur: ast.AST = name
    parent = getattr(cur, 'parent', None)
    while parent is not None:
        if isinstance(parent, ast.Attribute) and parent.value is cur \
                and parent.attr in STATIC_ATTRS:
            return True
        if isinstance(parent, ast.Call):
            fname = last_segment(call_name(parent))
            if fname in ('len', 'isinstance', 'hasattr', 'callable',
                         'getattr', 'type', 'id', 'repr') \
                    and cur in parent.args:
                return True
        if parent is stop:
            return False
        cur, parent = parent, getattr(parent, 'parent', None)
    return False


def assigned_attr_names(node: ast.AST) -> List[str]:
    """For Assign/AugAssign/AnnAssign: the `self.X` attribute names being
    written (empty for non-self targets)."""
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    out = []
    for t in targets:
        for el in _flatten_target(t):
            if isinstance(el, ast.Attribute) and \
                    isinstance(el.value, ast.Name) and el.value.id == 'self':
                out.append(el.attr)
    return out


def _flatten_target(t: ast.AST):
    if isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            yield from _flatten_target(el)
    else:
        yield t


def decorator_names(fn: ast.AST) -> List[str]:
    """Dotted names of decorators; for `partial(f, ...)` the inner f."""
    out = []
    for d in fn.decorator_list:
        if isinstance(d, ast.Call):
            name = call_name(d)
            if last_segment(name) == 'partial' and d.args:
                inner = dotted_name(d.args[0])
                if inner:
                    out.append(inner)
                    continue
            if name:
                out.append(name)
        else:
            name = dotted_name(d)
            if name:
                out.append(name)
    return out


def decorator_call(fn: ast.AST, segment: str) -> Optional[ast.Call]:
    """The decorator Call whose (possibly partial-wrapped) target's last
    segment matches, e.g. decorator_call(fn, 'jit') finds both
    `@jax.jit` -> None (not a Call) and `@partial(jax.jit, ...)`."""
    for d in fn.decorator_list:
        if not isinstance(d, ast.Call):
            continue
        name = call_name(d)
        if last_segment(name) == segment:
            return d
        if last_segment(name) == 'partial' and d.args:
            if last_segment(dotted_name(d.args[0])) == segment:
                return d
    return None
